"""paddle.sparse (reference: ``python/paddle/sparse/`` — COO/CSR tensors
over ``paddle/phi/kernels/sparse/``; SURVEY.md §2.2).

TPU-native: backed by ``jax.experimental.sparse`` BCOO/BCSR — XLA lowers the
sparse contractions to gather/scatter + dense tiles (TPUs have no native
sparse MXU path, same as the reference's cuSPARSE fallback tier). Dense
operands stay differentiable through the tape; sparse values are
differentiable through ``values()``-preserving elementwise ops.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..framework.core import Tensor
from ..framework import dtype as dtypes
from ..autograd.tape import apply

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
    "SparseCsrTensor", "add", "multiply", "matmul", "masked_matmul", "relu",
    "is_sparse", "nn",
]


class SparseCooTensor:
    """COO sparse tensor (wraps BCOO). ``indices`` [ndim, nnz], ``values``
    [nnz] — reference layout."""

    def __init__(self, bcoo):
        self._m = bcoo

    # -- construction -------------------------------------------------------
    @property
    def shape(self):
        return list(self._m.shape)

    @property
    def dtype(self):
        return self._m.dtype

    @property
    def nnz(self):
        return int(self._m.nse)

    def indices(self):
        return Tensor(jnp.swapaxes(self._m.indices, 0, 1))

    def values(self):
        return Tensor(self._m.data)

    def to_dense(self):
        return Tensor(self._m.todense())

    def to_sparse_csr(self):
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(self._m))

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def coalesce(self):
        return SparseCooTensor(self._m.sum_duplicates())

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={dtypes.dtype_name(self.dtype)})")


class SparseCsrTensor:
    def __init__(self, bcsr):
        self._m = bcsr

    @property
    def shape(self):
        return list(self._m.shape)

    @property
    def dtype(self):
        return self._m.dtype

    @property
    def nnz(self):
        return int(self._m.nse)

    def crows(self):
        return Tensor(self._m.indptr)

    def cols(self):
        return Tensor(self._m.indices)

    def values(self):
        return Tensor(self._m.data)

    def to_dense(self):
        return Tensor(self._m.todense())

    def to_sparse_coo(self, sparse_dim=None):
        return SparseCooTensor(self._m.to_bcoo())

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={dtypes.dtype_name(self.dtype)})")


def _as_array(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x)


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    idx = np.asarray(indices if not isinstance(indices, Tensor)
                     else indices.numpy())
    vals = _as_array(values)
    if dtype is not None:
        vals = vals.astype(dtypes.convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(i.max()) + 1 for i in idx)
    bcoo = jsparse.BCOO((vals, jnp.asarray(idx.T)), shape=tuple(shape))
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, **kw):
    vals = _as_array(values)
    if dtype is not None:
        vals = vals.astype(dtypes.convert_dtype(dtype))
    bcsr = jsparse.BCSR((vals, _as_array(cols).astype(jnp.int32),
                         _as_array(crows).astype(jnp.int32)),
                        shape=tuple(shape))
    return SparseCsrTensor(bcsr)


def is_sparse(x):
    return isinstance(x, (SparseCooTensor, SparseCsrTensor))


def _coo(x):
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    return x


# -- ops --------------------------------------------------------------------

def add(x, y):
    if is_sparse(x) and is_sparse(y):
        xm, ym = _coo(x)._m, _coo(y)._m
        # sum via dense-free concat of coordinates
        data = jnp.concatenate([xm.data, ym.data])
        idx = jnp.concatenate([xm.indices, ym.indices], axis=0)
        m = jsparse.BCOO((data, idx), shape=xm.shape).sum_duplicates(
            nse=xm.nse + ym.nse)
        return SparseCooTensor(m)
    if is_sparse(x):
        return Tensor(x.to_dense()._data + _as_array(y))
    return Tensor(_as_array(x) + y.to_dense()._data)


def multiply(x, y):
    if is_sparse(x) and not is_sparse(y):
        xm = _coo(x)._m
        dense_vals = xm.todense() * _as_array(y)
        m = jsparse.bcoo_fromdense(dense_vals, nse=xm.nse)
        return SparseCooTensor(m)
    if is_sparse(x) and is_sparse(y):
        return SparseCooTensor(jsparse.bcoo_multiply_sparse(
            _coo(x)._m, _coo(y)._m))
    return multiply(y, x)


def matmul(x, y):
    """sparse @ dense → dense (differentiable w.r.t. the dense operand)."""
    if is_sparse(x):
        xm = _coo(x)._m

        def fn(d):
            return xm @ d

        return apply(fn, y if isinstance(y, Tensor) else Tensor(y),
                     op_name="sparse_matmul")
    if is_sparse(y):
        ym = _coo(y)._m

        def fn(d):
            return jsparse.bcoo_dot_general(
                ym, d, dimension_numbers=(((0,), (d.ndim - 2,)), ((), ())))

        # x @ sparse == (sparse^T @ x^T)^T for 2-D; keep simple via dense
        return apply(lambda d: d @ ym.todense(),
                     x if isinstance(x, Tensor) else Tensor(x),
                     op_name="sparse_matmul")
    from ..ops import math as pmath
    return pmath.matmul(x, y)


def masked_matmul(x, y, mask):
    """(x @ y) sampled at mask's sparsity pattern (reference sddmm)."""
    xm = _as_array(x)
    ym = _as_array(y)
    mm = _coo(mask)._m
    rows = mm.indices[:, 0]
    cols = mm.indices[:, 1]
    vals = jnp.einsum("nd,nd->n", xm[rows], ym[:, cols].T)
    return SparseCooTensor(jsparse.BCOO((vals, mm.indices), shape=mm.shape))


def relu(x):
    m = _coo(x)._m
    return SparseCooTensor(jsparse.BCOO((jnp.maximum(m.data, 0), m.indices),
                                        shape=m.shape))


class nn:
    """paddle.sparse.nn — sparse activations (subset)."""

    class ReLU:
        def __call__(self, x):
            return relu(x)
