"""Per-request distributed tracing + SLO monitoring (ISSUE 9).

A request that crosses router admission -> quota -> prefill replica ->
KV-page handoff -> decode replica -> delivery used to leave only
per-subsystem histograms behind; no single artifact showed ONE request's
journey. Production disaggregated serving (PAPERS.md: "Ragged Paged
Attention", arxiv 2604.15464; the Gemma-on-TPU serving study, arxiv
2605.25645) lives on per-request TTFT/TPOT attribution and SLO
percentiles — this module supplies both, plus the telemetry-fed cost
table ROADMAP item 4's planner wants:

* :class:`TraceContext` — a ``trace_id`` (+ optional parent) minted at
  ``ServingRouter.generate()`` (or at direct engine admission for
  fleet-less use) and threaded through ticket -> dispatch -> replica
  ``generate`` -> engine request rows. Every lifecycle edge lands as a
  rank/replica-stamped span in the process-global
  :class:`RequestTraceStore`: quota decision (rejections trace too),
  route choice with affinity score, queue wait, each prefill chunk, the
  disaggregation ``export_pages``/``import_pages`` handoff, every decode
  tick the request participates in, cancellation/timeout, and requeue
  attempts (attempt generation in the span tags).
* :func:`request_timeline` — the per-request record: queue wait, TTFT,
  per-token latencies, cached tokens, replica hops, requeue count.
  Recent timelines ride into watchdog debug files through a flight-
  recorder state provider, and :func:`timeline_to_chrome` renders one
  request as per-replica chrome lanes that
  ``flight_recorder.merge_chrome_traces`` joins into a single flow.
* :class:`SLOMonitor` — sliding-window p50/p95/p99 over TTFT / TPOT /
  queue wait plus goodput counters (``paddle_slo_goodput_total{slo}`` /
  ``paddle_slo_violations_total{slo}``; targets from
  ``PADDLE_SLO_TTFT_MS`` / ``PADDLE_SLO_TPOT_MS``), exposed as gauges
  and :func:`slo_report`.
* :func:`cost_table` — planner-facing JSON: measured per-collective
  bytes/s (CommStats + flight-recorder seq records), per-program step
  times (every ``*_seconds`` histogram), the SLO report and the
  simulator wire model in one table.

Everything is stdlib-only. ``PADDLE_REQUEST_TRACE=0`` disables the whole
layer (``start_request`` returns ``None`` and every other call is a
None-check away from free); ``PADDLE_REQUEST_TRACE_CAPACITY`` bounds the
store (oldest finished records evict first).
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import OrderedDict, deque

__all__ = [
    "TraceContext", "RequestTraceStore", "SLOMonitor", "TRACE_SCHEMA",
    "get_trace_store", "is_enabled", "enable", "disable",
    "start_request", "add_span", "add_event", "span", "note_token",
    "finish_request", "request_timeline", "recent_timelines",
    "timeline_to_chrome", "get_slo_monitor", "reset_slo_monitor",
    "slo_report", "cost_table",
]

TRACE_SCHEMA = "paddle_request_trace/1"
COST_TABLE_SCHEMA = "paddle_cost_table/2"

DEFAULT_TRACE_CAPACITY = 1024
DEFAULT_SLO_WINDOW = 1024
#: spans kept per trace (a long decode emits one span per tick; beyond
#: the cap spans are counted, not stored)
MAX_SPANS_PER_TRACE = 2048
MAX_TOKENS_PER_TRACE = 8192

#: terminal request states (one per trace; first finish wins)
TERMINAL_STATUSES = ("ok", "rejected", "timeout", "cancelled", "error")


def _env_truthy(v) -> bool:
    return v not in (None, "", "0", "false", "False", "no")


_ENABLED = _env_truthy(os.environ.get("PADDLE_REQUEST_TRACE", "1"))


def is_enabled() -> bool:
    return _ENABLED


def enable():
    global _ENABLED
    _ENABLED = True


def disable():
    global _ENABLED
    _ENABLED = False


def _rank() -> int:
    """Issuing rank (thread-simulator aware) — same rule as the flight
    recorder, so trace spans and collective events agree."""
    try:
        from .flight_recorder import _rank as fr_rank
        return fr_rank()
    except Exception:
        return 0


class TraceContext:
    """One request's trace handle: the ``trace_id`` every span keys on,
    plus mutable default tags (``replica``/``attempt``) the router
    refreshes before each dispatch attempt so engine-side spans are
    stamped with where (and which try) they ran."""

    __slots__ = ("trace_id", "parent", "t0", "wall0", "source", "tags")

    _ids = itertools.count(1)

    def __init__(self, trace_id=None, parent=None, source="engine"):
        self.trace_id = (trace_id if trace_id is not None
                         else f"req-{os.getpid():x}-{next(self._ids):06x}")
        self.parent = parent
        self.t0 = time.perf_counter()
        self.wall0 = time.time()
        self.source = source
        self.tags: dict = {}

    def set_tags(self, **tags):
        """Merge default tags stamped onto every later span (the router
        sets ``replica=``/``attempt=`` before each dispatch attempt)."""
        self.tags.update(tags)
        return self

    def __repr__(self):
        return f"<TraceContext {self.trace_id} source={self.source}>"


class RequestTraceStore:
    """Process-global bounded store of per-request trace records.

    A record is one JSON-ready dict per trace_id: identity + timing
    fields, the ordered span list, and per-token timestamps. Records are
    mutated under one lock (router thread, dispatch threads and the
    engine serve loop all append concurrently) and evicted oldest-
    finished-first when the store exceeds its capacity.
    """

    def __init__(self, capacity=None):
        if capacity is None:
            try:
                capacity = int(os.environ.get(
                    "PADDLE_REQUEST_TRACE_CAPACITY",
                    str(DEFAULT_TRACE_CAPACITY)))
            except ValueError:
                capacity = DEFAULT_TRACE_CAPACITY
        self.capacity = max(int(capacity), 8)
        self._lock = threading.RLock()
        self._records: OrderedDict = OrderedDict()   # trace_id -> record
        self._metrics = None
        self._provider_registered = False

    # -- metrics ------------------------------------------------------------
    def _tele(self):
        if self._metrics is None:
            from .telemetry import get_registry
            r = get_registry()
            self._metrics = {
                "traces": r.counter(
                    "paddle_request_traces_total",
                    "request traces finished, by terminal status",
                    labels=("status",)),
                "active": r.gauge(
                    "paddle_request_active_traces",
                    "request traces currently open in the store"),
                "dropped": r.counter(
                    "paddle_request_spans_dropped_total",
                    "spans past the per-trace cap (counted, not stored)"),
            }
        return self._metrics

    def _register_provider(self):
        """Recent timelines ride into every watchdog/flight dump. Only
        the process-global store registers — an ad-hoc store (tests)
        must not hijack the dump provider."""
        if self._provider_registered or _STORE is not self:
            return
        self._provider_registered = True
        from . import flight_recorder
        flight_recorder.register_state_provider(
            "request_traces", lambda: {
                "recent": self.recent(8),
                "open": sum(1 for r in self._records.values()
                            if r["status"] == "open"),
            })

    # -- record lifecycle ---------------------------------------------------
    def start(self, tenant="default", source="engine", prompt_tokens=0,
              max_new_tokens=0, parent=None, trace_id=None) -> TraceContext:
        ctx = TraceContext(trace_id=trace_id, parent=parent, source=source)
        rec = {
            "schema": TRACE_SCHEMA,
            "trace_id": ctx.trace_id,
            "parent": parent,
            "source": source,
            "tenant": str(tenant),
            "prompt_tokens": int(prompt_tokens),
            "max_new_tokens": int(max_new_tokens),
            "t_start": ctx.t0,
            "wall_start": ctx.wall0,
            "status": "open",
            "spans": [],
            "tokens": [],
            "spans_dropped": 0,
        }
        with self._lock:
            self._records[ctx.trace_id] = rec
            while len(self._records) > self.capacity:
                victim = next(
                    (k for k, r in self._records.items()
                     if r["status"] != "open"),
                    next(iter(self._records)))
                self._records.pop(victim, None)
            n_open = sum(1 for r in self._records.values()
                         if r["status"] == "open")
        self._tele()["active"].set(n_open)
        self._register_provider()
        return ctx

    def add_span(self, ctx, name, t0=None, dur=0.0, **tags):
        if ctx is None or not _ENABLED:
            return None
        now = time.perf_counter()
        sp = {"name": str(name),
              "t0": float(t0) if t0 is not None else now,
              "dur": max(float(dur), 0.0),
              "wall": time.time(),
              "rank": _rank()}
        merged = dict(ctx.tags)
        merged.update(tags)
        for key in ("replica", "attempt"):
            if key in merged:
                sp[key] = merged.pop(key)
        if merged:
            sp["tags"] = merged
        with self._lock:
            rec = self._records.get(ctx.trace_id)
            if rec is None:
                return None
            if len(rec["spans"]) >= MAX_SPANS_PER_TRACE:
                rec["spans_dropped"] += 1
                self._tele()["dropped"].inc()
                return None
            rec["spans"].append(sp)
        return sp

    def note_token(self, ctx, t=None):
        """Record one generated-token timestamp (feeds TTFT / per-token
        latency without a full span per token delivery)."""
        if ctx is None or not _ENABLED:
            return
        t = time.perf_counter() if t is None else float(t)
        with self._lock:
            rec = self._records.get(ctx.trace_id)
            if rec is not None and len(rec["tokens"]) < MAX_TOKENS_PER_TRACE:
                rec["tokens"].append(t)

    def finish(self, ctx, status="ok", **tags):
        """Seal the trace: compute the timeline summary, feed the SLO
        monitor (completed requests only) and bump the status counter.
        Idempotent — the first terminal status wins (a requeued
        attempt's late failure can never overwrite a delivery)."""
        if ctx is None or not _ENABLED:
            return None
        if status not in TERMINAL_STATUSES:
            raise ValueError(f"unknown terminal status {status!r}")
        self.add_span(ctx, "done", status=status, **tags)
        with self._lock:
            rec = self._records.get(ctx.trace_id)
            if rec is None or rec["status"] != "open":
                return rec
            rec["status"] = status
            rec["t_end"] = time.perf_counter()
            self._summarize_locked(rec)
            n_open = sum(1 for r in self._records.values()
                         if r["status"] == "open")
        tele = self._tele()
        tele["traces"].inc(status=status)
        tele["active"].set(n_open)
        if status == "ok":
            s = rec["summary"]
            get_slo_monitor().observe(ttft_s=s.get("ttft_s"),
                                      tpot_s=s.get("tpot_s"),
                                      queue_wait_s=s.get("queue_wait_s"))
        return rec

    def _summarize_locked(self, rec):
        t_start = rec["t_start"]
        tokens = rec["tokens"]
        spans = rec["spans"]
        ttft = tokens[0] - t_start if tokens else None
        gaps = [b - a for a, b in zip(tokens, tokens[1:])]
        tpot = sum(gaps) / len(gaps) if gaps else None
        qw = next((s["dur"] for s in spans if s["name"] == "queue_wait"),
                  None)
        hops, seen = [], set()
        for s in spans:
            r = s.get("replica")
            if r is not None and r not in seen:
                seen.add(r)
                hops.append(r)
        cached = max((int((s.get("tags") or {}).get("cached_tokens", 0))
                      for s in spans if s["name"] == "admit"), default=0)
        rec["summary"] = {
            "queue_wait_s": qw,
            "ttft_s": ttft,
            "tpot_s": tpot,
            "token_latencies_s": gaps[:256],
            "tokens_generated": len(tokens),
            "cached_tokens": cached,
            "replica_hops": hops,
            "requeues": sum(1 for s in spans if s["name"] == "requeue"),
            "attempts": max((s.get("attempt", 0) for s in spans), default=0),
            "duration_s": rec.get("t_end", t_start) - t_start,
        }

    # -- read side ----------------------------------------------------------
    def timeline(self, trace_id) -> dict:
        """The per-request timeline record (spans + computed summary).
        Open traces are summarized on the fly."""
        with self._lock:
            rec = self._records.get(str(trace_id))
            if rec is None:
                raise KeyError(f"no trace {trace_id!r} in the store")
            rec = json.loads(json.dumps(rec))   # deep, JSON-clean copy
        if "summary" not in rec:
            self._summarize_locked(rec)
        return rec

    def recent(self, n=16) -> list:
        """Newest-first compact timelines (watchdog dumps / debugging):
        summary + identity, spans trimmed to the last 32."""
        with self._lock:
            recs = list(self._records.values())[-int(n):]
        out = []
        for rec in reversed(recs):
            rec = json.loads(json.dumps(rec))
            if "summary" not in rec:
                self._summarize_locked(rec)
            rec["spans"] = rec["spans"][-32:]
            rec.pop("tokens", None)
            out.append(rec)
        return out

    def trace_ids(self) -> list:
        with self._lock:
            return list(self._records)

    def clear(self):
        with self._lock:
            self._records.clear()


_STORE: "RequestTraceStore | None" = None
_STORE_LOCK = threading.Lock()


def get_trace_store() -> RequestTraceStore:
    global _STORE
    if _STORE is None:
        with _STORE_LOCK:
            if _STORE is None:
                _STORE = RequestTraceStore()
    return _STORE


# ---------------------------------------------------------------------------
# module facade (every call is a None/bool check when tracing is off)
# ---------------------------------------------------------------------------


def _eventlog_tee(ctx, kind, tags):
    """Mirror one trace edge into the structured event log (ISSUE 15)
    with the uniform correlation fields — the cross-replica join key a
    dead process's in-memory trace store cannot provide."""
    from . import eventlog as _eventlog
    if not _eventlog.is_enabled():
        return
    fields = {k: v for k, v in tags.items() if k != "replica"}
    replica = tags.get("replica")
    if replica is None:
        replica = getattr(ctx, "tags", {}).get("replica")
    _eventlog.log_event(kind, trace_id=getattr(ctx, "trace_id", None),
                        replica=replica, src="trace", **fields)


def start_request(tenant="default", source="engine", prompt_tokens=0,
                  max_new_tokens=0, parent=None, trace_id=None):
    """Mint a :class:`TraceContext` (or None when tracing is disabled)."""
    if not _ENABLED:
        return None
    ctx = get_trace_store().start(
        tenant=tenant, source=source, prompt_tokens=prompt_tokens,
        max_new_tokens=max_new_tokens, parent=parent, trace_id=trace_id)
    _eventlog_tee(ctx, "admission", {"tenant": str(tenant),
                                     "source": source,
                                     "prompt_tokens": int(prompt_tokens)})
    return ctx


def add_span(ctx, name, t0=None, dur=0.0, **tags):
    """Record one completed span on ``ctx`` (no-op for ``ctx=None``)."""
    if ctx is None or not _ENABLED:
        return None
    _eventlog_tee(ctx, name, tags)
    return get_trace_store().add_span(ctx, name, t0=t0, dur=dur, **tags)


def add_event(ctx, name, **tags):
    """Zero-duration span (a lifecycle edge: route, requeue, reject)."""
    return add_span(ctx, name, **tags)


class span:
    """Context-manager span: ``with span(ctx, "handoff_export"): ...``.
    Records on normal AND exceptional exit (an aborted handoff still
    shows how long it ran)."""

    def __init__(self, ctx, name, **tags):
        self.ctx = ctx
        self.name = name
        self.tags = tags
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.tags.setdefault("error", exc_type.__name__)
        add_span(self.ctx, self.name, t0=self._t0,
                 dur=time.perf_counter() - self._t0, **self.tags)
        return False


def note_token(ctx, t=None):
    if ctx is None or not _ENABLED:
        return
    get_trace_store().note_token(ctx, t)


def finish_request(ctx, status="ok", **tags):
    if ctx is None or not _ENABLED:
        return None
    _eventlog_tee(ctx, "finish", dict(tags, status=status))
    return get_trace_store().finish(ctx, status=status, **tags)


def request_timeline(trace_id) -> dict:
    """``paddle.profiler.request_timeline(trace_id)`` — the per-request
    timeline record (spans, per-token latencies, summary)."""
    return get_trace_store().timeline(trace_id)


def recent_timelines(n=16) -> list:
    return get_trace_store().recent(n)


# ---------------------------------------------------------------------------
# chrome rendering: one request as per-replica lanes
# ---------------------------------------------------------------------------


def timeline_to_chrome(timeline_or_id) -> dict:
    """Render one request's timeline as ``{lane: chrome trace}`` — one
    lane per replica (spans with no replica stamp land on the minting
    source's lane). Feed the result to
    ``flight_recorder.merge_chrome_traces`` to get a single trace where
    the request renders as one flow across lanes (every event carries
    ``args.trace_id``; the merger links same-trace events with chrome
    flow events)."""
    rec = (timeline_or_id if isinstance(timeline_or_id, dict)
           else request_timeline(timeline_or_id))
    lanes: dict = {}
    t_origin = rec.get("t_start", 0.0)
    for sp in rec.get("spans", []):
        lane = str(sp.get("replica", rec.get("source", "engine")))
        args = {"trace_id": rec["trace_id"], "rank": sp.get("rank")}
        if sp.get("attempt") is not None:
            args["attempt"] = sp["attempt"]
        args.update(sp.get("tags") or {})
        lanes.setdefault(lane, []).append({
            "name": sp["name"], "ph": "X", "tid": 0,
            "ts": round((sp["t0"] - t_origin) * 1e6, 3),
            "dur": max(round(sp["dur"] * 1e6, 3), 0.001),
            "args": args,
        })
    return {lane: {"traceEvents": evs} for lane, evs in lanes.items()}


# ---------------------------------------------------------------------------
# SLO monitor
# ---------------------------------------------------------------------------


def _exact_percentile(sorted_vals, p):
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   int(round((p / 100.0) * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


class SLOMonitor:
    """Sliding-window SLO accounting over completed requests.

    Keeps the last ``window`` raw observations of TTFT, TPOT and queue
    wait (count-based window, ``PADDLE_SLO_WINDOW``) and computes EXACT
    p50/p95/p99 over the window — percentiles match the raw per-request
    timelines by construction, no histogram-bucket quantization.
    Targets come from ``PADDLE_SLO_TTFT_MS`` / ``PADDLE_SLO_TPOT_MS``
    (0 = no target, everything is goodput); each observed request bumps
    ``paddle_slo_goodput_total{slo}`` or
    ``paddle_slo_violations_total{slo}`` per targeted SLO plus the
    ``slo="request"`` rollup (a request is goodput only when EVERY
    targeted SLO held). Current window percentiles ride as
    ``paddle_slo_latency_seconds{metric,quantile}`` gauges.
    """

    METRICS = ("ttft", "tpot", "queue_wait")
    QUANTILES = (50, 95, 99)

    def __init__(self, window=None, ttft_ms=None, tpot_ms=None):
        if window is None:
            try:
                window = int(os.environ.get("PADDLE_SLO_WINDOW",
                                            str(DEFAULT_SLO_WINDOW)))
            except ValueError:
                window = DEFAULT_SLO_WINDOW
        if ttft_ms is None:
            ttft_ms = float(os.environ.get("PADDLE_SLO_TTFT_MS", "0"))
        if tpot_ms is None:
            tpot_ms = float(os.environ.get("PADDLE_SLO_TPOT_MS", "0"))
        self.window = max(int(window), 1)
        self.targets_s = {"ttft": ttft_ms / 1e3, "tpot": tpot_ms / 1e3}
        self._lock = threading.Lock()
        self._win = {m: deque(maxlen=self.window) for m in self.METRICS}
        self._goodput = {"ttft": 0, "tpot": 0, "request": 0}
        self._violations = {"ttft": 0, "tpot": 0, "request": 0}
        self._tele_fams = None

    def _tele(self):
        if self._tele_fams is None:
            from .telemetry import get_registry
            r = get_registry()
            self._tele_fams = {
                "latency": r.gauge(
                    "paddle_slo_latency_seconds",
                    "sliding-window latency percentile (exact over the "
                    "last PADDLE_SLO_WINDOW requests)",
                    labels=("metric", "quantile")),
                "goodput": r.counter(
                    "paddle_slo_goodput_total",
                    "requests inside their SLO target (slo=request "
                    "rolls up every targeted SLO)", labels=("slo",)),
                "violations": r.counter(
                    "paddle_slo_violations_total",
                    "requests over their SLO target", labels=("slo",)),
            }
        return self._tele_fams

    def observe(self, ttft_s=None, tpot_s=None, queue_wait_s=None):
        """One completed request's latencies (None = not applicable,
        e.g. a single-token request has no TPOT)."""
        tele = self._tele()
        vals = {"ttft": ttft_s, "tpot": tpot_s, "queue_wait": queue_wait_s}
        with self._lock:
            for m, v in vals.items():
                if v is not None:
                    self._win[m].append(float(v))
            ok_all = True
            for slo in ("ttft", "tpot"):
                v, target = vals[slo], self.targets_s[slo]
                if v is None:
                    continue
                good = target <= 0 or v <= target
                key = "_goodput" if good else "_violations"
                getattr(self, key)[slo] += 1
                if not good:
                    ok_all = False
                tele["goodput" if good else "violations"].inc(slo=slo)
            key = "_goodput" if ok_all else "_violations"
            getattr(self, key)["request"] += 1
            tele["goodput" if ok_all else "violations"].inc(slo="request")
            pct = {m: sorted(self._win[m]) for m in self.METRICS}
        for m, sv in pct.items():
            for q in self.QUANTILES:
                tele["latency"].set(_exact_percentile(sv, q),
                                    metric=m, quantile=f"p{q}")

    def percentile(self, metric, p):
        with self._lock:
            return _exact_percentile(sorted(self._win[metric]), p)

    def report(self) -> dict:
        with self._lock:
            win = {m: sorted(self._win[m]) for m in self.METRICS}
            goodput = dict(self._goodput)
            violations = dict(self._violations)
        out = {
            "window": self.window,
            "targets_ms": {m: self.targets_s[m] * 1e3
                           for m in ("ttft", "tpot")},
            "goodput": goodput,
            "violations": violations,
        }
        total = goodput["request"] + violations["request"]
        out["goodput_ratio"] = goodput["request"] / total if total else 1.0
        for m, sv in win.items():
            out[m] = {
                "count": len(sv),
                **{f"p{q}_s": _exact_percentile(sv, q)
                   for q in self.QUANTILES},
                "max_s": sv[-1] if sv else 0.0,
            }
        return out

    def reset(self):
        with self._lock:
            for d in self._win.values():
                d.clear()
            for d in (self._goodput, self._violations):
                for k in d:
                    d[k] = 0


_SLO: "SLOMonitor | None" = None
_SLO_LOCK = threading.Lock()


def get_slo_monitor() -> SLOMonitor:
    global _SLO
    if _SLO is None:
        with _SLO_LOCK:
            if _SLO is None:
                _SLO = SLOMonitor()
    return _SLO


def reset_slo_monitor() -> SLOMonitor:
    """Rebuild the global monitor from the current env (fresh window AND
    fresh targets — tests and bench runs)."""
    global _SLO
    with _SLO_LOCK:
        _SLO = SLOMonitor()
    return _SLO


def slo_report() -> dict:
    """``paddle.profiler.slo_report()`` — the sliding-window SLO view."""
    return get_slo_monitor().report()


# ---------------------------------------------------------------------------
# planner-facing cost table (ROADMAP 4's input)
# ---------------------------------------------------------------------------


def cost_table(path=None) -> dict:
    """Fold measured telemetry into one JSON cost table: per-collective
    wire throughput (CommStats totals + flight-recorder seq records with
    entry/exit timestamps), per-program step times (every ``*_seconds``
    histogram family with observations), the current SLO report and the
    simulator wire model. Schema v2 adds the training observatory's
    sections: ``phases`` (per-phase step seconds + fractions from
    ``profiler.step_phase``) and ``memory`` (the registered per-module
    param/grad/optimizer-state/comm byte breakdown plus the memory
    timeline's peak attribution) — the per-stage compute/memory table
    ROADMAP item 1's pipeline-split search consumes. ``path=`` also
    writes it."""
    from .telemetry import get_registry

    table: dict = {"schema": COST_TABLE_SCHEMA, "unix_time": time.time()}
    try:
        from ..distributed.comm import get_comm_stats
        table["comm"] = get_comm_stats().as_dict()
    except Exception:
        table["comm"] = {}
    # per-collective measured throughput from the flight recorder's seq
    # records (entry/exit wall clock per collective)
    collectives: dict = {}
    try:
        from .flight_recorder import get_flight_recorder
        for ev in get_flight_recorder().events(kind="collective"):
            if ev.get("t_exit") is None:
                continue
            op = str(ev.get("op"))
            dur = max(float(ev["t_exit"]) - float(ev["t_enter"]), 0.0)
            d = collectives.setdefault(
                op, {"calls": 0, "bytes": 0, "seconds": 0.0})
            d["calls"] += 1
            d["bytes"] += int(ev.get("bytes", 0))
            d["seconds"] += dur
    except Exception:
        pass
    for op, d in collectives.items():
        d["mean_s"] = d["seconds"] / max(d["calls"], 1)
        d["bytes_per_s"] = d["bytes"] / d["seconds"] if d["seconds"] else 0.0
    table["collectives"] = collectives
    # per-program step times: every latency histogram that observed
    programs: dict = {}
    for name, fam in get_registry().collect().items():
        if fam.get("type") != "histogram" or not name.endswith("_seconds"):
            continue
        for key, s in fam.get("series", {}).items():
            if not s.get("count"):
                continue
            label = f"{name}{{{key}}}" if key else name
            programs[label] = {
                "count": s["count"],
                "mean_s": s["sum"] / s["count"],
                "p50_s": s["p50"], "p95_s": s["p95"],
            }
    table["programs"] = programs
    # training observatory (schema v2): per-phase step seconds + the
    # per-module memory table the parallelism planner splits against
    try:
        from . import step_phase as _step_phase
        table["phases"] = _step_phase.breakdown()
    except Exception:
        table["phases"] = {}
    try:
        from . import memory as _memory
        mem: dict = {}
        bd = _memory.last_breakdown()
        if bd:
            mem["modules"] = bd["modules"]
            mem["totals"] = bd["totals"]
        if _memory.is_enabled():
            mem["timeline"] = _memory.get_timeline().peak_report()
        table["memory"] = mem
    except Exception:
        table["memory"] = {}
    # compile observatory: per-family compile counts + wall seconds (the
    # retrace tax a planner must charge against any shape-churning plan)
    try:
        from . import compile_observatory as _co
        table["compile"] = _co.cost_section()
    except Exception:
        table["compile"] = {}
    table["slo"] = slo_report()
    table["wire_model"] = {
        "sim_lat_us": float(os.environ.get("PADDLE_SIM_WIRE_LAT_US", "0")),
        "sim_gbps": float(os.environ.get("PADDLE_SIM_WIRE_GBPS", "0")),
    }
    if path:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(table, f)
    return table
