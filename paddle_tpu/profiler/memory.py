"""Step memory timeline + analytic per-module breakdown (ISSUE 12 —
the memory half of the training observatory).

Training memory today is one live-bytes high-water gauge. This module
answers the two questions that number cannot: *when inside the step*
does the peak happen, and *which module's state* is it made of.

* :class:`MemoryTimeline` — live device bytes sampled at every
  step-phase boundary (:mod:`.step_phase` forwards each
  ``record_phase`` as a :func:`phase_sample`), kept in a bounded ring
  of ``(t, step, phase, bytes)`` points with per-step peak attribution
  (:meth:`~MemoryTimeline.peak_report`: the peak step, the phase the
  peak landed in, per-phase maxima) and a chrome **counter track**
  (:meth:`~MemoryTimeline.to_chrome`, ``ph:"C"``) that
  ``flight_recorder.merge_chrome_traces`` folds into the per-rank trace
  view next to the span lanes.
* :func:`module_breakdown` — the analytic side: per-top-level-module
  parameter / gradient / optimizer-slot / comm-bucket bytes, dtype-aware
  (bf16 params cost half, int8 wire buckets a quarter — the same
  byte-accounting discipline as ``kv_page_nbytes``). Registered via
  :func:`register_model_breakdown`, it becomes the ``memory.modules``
  section of ``profiler.cost_table()`` schema v2 — the per-stage memory
  table ROADMAP item 1's pipeline-split search needs.

Zero overhead disabled (flight-recorder-style module bool): the wired
call sites (:func:`phase_sample`, :func:`step_begin`) are one bool
check when off. ``PADDLE_MEMORY=1`` enables at import;
``PADDLE_MEMORY_CAPACITY`` bounds the sample ring (default 2048).
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque

__all__ = [
    "MemoryTimeline", "get_timeline", "enable", "disable", "is_enabled",
    "reset", "phase_sample", "step_begin", "module_breakdown",
    "register_model_breakdown", "last_breakdown",
    "DEFAULT_MEMORY_CAPACITY",
]

DEFAULT_MEMORY_CAPACITY = 2048

_ENABLED = False
_TIMELINE: "MemoryTimeline | None" = None
_MODULE_LOCK = threading.Lock()
_LAST_BREAKDOWN: list = [None]


def _live_bytes() -> int:
    """Current device bytes in use (PJRT allocator); 0 on backends
    without allocator stats (CPU jax) — explicit ``nbytes=`` samples
    and the analytic breakdown carry the signal there."""
    try:
        from ..device.memory import memory_allocated
        return int(memory_allocated())
    except Exception:
        return 0


class MemoryTimeline:
    """Bounded ring of phase-stamped live-byte samples with per-step
    peak attribution. Thread-safe (dp sim ranks sample concurrently)."""

    def __init__(self, capacity=None):
        if capacity is None:
            try:
                capacity = int(os.environ.get("PADDLE_MEMORY_CAPACITY",
                                              str(DEFAULT_MEMORY_CAPACITY)))
            except ValueError:
                capacity = DEFAULT_MEMORY_CAPACITY
        self.capacity = max(int(capacity), 16)
        self._lock = threading.Lock()
        self._samples: deque = deque(maxlen=self.capacity)
        self._step = 0
        self._step_peak: dict = {}     # step -> (bytes, phase)
        self._phase_max: dict = {}     # phase -> max bytes seen
        self._tele = None

    def _telemetry(self):
        if self._tele is None:
            from .telemetry import get_registry
            r = get_registry()
            self._tele = {
                "live": r.gauge(
                    "paddle_memory_live_bytes",
                    "live device bytes at the last sampled phase "
                    "boundary", labels=("phase",)),
                "peak": r.gauge(
                    "paddle_memory_step_peak_bytes",
                    "peak sampled live bytes within the current step"),
                "samples": r.counter(
                    "paddle_memory_samples_total",
                    "memory-timeline phase-boundary samples taken"),
            }
        return self._tele

    # -- sampling ------------------------------------------------------------
    def step_begin(self, step=None):
        with self._lock:
            self._step = self._step + 1 if step is None else int(step)

    def sample(self, phase: str, nbytes=None) -> int:
        """One phase-boundary sample. ``nbytes=`` overrides the device
        reading (tests, or callers accounting host-side pools)."""
        b = _live_bytes() if nbytes is None else int(nbytes)
        now = time.monotonic()
        with self._lock:
            step = self._step
            self._samples.append((now, step, str(phase), b))
            cur = self._step_peak.get(step)
            if cur is None or b > cur[0]:
                self._step_peak[step] = (b, str(phase))
                # bounded: keep the last capacity steps' attributions
                if len(self._step_peak) > self.capacity:
                    for k in sorted(self._step_peak)[:-self.capacity]:
                        del self._step_peak[k]
            if b > self._phase_max.get(str(phase), -1):
                self._phase_max[str(phase)] = b
            peak = self._step_peak[step][0]
        tele = self._telemetry()
        tele["live"].set(b, phase=str(phase))
        tele["peak"].set(peak)
        tele["samples"].inc()
        return b

    # -- read side -----------------------------------------------------------
    def samples(self) -> list:
        with self._lock:
            return list(self._samples)

    def peak_report(self) -> dict:
        """Peak-step attribution: the global peak, the step and phase it
        landed in, and per-phase maxima."""
        with self._lock:
            if not self._step_peak:
                return {"peak_bytes": 0, "peak_step": None,
                        "peak_phase": None, "per_phase_max": {},
                        "samples": 0}
            peak_step = max(self._step_peak,
                            key=lambda s: self._step_peak[s][0])
            peak_bytes, peak_phase = self._step_peak[peak_step]
            return {
                "peak_bytes": peak_bytes,
                "peak_step": peak_step,
                "peak_phase": peak_phase,
                "per_phase_max": dict(self._phase_max),
                "samples": len(self._samples),
            }

    def to_chrome(self, pid=None) -> dict:
        """Chrome counter-track events (``ph:"C"``) — one
        live-bytes-over-time lane ``merge_chrome_traces`` draws next to
        the span lanes (same convention as
        ``MetricsHistory.to_chrome``)."""
        pid = os.getpid() if pid is None else pid
        events = []
        for t, step, phase, b in self.samples():
            events.append({"name": "paddle_memory_live_bytes", "ph": "C",
                           "pid": pid, "tid": 0,
                           "ts": round(t * 1e6, 3),
                           "args": {"value": b, "step": step,
                                    "phase": phase}})
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def clear(self):
        with self._lock:
            self._samples.clear()
            self._step_peak.clear()
            self._phase_max.clear()
            self._step = 0


# ---------------------------------------------------------------------------
# analytic per-module breakdown (the cost_table memory side)
# ---------------------------------------------------------------------------


def _nbytes(arr) -> int:
    import numpy as np
    a = getattr(arr, "_data", arr)
    try:
        numel = 1
        for d in a.shape:
            numel *= int(d)
        return numel * np.dtype(a.dtype).itemsize
    except Exception:
        return 0


def module_breakdown(model, optimizer=None, bucketer=None) -> dict:
    """Analytic per-top-level-module byte accounting, dtype-aware:

    * ``param_bytes`` — each parameter at its stored dtype;
    * ``grad_bytes`` — the live ``p.grad`` when present, else the
      parameter's own size for trainables (the steady-state bound);
    * ``opt_bytes`` — the optimizer's slot arrays for the module's
      parameters (moments, master weights, ... at their real dtypes);
    * ``comm_bytes`` — the module's share of the gradient fusion
      buckets (bucket dtype x per-item numel; block-alignment padding
      reported separately as ``comm_padding_bytes`` in the totals).
    """
    modules: dict = {}
    param_module: dict = {}

    def bucket_of(name: str) -> str:
        return name.split(".", 1)[0] if "." in name else name

    named = list(model.named_parameters()) if hasattr(
        model, "named_parameters") else [
        (getattr(p, "name", f"param{i}"), p)
        for i, p in enumerate(model.parameters())]
    for name, p in named:
        if p is None:
            continue
        m = bucket_of(name)
        ent = modules.setdefault(m, {"param_bytes": 0, "grad_bytes": 0,
                                     "opt_bytes": 0, "comm_bytes": 0,
                                     "params": 0})
        pb = _nbytes(p)
        ent["param_bytes"] += pb
        ent["params"] += 1
        g = getattr(p, "grad", None)
        if g is not None:
            ent["grad_bytes"] += _nbytes(g)
        elif getattr(p, "trainable", not p.stop_gradient):
            ent["grad_bytes"] += pb
        param_module[id(p)] = m
        if optimizer is not None:
            slots = getattr(optimizer, "_slots", {}).get(id(p))
            if slots:
                ent["opt_bytes"] += sum(_nbytes(a) for a in slots.values())
    comm_padding = 0
    if bucketer is not None:
        import numpy as np
        for b in bucketer.buckets:
            itemsize = np.dtype(b.dtype).itemsize
            used = 0
            for (i, _off, numel, _shape) in b.items:
                p = bucketer._params[i]
                m = param_module.get(id(p))
                if m is not None:
                    modules[m]["comm_bytes"] += numel * itemsize
                used += numel
            comm_padding += (b.numel - used) * itemsize
    for ent in modules.values():
        ent["total_bytes"] = (ent["param_bytes"] + ent["grad_bytes"]
                              + ent["opt_bytes"] + ent["comm_bytes"])
    totals = {
        k: sum(ent[k] for ent in modules.values())
        for k in ("param_bytes", "grad_bytes", "opt_bytes", "comm_bytes",
                  "total_bytes", "params")
    }
    totals["comm_padding_bytes"] = comm_padding
    return {"modules": modules, "totals": totals}


def register_model_breakdown(model, optimizer=None, bucketer=None) -> dict:
    """Compute and register the breakdown as THE training job's memory
    table — ``profiler.cost_table()`` folds the last registered one into
    its ``memory`` section."""
    bd = module_breakdown(model, optimizer=optimizer, bucketer=bucketer)
    _LAST_BREAKDOWN[0] = bd
    return bd


def last_breakdown():
    return _LAST_BREAKDOWN[0]


# ---------------------------------------------------------------------------
# module facade (zero overhead disabled — same pattern as flight_recorder)
# ---------------------------------------------------------------------------


def get_timeline() -> MemoryTimeline:
    global _TIMELINE
    if _TIMELINE is None:
        with _MODULE_LOCK:
            if _TIMELINE is None:
                _TIMELINE = MemoryTimeline()
    return _TIMELINE


def is_enabled() -> bool:
    return _ENABLED


def enable(capacity=None) -> MemoryTimeline:
    global _ENABLED, _TIMELINE
    if capacity is not None:
        with _MODULE_LOCK:
            _TIMELINE = MemoryTimeline(capacity=capacity)
    _ENABLED = True
    return get_timeline()


def disable():
    global _ENABLED
    _ENABLED = False


def reset():
    """Drop the timeline and the registered breakdown (tests / between
    jobs). Keeps the enabled flag."""
    global _TIMELINE
    with _MODULE_LOCK:
        _TIMELINE = None
    _LAST_BREAKDOWN[0] = None


def phase_sample(phase: str, nbytes=None):
    """The wired call site (every ``step_phase.record_phase`` boundary,
    ``TelemetryCallback`` step ends): one sample IF enabled — a plain
    bool check when off."""
    if not _ENABLED:
        return None
    return get_timeline().sample(phase, nbytes=nbytes)


def step_begin(step=None):
    if not _ENABLED:
        return
    get_timeline().step_begin(step)


def _env_truthy(v) -> bool:
    return v not in (None, "", "0", "false", "False", "no")


if _env_truthy(os.environ.get("PADDLE_MEMORY")):   # pragma: no cover
    enable()
