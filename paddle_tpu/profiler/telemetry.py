"""Unified runtime telemetry: process-global metrics registry + span tracer.

Two substrates every subsystem shares (ISSUE 2; the per-stage accounting
Piper and the Gemma-on-TPU comparison lean on — step breakdown, MFU,
latency percentiles):

* :class:`MetricRegistry` — thread-safe labeled Counter / Gauge /
  Histogram families with fixed-bucket percentile estimation,
  Prometheus-style text exposition (:func:`metrics_text`) and JSONL
  snapshot export. One process-global instance (:func:`get_registry`)
  is fed by the autograd tape, ``jit/to_static``, ``distributed.comm``,
  ``io.DataLoader``, the serving engines and ``TelemetryCallback``.
* :class:`SpanTracer` — nested spans with true wall-clock begin/duration,
  per-thread ids and parent linkage. Backs ``RecordEvent`` and
  ``export_chrome_tracing`` (the Profiler's trace is assembled from
  these spans, not fabricated from cumulative totals).

Everything here is stdlib-only and cheap when idle: span recording is
gated on :meth:`SpanTracer.enable` (the Profiler enables it while
recording) and the tape's per-op observer is installed only while
op telemetry is explicitly enabled (``TelemetryCallback`` / Profiler).
"""
from __future__ import annotations

import bisect
import json
import os
import threading
import time
from collections import deque

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricRegistry", "get_registry",
    "metrics", "metrics_text", "Span", "SpanTracer", "get_tracer",
    "enable_op_telemetry", "disable_op_telemetry", "op_telemetry",
    "DEFAULT_LATENCY_BUCKETS", "DEFAULT_RATIO_BUCKETS",
]

# Prometheus-style cumulative latency bounds (seconds). ``inf`` is
# implicit as the final +Inf bucket.
DEFAULT_LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

# Bounds for [0, 1]-valued observations (utilization / occupancy ratios —
# e.g. the serving engine's chunk-budget utilization histogram).
DEFAULT_RATIO_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)

_INF = float("inf")


def _escape_label(v) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt_labels(names, values) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{_escape_label(v)}"'
                     for n, v in zip(names, values))
    return "{" + inner + "}"


class _Family:
    """Base for one named metric family: a dict of children keyed by the
    label-value tuple. Lock is shared with the owning registry."""

    kind = "untyped"

    def __init__(self, name, help, labels, lock):
        self.name = name
        # real Prometheus scrapers warn on empty HELP text — an
        # undescribed family self-documents with its own name
        self.help = help or name
        self.label_names = tuple(labels)
        self._lock = lock
        self._children = {}

    def _key(self, kwargs):
        if set(kwargs) != set(self.label_names):
            raise ValueError(
                f"metric {self.name} expects labels {self.label_names}, "
                f"got {tuple(kwargs)}")
        return tuple(kwargs[n] for n in self.label_names)

    def labels(self, **kwargs):
        key = self._key(kwargs)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._new_child())
        return child

    def _default_child(self):
        """The unlabeled singleton child (for labels=() families)."""
        return self.labels()

    def reset(self):
        with self._lock:
            for c in self._children.values():
                c._reset()

    def collect(self):
        with self._lock:
            return {
                "type": self.kind,
                "help": self.help,
                "label_names": list(self.label_names),
                "series": {
                    ",".join(map(str, k)) if k else "": c._snapshot()
                    for k, c in self._children.items()
                },
            }

    def expose(self, lines):
        lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        with self._lock:
            items = list(self._children.items())
        for key, child in items:
            child._expose(lines, self.name,
                          _fmt_labels(self.label_names, key),
                          self.label_names, key)


class Counter(_Family):
    kind = "counter"

    class _Child:
        __slots__ = ("value",)

        def __init__(self):
            self.value = 0.0

        def inc(self, amount=1.0):
            self.value += amount

        def _reset(self):
            self.value = 0.0

        def _snapshot(self):
            return self.value

        def _expose(self, lines, name, labelstr, *_):
            lines.append(f"{name}{labelstr} {self.value:g}")

    def _new_child(self):
        return Counter._Child()

    def inc(self, amount=1.0, **labels):
        c = self.labels(**labels)
        with self._lock:
            c.inc(amount)

    def value(self, **labels):
        return self.labels(**labels).value


class Gauge(_Family):
    kind = "gauge"

    class _Child:
        __slots__ = ("value",)

        def __init__(self):
            self.value = 0.0

        def _reset(self):
            self.value = 0.0

        def _snapshot(self):
            return self.value

        def _expose(self, lines, name, labelstr, *_):
            lines.append(f"{name}{labelstr} {self.value:g}")

    def _new_child(self):
        return Gauge._Child()

    def set(self, value, **labels):
        c = self.labels(**labels)
        with self._lock:
            c.value = float(value)

    def inc(self, amount=1.0, **labels):
        c = self.labels(**labels)
        with self._lock:
            c.value += amount

    def set_max(self, value, **labels):
        """High-water update: keep the maximum ever set (live-bytes)."""
        c = self.labels(**labels)
        with self._lock:
            if value > c.value:
                c.value = float(value)

    def value(self, **labels):
        return self.labels(**labels).value


class Histogram(_Family):
    kind = "histogram"

    class _Child:
        __slots__ = ("bounds", "counts", "sum", "count")

        def __init__(self, bounds):
            self.bounds = bounds          # sorted, excludes +Inf
            self._reset()

        def _reset(self):
            self.counts = [0] * (len(self.bounds) + 1)
            self.sum = 0.0
            self.count = 0

        def observe(self, value):
            self.counts[bisect.bisect_left(self.bounds, value)] += 1
            self.sum += value
            self.count += 1

        def percentile(self, p):
            """Fixed-bucket estimate with linear interpolation inside the
            winning bucket; the +Inf bucket clamps to its lower bound."""
            if self.count == 0:
                return 0.0
            rank = self.count * (p / 100.0)
            cum = 0
            lo = 0.0
            for i, c in enumerate(self.counts):
                cum += c
                if cum >= rank and c > 0:
                    hi = self.bounds[i] if i < len(self.bounds) else None
                    if hi is None:
                        return lo
                    frac = (rank - (cum - c)) / c
                    return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
                if i < len(self.bounds):
                    lo = self.bounds[i]
            return lo

        def _snapshot(self):
            cum = 0
            buckets = {}
            for i, b in enumerate(self.bounds):
                cum += self.counts[i]
                buckets[f"{b:g}"] = cum
            buckets["+Inf"] = self.count
            return {
                "count": self.count,
                "sum": self.sum,
                "buckets": buckets,
                "p50": self.percentile(50),
                "p95": self.percentile(95),
                "p99": self.percentile(99),
            }

        def _expose(self, lines, name, labelstr, label_names, key):
            cum = 0
            for i, b in enumerate(self.bounds):
                cum += self.counts[i]
                ls = _fmt_labels(tuple(label_names) + ("le",),
                                 tuple(key) + (f"{b:g}",))
                lines.append(f"{name}_bucket{ls} {cum}")
            ls = _fmt_labels(tuple(label_names) + ("le",),
                             tuple(key) + ("+Inf",))
            lines.append(f"{name}_bucket{ls} {self.count}")
            lines.append(f"{name}_sum{labelstr} {self.sum:g}")
            lines.append(f"{name}_count{labelstr} {self.count}")

    def __init__(self, name, help, labels, lock,
                 buckets=DEFAULT_LATENCY_BUCKETS):
        super().__init__(name, help, labels, lock)
        self.bounds = tuple(sorted(float(b) for b in buckets
                                   if b != _INF))

    def _new_child(self):
        return Histogram._Child(self.bounds)

    def observe(self, value, **labels):
        c = self.labels(**labels)
        with self._lock:
            c.observe(float(value))

    def percentile(self, p, **labels):
        with self._lock:
            return self.labels(**labels).percentile(p)


class MetricRegistry:
    """Process-global, thread-safe registry of metric families.

    Families are get-or-create by name — repeated ``counter(...)`` calls
    from different call sites share one family (a kind mismatch raises).
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._families: dict = {}

    def _get_or_create(self, cls, name, help, labels, **kw):
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = cls(name, help, labels, self._lock, **kw)
                self._families[name] = fam
            elif not isinstance(fam, cls):
                raise TypeError(
                    f"metric {name} already registered as {fam.kind}")
            return fam

    def counter(self, name, help="", labels=()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name, help="", labels=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name, help="", labels=(),
                  buckets=DEFAULT_LATENCY_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def get(self, name):
        return self._families.get(name)

    def collect(self) -> dict:
        with self._lock:
            fams = list(self._families.values())
        return {f.name: f.collect() for f in fams}

    def to_text(self) -> str:
        """Prometheus text exposition format."""
        with self._lock:
            fams = list(self._families.values())
        lines: list = []
        for f in fams:
            f.expose(lines)
        return "\n".join(lines) + ("\n" if lines else "")

    def export_jsonl(self, path, extra=None) -> dict:
        """Append one JSON snapshot line to ``path``; returns the record.

        Multi-process safe: the whole line goes down in a single
        ``os.write`` on an ``O_APPEND`` fd, so concurrent ranks
        appending to one file (bench_telemetry.jsonl) can interleave
        only whole lines, never partial ones.

        Size-capped: when the file would grow past
        ``PADDLE_TELEMETRY_JSONL_MAX_MB`` (default 16, ``0`` disables),
        it first rotates to ``<path>.1`` (atomic ``os.replace``,
        clobbering the previous rotation) — an append-forever snapshot
        file must not eat the disk across bench runs."""
        rec = {"unix_time": time.time(), "metrics": self.collect()}
        if extra:
            rec.update(extra)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        line = (json.dumps(rec) + "\n").encode()
        try:
            max_mb = float(os.environ.get("PADDLE_TELEMETRY_JSONL_MAX_MB",
                                          "16"))
        except ValueError:
            max_mb = 16.0
        if max_mb > 0:
            try:
                if os.path.getsize(path) + len(line) > max_mb * (1 << 20):
                    os.replace(path, f"{path}.1")
            except OSError:
                pass               # no file yet / raced: append fresh
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, line)
        finally:
            os.close(fd)
        return rec

    def reset(self):
        """Zero every series (families and label sets are kept)."""
        with self._lock:
            fams = list(self._families.values())
        for f in fams:
            f.reset()


_REGISTRY = MetricRegistry()


def get_registry() -> MetricRegistry:
    return _REGISTRY


def metrics(reset=False) -> dict:
    """Snapshot of every registered metric family (nested dict). With
    ``reset=True`` the counters/histograms are zeroed after reading
    (per-window accounting, mirroring ``comm_stats``)."""
    snap = _REGISTRY.collect()
    if reset:
        _REGISTRY.reset()
    return snap


def metrics_text() -> str:
    """The registry in Prometheus text exposition format."""
    return _REGISTRY.to_text()


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------


class Span:
    """One completed (or open) span. ``ts``/``dur`` are seconds on the
    tracer's monotonic clock (``ts_us``/``dur_us`` for chrome traces);
    ``wall_time`` is the true wall-clock begin."""

    __slots__ = ("name", "ts", "dur", "tid", "span_id", "parent_id",
                 "wall_time", "args")

    def __init__(self, name, ts, tid, span_id, parent_id, wall_time,
                 args=None):
        self.name = name
        self.ts = ts
        self.dur = 0.0
        self.tid = tid
        self.span_id = span_id
        self.parent_id = parent_id
        self.wall_time = wall_time
        self.args = args

    @property
    def ts_us(self):
        return self.ts * 1e6

    @property
    def dur_us(self):
        return self.dur * 1e6

    def as_dict(self):
        return {"name": self.name, "ts": self.ts, "dur": self.dur,
                "tid": self.tid, "span_id": self.span_id,
                "parent_id": self.parent_id, "wall_time": self.wall_time,
                "args": self.args}

    def __repr__(self):
        return (f"<Span {self.name} ts={self.ts:.6f} dur={self.dur:.6f} "
                f"tid={self.tid}>")


class SpanTracer:
    """Nested span recorder with real begin timestamps and per-thread
    parent linkage. Enable/disable is refcounted (the Profiler enables
    it for each recording window); when disabled, begin/end are no-ops.
    Completed spans land in a bounded deque and are pulled with
    :meth:`drain`."""

    def __init__(self, max_spans=200_000):
        self._lock = threading.Lock()
        self._done: deque = deque(maxlen=max_spans)
        self._tls = threading.local()
        self._enabled = 0
        self._next_id = 0
        self._tids: dict = {}          # thread ident -> small stable tid
        # monotonic origin + matching wall clock, so ts is comparable
        # across threads and wall_time is recoverable for any span
        self._t0 = time.perf_counter()
        self._wall0 = time.time()

    # -- lifecycle -----------------------------------------------------------
    def enable(self):
        with self._lock:
            self._enabled += 1

    def disable(self):
        with self._lock:
            self._enabled = max(0, self._enabled - 1)

    @property
    def enabled(self) -> bool:
        return self._enabled > 0

    def _tid(self):
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            with self._lock:
                tid = self._tids.setdefault(ident, len(self._tids))
        return tid

    def _stack(self):
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _new_span(self, name, ts, args):
        stack = self._stack()
        parent = stack[-1].span_id if stack else None
        with self._lock:
            self._next_id += 1
            sid = self._next_id
        return Span(name, ts, self._tid(), sid, parent,
                    self._wall0 + ts, args)

    # -- recording -----------------------------------------------------------
    def begin(self, name, **args):
        """Open a nested span; returns the Span (or None when disabled).
        Must be closed with :meth:`end` on the same thread."""
        if not self.enabled:
            return None
        sp = self._new_span(name, time.perf_counter() - self._t0,
                            args or None)
        self._stack().append(sp)
        return sp

    def end(self, span=None):
        """Close the innermost open span of this thread (or the given
        span and anything opened after it)."""
        if span is None and not self.enabled:
            return None
        stack = self._stack()
        if not stack:
            return None
        now = time.perf_counter() - self._t0
        target = span if span in stack else stack[-1]
        while stack:
            sp = stack.pop()
            sp.dur = max(now - sp.ts, 0.0)
            with self._lock:
                self._done.append(sp)
            if sp is target:
                return sp
        return None

    def span(self, name, **args):
        """Context manager form."""
        tracer = self

        class _Ctx:
            def __enter__(self):
                self._sp = tracer.begin(name, **args)
                return self._sp

            def __exit__(self, *exc):
                if self._sp is not None:
                    tracer.end(self._sp)
                return False

        return _Ctx()

    def add_complete(self, name, duration, end_ts=None, **args):
        """Record an already-finished span (the tape's dispatch hook
        measures after the fact): begin = end - duration, parented to
        this thread's currently-open span."""
        if not self.enabled:
            return None
        now = (end_ts if end_ts is not None
               else time.perf_counter() - self._t0)
        sp = self._new_span(name, max(now - duration, 0.0), args or None)
        sp.dur = duration
        with self._lock:
            self._done.append(sp)
        return sp

    # -- consumption ---------------------------------------------------------
    def drain(self):
        """Pull (and clear) every completed span."""
        with self._lock:
            out = list(self._done)
            self._done.clear()
        return out

    def __len__(self):
        return len(self._done)


def spans_to_chrome(spans, pid=None):
    """Chrome-tracing ``traceEvents`` from completed spans — real per-span
    ``ts``/``dur`` (µs) and per-thread ``tid``, no fabricated timeline."""
    pid = os.getpid() if pid is None else pid
    events = []
    for s in sorted(spans, key=lambda x: x.ts):
        args = dict(s.args or {})
        args["span_id"] = s.span_id
        if s.parent_id is not None:
            args["parent_id"] = s.parent_id
        events.append({
            "name": s.name, "ph": "X", "pid": pid, "tid": s.tid,
            "ts": round(s.ts_us, 3), "dur": max(round(s.dur_us, 3), 0.001),
            "args": args,
        })
    return events


_TRACER = SpanTracer()


def get_tracer() -> SpanTracer:
    return _TRACER


# ---------------------------------------------------------------------------
# tape op telemetry (installed on demand — zero overhead when off)
# ---------------------------------------------------------------------------

_op_lock = threading.Lock()
_op_depth = 0
_op_metrics = None     # (counter, histogram) lazily created


def _observe_op(name, dt):
    global _op_metrics
    m = _op_metrics
    if m is None:
        r = get_registry()
        m = _op_metrics = (
            r.counter("paddle_op_dispatch_total",
                      "eager ops dispatched through the autograd tape",
                      labels=("op",)),
            r.histogram("paddle_op_dispatch_seconds",
                        "host wall time per eager op dispatch"),
        )
    m[0].inc(op=name)
    m[1].observe(dt)


def enable_op_telemetry():
    """Install the per-op observer on the autograd tape (refcounted).
    While installed, every eager dispatch feeds
    ``paddle_op_dispatch_total{op=...}`` and
    ``paddle_op_dispatch_seconds``."""
    global _op_depth
    from ..autograd import tape
    with _op_lock:
        _op_depth += 1
        if _observe_op not in tape._op_observers:
            tape._op_observers.append(_observe_op)


def disable_op_telemetry():
    global _op_depth
    from ..autograd import tape
    with _op_lock:
        _op_depth = max(0, _op_depth - 1)
        if _op_depth == 0 and _observe_op in tape._op_observers:
            tape._op_observers.remove(_observe_op)


class op_telemetry:
    """Context manager form of enable/disable_op_telemetry."""

    def __enter__(self):
        enable_op_telemetry()
        return self

    def __exit__(self, *exc):
        disable_op_telemetry()
        return False
