"""Per-layer numerics sentinel (ISSUE 12 — the numerics half of the
training observatory).

A NaN in one layer's gradient today surfaces steps later as a diverged
loss with no attribution. The sentinel watches every parameter's
gradient the moment it is FINAL — the tape's grad-ready hook
(``autograd.tape.register_grad_ready_callback``, PR 5's overlap
infrastructure) fires per leaf DURING backward — and keeps per-parameter
L2 norm / abs-max / nonfinite counts, sampled every
``PADDLE_NUMERICS_INTERVAL`` steps:

* the **first nonfinite gradient** raises a structured
  :class:`NonFiniteGradError` naming the exact parameter (or records
  and continues under ``PADDLE_NUMERICS_MODE=warn``), ticks
  ``paddle_numerics_nonfinite_total{param}``, records a
  flight-recorder ``numerics`` event, and sets the
  ``paddle_numerics_nonfinite_params`` gauge the built-in
  :class:`~.alerts.ThresholdRule` (``numerics_nonfinite``) pages on —
  so the watchdog dump's ``numerics`` state provider names the
  misbehaving layer;
* optional **activation abs-max** per op rides the tape's activation
  observer hook (``PADDLE_NUMERICS_ACTIVATIONS=1``) — the int8
  wire/KV codecs' clipping story (EQuARX blockwise discipline) needs
  exactly this range telemetry;
* the read path never perturbs training: stats are read-only over the
  finalized gradient, so a ``warn``-mode run is bit-identical to a
  sentinel-free run (tested), and the overlapped-backward dispatch
  order is untouched.

Zero overhead disabled (flight-recorder-style module bool): nothing is
registered on the tape until :func:`enable`/:func:`attach`, so the off
path costs literally nothing per dispatch. Tape callbacks are
thread-local per simulated rank — in a dp sim each rank's worker calls
:func:`attach` on its own thread (``enable()`` attaches the calling
thread). ``PADDLE_NUMERICS=1`` enables+attaches at import.
"""
from __future__ import annotations

import os
import threading
import time

__all__ = [
    "NonFiniteGradError", "NumericsSentinel", "get_sentinel", "enable",
    "disable", "attach", "detach", "is_enabled", "reset",
    "DEFAULT_NUMERICS_INTERVAL",
]

DEFAULT_NUMERICS_INTERVAL = 1
_MODES = ("raise", "warn")

_ENABLED = False
_SENTINEL: "NumericsSentinel | None" = None
_MODULE_LOCK = threading.Lock()


class NonFiniteGradError(RuntimeError):
    """A parameter's finalized gradient contains NaN/Inf. Carries the
    exact parameter (``param``), the issuing rank, the sentinel's step
    count and the nonfinite element count."""

    def __init__(self, param, rank, step, nonfinite, total):
        self.param = str(param)
        self.rank = rank
        self.step = step
        self.nonfinite = int(nonfinite)
        self.total = int(total)
        super().__init__(
            f"nonfinite gradient in parameter '{self.param}' "
            f"(rank {rank}, sentinel step {step}): {self.nonfinite}/"
            f"{self.total} elements are NaN/Inf — dump the numerics "
            f"state (watchdog 'numerics' provider) and see "
            f"docs/RUNBOOK.md 'nonfinite gradients'")


def _rank():
    try:
        from ..distributed import simulator
        r = simulator.current_rank()
        if r is not None:
            return r
    except Exception:
        pass
    return 0


class NumericsSentinel:
    """Per-parameter gradient statistics + nonfinite detection.

    One process-global instance; per-rank *attachment* (tape callbacks
    are thread-local). Stats are keyed ``(rank, param_name)``.
    """

    def __init__(self, interval=None, mode=None, activations=None):
        if interval is None:
            try:
                interval = int(os.environ.get(
                    "PADDLE_NUMERICS_INTERVAL",
                    str(DEFAULT_NUMERICS_INTERVAL)))
            except ValueError:
                interval = DEFAULT_NUMERICS_INTERVAL
        self.interval = max(int(interval), 1)
        if mode is None:
            mode = os.environ.get("PADDLE_NUMERICS_MODE", "raise")
        if mode not in _MODES:
            raise ValueError(f"unknown PADDLE_NUMERICS_MODE {mode!r} "
                             f"(one of {'/'.join(_MODES)})")
        self.mode = mode
        if activations is None:
            activations = os.environ.get(
                "PADDLE_NUMERICS_ACTIVATIONS") not in (
                None, "", "0", "false", "False", "no")
        self.activations = bool(activations)
        self._lock = threading.Lock()
        self._stats: dict = {}        # (rank, param) -> stats dict
        self._act: dict = {}          # (rank, op) -> abs-max high-water
        self._steps: dict = {}        # rank -> completed backward count
        self._offenders: list = []    # latched nonfinite records (warn)
        self._tele = None

    # -- telemetry -----------------------------------------------------------
    def _telemetry(self):
        if self._tele is None:
            from .telemetry import get_registry
            r = get_registry()
            self._tele = {
                "nonfinite": r.counter(
                    "paddle_numerics_nonfinite_total",
                    "nonfinite (NaN/Inf) gradient detections",
                    labels=("param",)),
                "bad_params": r.gauge(
                    "paddle_numerics_nonfinite_params",
                    "distinct parameters with a nonfinite gradient "
                    "detected (the built-in alert rule's signal)"),
                "samples": r.counter(
                    "paddle_numerics_samples_total",
                    "per-parameter gradient stat samples taken"),
            }
        return self._tele

    # -- sampling gate -------------------------------------------------------
    def _sampling(self, rank) -> bool:
        return self._steps.get(rank, 0) % self.interval == 0

    @staticmethod
    def _param_name(t) -> str:
        return getattr(t, "name", None) or f"param{id(t)}"

    # -- tape hooks ----------------------------------------------------------
    def _on_grad_ready(self, t):
        g = getattr(t, "grad", None)
        if g is None:
            return
        rank = _rank()
        if not self._sampling(rank):
            return
        import numpy as np
        a = np.asarray(g._data)
        if not np.issubdtype(a.dtype, np.floating):
            return
        a64 = a.astype(np.float64, copy=False)
        finite = np.isfinite(a64)
        nonfinite = int(a64.size - int(finite.sum()))
        absmax = float(np.max(np.abs(np.where(finite, a64, 0.0)))) \
            if a64.size else 0.0
        l2 = float(np.linalg.norm(np.where(finite, a64, 0.0).ravel()))
        name = self._param_name(t)
        step = self._steps.get(rank, 0)
        with self._lock:
            self._stats[(rank, name)] = {
                "param": name, "rank": rank, "step": step,
                "l2": l2, "absmax": absmax, "nonfinite": nonfinite,
                "numel": int(a64.size), "t": time.time(),
            }
            bad = sum(1 for s in self._stats.values() if s["nonfinite"])
        tele = self._telemetry()
        tele["samples"].inc()
        if nonfinite:
            tele["nonfinite"].inc(param=name)
            tele["bad_params"].set(bad)
            from . import flight_recorder
            flight_recorder.record_event(
                "numerics", param=name, nonfinite=nonfinite,
                numel=int(a64.size), step=step, mode=self.mode)
            rec = {"param": name, "rank": rank, "step": step,
                   "nonfinite": nonfinite}
            with self._lock:
                self._offenders.append(rec)
                del self._offenders[:-32]
            if self.mode == "raise":
                raise NonFiniteGradError(name, rank, step, nonfinite,
                                         a64.size)
        else:
            tele["bad_params"].set(bad)

    def _on_post_backward(self):
        rank = _rank()
        self._steps[rank] = self._steps.get(rank, 0) + 1

    def _on_activation(self, op_name, out):
        rank = _rank()
        if not self._sampling(rank):
            return
        import numpy as np
        import jax
        from ..framework.core import Tensor
        hi = None
        for leaf in jax.tree.leaves(
                out, is_leaf=lambda x: isinstance(x, Tensor)):
            a = getattr(leaf, "_data", leaf)
            try:
                if not np.issubdtype(np.asarray(a).dtype, np.floating):
                    continue
                m = float(np.max(np.abs(np.asarray(a, np.float64))))
            except Exception:
                continue
            hi = m if hi is None else max(hi, m)
        if hi is None:
            return
        key = (rank, str(op_name))
        with self._lock:
            if hi > self._act.get(key, -1.0):
                self._act[key] = hi

    # -- read side -----------------------------------------------------------
    def report(self) -> dict:
        """{(rank, param): stats} flattened for humans/tests."""
        with self._lock:
            return {f"{r}/{p}": dict(s)
                    for (r, p), s in sorted(self._stats.items())}

    def activation_report(self) -> dict:
        with self._lock:
            return {f"{r}/{op}": v
                    for (r, op), v in sorted(self._act.items())}

    def offenders(self) -> list:
        with self._lock:
            return [dict(o) for o in self._offenders]

    def state(self) -> dict:
        """The ``numerics`` state-provider payload (watchdog dumps)."""
        with self._lock:
            stats = sorted(self._stats.values(),
                           key=lambda s: (-s["nonfinite"], -s["absmax"]))
            return {
                "mode": self.mode,
                "interval": self.interval,
                "steps": dict(self._steps),
                "params": [dict(s) for s in stats[:64]],
                "offenders": [dict(o) for o in self._offenders],
                "activation_absmax": {
                    f"{r}/{op}": v
                    for (r, op), v in sorted(self._act.items())[:64]},
            }

    def clear(self):
        with self._lock:
            self._stats.clear()
            self._act.clear()
            self._steps.clear()
            del self._offenders[:]


# ---------------------------------------------------------------------------
# module facade
# ---------------------------------------------------------------------------

_ATTACHED = threading.local()


def get_sentinel() -> NumericsSentinel:
    global _SENTINEL
    if _SENTINEL is None:
        with _MODULE_LOCK:
            if _SENTINEL is None:
                _SENTINEL = NumericsSentinel()
    return _SENTINEL


def is_enabled() -> bool:
    return _ENABLED


def attach() -> NumericsSentinel:
    """Register the sentinel's tape callbacks on THIS thread (each
    simulated rank attaches itself — tape hooks are thread-local).
    Idempotent per thread."""
    s = get_sentinel()
    if getattr(_ATTACHED, "cbs", None) is not None:
        return s
    from ..autograd import tape
    ready = tape.register_grad_ready_callback(s._on_grad_ready)
    post = tape.register_post_backward_callback(s._on_post_backward)
    _ATTACHED.cbs = (ready, post)
    if s.activations:
        tape.register_activation_observer(s._on_activation)
        _ATTACHED.act = s._on_activation
    return s


def detach():
    """Unregister this thread's callbacks."""
    cbs = getattr(_ATTACHED, "cbs", None)
    if cbs is None:
        return
    from ..autograd import tape
    ready, post = cbs
    tape.unregister_grad_ready_callback(ready)
    tape.unregister_post_backward_callback(post)
    _ATTACHED.cbs = None
    act = getattr(_ATTACHED, "act", None)
    if act is not None:
        tape.unregister_activation_observer(act)
        _ATTACHED.act = None


def enable(interval=None, mode=None, activations=None) -> NumericsSentinel:
    """Build/replace the global sentinel, attach the calling thread,
    register the ``numerics`` watchdog state provider and the built-in
    ``numerics_nonfinite`` alert rule."""
    global _ENABLED, _SENTINEL
    with _MODULE_LOCK:
        if (_SENTINEL is None or interval is not None or mode is not None
                or activations is not None):
            _SENTINEL = NumericsSentinel(interval=interval, mode=mode,
                                         activations=activations)
    _ENABLED = True
    s = attach()
    from . import flight_recorder
    flight_recorder.register_state_provider("numerics", s.state)
    try:
        from .alerts import ThresholdRule, get_alert_engine
        eng = get_alert_engine()
        if "numerics_nonfinite" not in eng.rules:
            eng.add_rule(ThresholdRule(
                name="numerics_nonfinite",
                metric="paddle_numerics_nonfinite_params",
                above=0, severity="page"))
    except Exception:
        pass           # alerting is optional; detection must still work
    return s


def disable():
    """Detach this thread and drop the module gate + state provider.
    Other threads' attachments detach lazily via their own
    :func:`detach` (tests) or die with their rank threads."""
    global _ENABLED
    _ENABLED = False
    detach()
    from . import flight_recorder
    flight_recorder.unregister_state_provider("numerics")


def reset():
    """Drop the sentinel and its stats (tests / between jobs)."""
    global _SENTINEL
    detach()
    with _MODULE_LOCK:
        _SENTINEL = None
    try:
        from .alerts import _ENGINE
        if _ENGINE is not None:
            _ENGINE.remove_rule("numerics_nonfinite")
    except Exception:
        pass


def _env_truthy(v) -> bool:
    return v not in (None, "", "0", "false", "False", "no")


if _env_truthy(os.environ.get("PADDLE_NUMERICS")):   # pragma: no cover
    enable()
