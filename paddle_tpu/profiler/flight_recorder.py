"""Distributed flight recorder: per-rank post-mortem ring buffer,
collective sequence tracking, and hang/straggler diagnosis (ISSUE 3).

A hung collective or a straggling rank dies silently today — the wedged
chip hangs documented in ``ops/pallas/flash_attention.py`` leave no
trail. This module is the PyTorch-NCCL-flight-recorder analogue on the
PR 2 telemetry substrate:

* :class:`FlightRecorder` — a bounded ring buffer of recent spans, op
  dispatches and collective events, each stamped with wall time and the
  issuing rank (thread-rank simulator aware). Every collective gets a
  monotonically increasing per-rank **seq id** with entry/exit
  timestamps, so desync ("rank 3 never entered seq 41") is detectable
  after the fact instead of presenting as a bare hang.
* :class:`Watchdog` — a daemon thread that watches per-rank heartbeats
  (fed by ``TelemetryCallback`` and by every tracked collective); when a
  rank misses its deadline it dumps all-thread stacks, the ring buffer,
  a ``metrics()`` snapshot, in-flight collective state and registered
  subsystem state (e.g. the serving request queue) to one JSON debug
  file per rank, plus a cross-rank desync/straggler report when it can
  see more than one rank.
* cross-rank aggregation — :func:`publish_snapshot` /
  :func:`gather_metrics` ride any elastic KV store
  (``fleet/elastic/tcp_kv.py`` ``TcpKVStore`` or the in-process
  ``MemKVStore``) to merge per-rank snapshots, rank-labeled, into one
  registry view; :func:`merge_chrome_traces` unions per-rank span dumps
  into a single Chrome trace with one pid per rank; and
  :func:`straggler_report` computes per-collective entry-time skew.

Everything is stdlib-only and **zero overhead when disabled**: the
module-level gate (:func:`is_enabled`) is a plain bool check, and every
wired call site (collectives, the train-step heartbeat, the DataLoader
failure path) goes through a module function that returns immediately
when the gate is off.

Env flags: ``PADDLE_FLIGHT_RECORDER=1`` enables at import (with the
watchdog unless ``PADDLE_FLIGHT_WATCHDOG=0``);
``PADDLE_FLIGHT_DEADLINE_S`` (default 300), ``PADDLE_FLIGHT_CAPACITY``
(default 2048), ``PADDLE_FLIGHT_DIR`` (dump directory, default
``./flight_recorder``), ``PADDLE_METRICS_TEXT_PATH`` (the watchdog
periodically rewrites ``metrics_text()`` there for
``tools/tpu_watch.sh metrics`` to tail).
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from collections import deque

__all__ = [
    "FlightRecorder", "Watchdog", "get_flight_recorder", "enable",
    "disable", "is_enabled", "reset", "record_event", "heartbeat",
    "collective_begin", "collective_end", "register_state_provider",
    "unregister_state_provider", "desync_report", "straggler_report",
    "merge_rank_snapshots", "merge_chrome_traces", "publish_snapshot",
    "gather_snapshots", "gather_metrics", "KV_PREFIX",
    "DUMP_SCHEMA", "REPORT_SCHEMA",
]

DUMP_SCHEMA = "paddle_flight_recorder/1"
REPORT_SCHEMA = "paddle_flight_cross_report/1"
KV_PREFIX = "flight/rank/"

_ENABLED = False
_RECORDER: "FlightRecorder | None" = None
_WATCHDOG: "Watchdog | None" = None
_MODULE_LOCK = threading.Lock()
# subsystem state captured into every dump (name -> zero-arg callable);
# registration is independent of the recorder lifecycle so a serving
# engine started before enable() still shows up in the dump
_STATE_PROVIDERS: dict = {}


def _rank() -> int:
    """Issuing rank: thread-simulator rank when inside a simulated world,
    else the launch env's trainer id (0 for single-process)."""
    try:
        from ..distributed import simulator
        r = simulator.current_rank()
        if r is not None:
            return r
    except Exception:
        pass
    try:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    except ValueError:
        return 0


def _thread_stacks() -> dict:
    """Formatted stacks of every live thread (the post-hang 'where is
    everyone' view)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        key = f"{names.get(ident, 'thread')}-{ident}"
        out[key] = traceback.format_stack(frame)
    return out


class FlightRecorder:
    """Bounded ring of recent events plus live collective-sequence and
    heartbeat state. All methods are thread-safe; events are plain dicts
    (JSON-ready) stamped with ``t`` (wall clock) and ``rank``."""

    def __init__(self, capacity: int = 2048):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq: dict = {}          # rank -> last issued collective seq
        self._inflight: dict = {}     # (rank, seq) -> entry event (not exited)
        self._heartbeats: dict = {}   # rank -> monotonic ts of last liveness

    # -- generic events ------------------------------------------------------
    def record(self, kind: str, rank=None, **fields) -> dict:
        ev = {"t": time.time(), "rank": _rank() if rank is None else rank,
              "kind": kind}
        ev.update(fields)
        with self._lock:
            self._ring.append(ev)
        return ev

    def events(self, rank=None, kind=None) -> list:
        with self._lock:
            evs = list(self._ring)
        return [dict(e) for e in evs
                if (rank is None or e.get("rank") == rank)
                and (kind is None or e.get("kind") == kind)]

    def collective_events(self, by_rank: bool = False):
        evs = self.events(kind="collective")
        if not by_rank:
            return evs
        out: dict = {}
        for e in evs:
            out.setdefault(e["rank"], []).append(e)
        return out

    # -- liveness ------------------------------------------------------------
    def heartbeat(self, rank=None):
        self._heartbeats[_rank() if rank is None else rank] = time.monotonic()

    # -- collective sequence tracking ---------------------------------------
    def collective_begin(self, op: str, nbytes: int, group_ranks) -> dict:
        rank = _rank()
        now = time.time()
        with self._lock:
            seq = self._seq.get(rank, 0) + 1
            self._seq[rank] = seq
            ev = {"t": now, "rank": rank, "kind": "collective", "seq": seq,
                  "op": op, "bytes": int(nbytes),
                  "group": list(group_ranks), "t_enter": now, "t_exit": None}
            self._ring.append(ev)
            self._inflight[(rank, seq)] = ev
        self._heartbeats[rank] = time.monotonic()
        return ev

    def collective_end(self, ev: dict):
        if ev is None:
            return
        ev["t_exit"] = time.time()
        with self._lock:
            self._inflight.pop((ev["rank"], ev["seq"]), None)
        self._heartbeats[ev["rank"]] = time.monotonic()

    # -- snapshots / dumps ---------------------------------------------------
    def known_ranks(self) -> list:
        with self._lock:
            ranks = set(self._seq) | set(self._heartbeats)
            ranks.update(e.get("rank") for e in self._ring)
        ranks.discard(None)
        return sorted(ranks) or [_rank()]

    def snapshot(self, rank=None, max_events: int = 512) -> dict:
        """Per-rank JSON-ready snapshot (what :func:`publish_snapshot`
        ships over the KV store)."""
        r = _rank() if rank is None else rank
        with self._lock:
            evs = [dict(e) for e in self._ring if e.get("rank") == r]
            last_seq = self._seq.get(r, 0)
            inflight = [dict(e) for (rr, _), e in self._inflight.items()
                        if rr == r]
        from .telemetry import get_registry
        return {
            "schema": DUMP_SCHEMA, "rank": r, "unix_time": time.time(),
            "last_seq": last_seq,
            "in_flight": inflight,
            "events": evs[-max_events:],
            "collectives": [e for e in evs
                            if e.get("kind") == "collective"][-max_events:],
            "metrics": get_registry().collect(),
        }

    def _provider_state(self) -> dict:
        state = {}
        for name, fn in list(_STATE_PROVIDERS.items()):
            try:
                state[name] = fn()
            except Exception as e:       # a dump must never die on a probe
                state[name] = {"error": repr(e)}
        return state

    def dump(self, reason: str = "manual", directory=None, stalled=None,
             deadline_s=None) -> dict:
        """Write one debug file per known rank plus (when more than one
        rank is visible, e.g. under the thread simulator) a cross-rank
        desync/straggler report. Returns ``{"ranks": {rank: path},
        "report": path | None}``."""
        directory = directory or os.environ.get("PADDLE_FLIGHT_DIR",
                                                "./flight_recorder")
        os.makedirs(directory, exist_ok=True)
        stacks = _thread_stacks()
        state = self._provider_state()
        try:
            from .telemetry import get_registry
            metrics_snap = get_registry().collect()
        except Exception:
            metrics_snap = {}
        ranks = self.known_ranks()
        paths: dict = {}
        for r in ranks:
            snap = self.snapshot(rank=r)
            snap.update({
                "reason": reason,
                "stalled_ranks": list(stalled) if stalled else [],
                "deadline_s": deadline_s,
                "thread_stacks": stacks,
                "state": state,
                "metrics": metrics_snap,
            })
            path = os.path.join(directory, f"flight_rank{r}.json")
            with open(path, "w") as f:
                json.dump(snap, f)
            paths[r] = path
        report_path = None
        if len(ranks) > 1:
            by_rank = self.collective_events(by_rank=True)
            report = {
                "schema": REPORT_SCHEMA, "reason": reason,
                "unix_time": time.time(),
                "stalled_heartbeat_ranks": (sorted(stalled)
                                            if stalled else []),
                "desync": desync_report(by_rank, world=ranks),
                "straggler": straggler_report(by_rank),
            }
            report_path = os.path.join(directory, "flight_cross_report.json")
            with open(report_path, "w") as f:
                json.dump(report, f)
        return {"ranks": paths, "report": report_path}


class Watchdog:
    """Heartbeat monitor: when any tracked rank goes quiet past
    ``deadline_s``, dump the recorder once (latched; re-arms when every
    rank is fresh again). Optionally rewrites ``metrics_text()`` to a
    file on each poll so ``tools/tpu_watch.sh metrics`` can tail it."""

    def __init__(self, recorder: FlightRecorder, deadline_s: float = 300.0,
                 poll_s=None, dump_dir=None, metrics_text_path=None):
        self.recorder = recorder
        self.deadline_s = float(deadline_s)
        self.poll_s = (max(self.deadline_s / 4.0, 0.05)
                       if poll_s is None else float(poll_s))
        self.dump_dir = dump_dir
        self.metrics_text_path = metrics_text_path or os.environ.get(
            "PADDLE_METRICS_TEXT_PATH")
        self.last_dump = None
        self._fired = False
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="paddle-flight-watchdog")
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def write_metrics_text(self):
        if not self.metrics_text_path:
            return
        try:
            from .telemetry import metrics_text
            # write-tmp-then-replace with a WRITER-UNIQUE tmp name: two
            # watchdogs (or a watchdog racing a manual rewrite) must
            # never interleave writes into one tmp file and publish the
            # torn result — a scraper tailing the path (tools/
            # tpu_watch.sh metrics) may read a complete exposition or
            # the previous one, never a truncated body
            tmp = (f"{self.metrics_text_path}.tmp."
                   f"{os.getpid()}.{threading.get_ident()}")
            with open(tmp, "w") as f:
                f.write(metrics_text())
            os.replace(tmp, self.metrics_text_path)
        except Exception:
            pass                   # a metrics dump must never kill the dog

    def check(self, now=None) -> list:
        """One poll: returns the currently-stale ranks, dumping once per
        stall episode."""
        now = time.monotonic() if now is None else now
        hb = dict(self.recorder._heartbeats)
        stale = sorted(r for r, t in hb.items() if now - t > self.deadline_s)
        if stale and not self._fired:
            self._fired = True
            self.last_dump = self.recorder.dump(
                reason=(f"watchdog: no heartbeat within "
                        f"{self.deadline_s:g}s from ranks {stale}"),
                directory=self.dump_dir, stalled=stale,
                deadline_s=self.deadline_s)
        elif not stale:
            self._fired = False    # everyone fresh again: re-arm
        return stale

    def _loop(self):
        while not self._stop.wait(self.poll_s):
            self.write_metrics_text()
            self.check()


# ---------------------------------------------------------------------------
# module facade (the wired call sites go through these; all are a plain
# bool check when disabled)
# ---------------------------------------------------------------------------


def get_flight_recorder() -> FlightRecorder:
    global _RECORDER
    if _RECORDER is None:
        with _MODULE_LOCK:
            if _RECORDER is None:
                try:
                    cap = int(os.environ.get("PADDLE_FLIGHT_CAPACITY", 2048))
                except ValueError:
                    cap = 2048
                _RECORDER = FlightRecorder(capacity=cap)
    return _RECORDER


def is_enabled() -> bool:
    return _ENABLED


def enable(capacity=None, watchdog=False, deadline_s=None, poll_s=None,
           dump_dir=None, metrics_text_path=None) -> FlightRecorder:
    """Turn recording on (and optionally start the watchdog)."""
    global _ENABLED, _WATCHDOG
    fr = get_flight_recorder()
    if capacity is not None and int(capacity) != fr.capacity:
        with fr._lock:
            fr.capacity = int(capacity)
            fr._ring = deque(fr._ring, maxlen=fr.capacity)
    _ENABLED = True
    if watchdog:
        if deadline_s is None:
            try:
                deadline_s = float(
                    os.environ.get("PADDLE_FLIGHT_DEADLINE_S", 300.0))
            except ValueError:
                deadline_s = 300.0
        with _MODULE_LOCK:
            if _WATCHDOG is not None:
                _WATCHDOG.stop()
            _WATCHDOG = Watchdog(fr, deadline_s=deadline_s, poll_s=poll_s,
                                 dump_dir=dump_dir,
                                 metrics_text_path=metrics_text_path).start()
    return fr


def disable():
    global _ENABLED, _WATCHDOG
    _ENABLED = False
    with _MODULE_LOCK:
        if _WATCHDOG is not None:
            _WATCHDOG.stop()
            _WATCHDOG = None


def get_watchdog() -> "Watchdog | None":
    return _WATCHDOG


def reset():
    """Drop all recorded state (tests / between jobs). Keeps the enabled
    flag and state providers."""
    global _RECORDER
    with _MODULE_LOCK:
        _RECORDER = None


def record_event(kind: str, **fields):
    # tee into the structured event log (ISSUE 15) independently of the
    # ring gate: controller actions, alert firings and replica deaths
    # must survive the process even when the flight ring is off
    from . import eventlog as _eventlog
    if _eventlog.is_enabled():
        _eventlog.log_event(kind, **fields)
    if not _ENABLED:
        return None
    return get_flight_recorder().record(kind, **fields)


def heartbeat(rank=None):
    if not _ENABLED:
        return
    get_flight_recorder().heartbeat(rank)


def collective_begin(op: str, nbytes: int, group_ranks):
    if not _ENABLED:
        return None
    return get_flight_recorder().collective_begin(op, nbytes, group_ranks)


def collective_end(ev):
    if ev is not None:
        get_flight_recorder().collective_end(ev)


def register_state_provider(name: str, fn):
    """``fn()`` -> JSON-able dict captured into every dump (e.g. the
    serving engine's request-queue state)."""
    _STATE_PROVIDERS[name] = fn


def unregister_state_provider(name: str):
    _STATE_PROVIDERS.pop(name, None)


# ---------------------------------------------------------------------------
# cross-rank analysis (pure functions over per-rank collective events)
# ---------------------------------------------------------------------------


def _pctl(sorted_vals, p):
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   int(round((p / 100.0) * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


def desync_report(events_by_rank: dict, world=None) -> dict:
    """Detect sequence desync across ranks.

    ``events_by_rank``: {rank: [collective event dicts]} (each event has
    ``seq``/``op``/``bytes``). ``world``: optional full rank list so
    ranks with NO events at all are reported too. Returns the frontier
    seq (max entered anywhere), per-rank last seq, the ranks stuck
    behind the frontier (with the first seq they never entered and what
    that collective was on the ranks that did enter it), and per-seq
    op/byte mismatches."""
    ranks = sorted(set(events_by_rank) | set(world or []))
    by_seq: dict = {}
    last = {}
    for r in ranks:
        evs = events_by_rank.get(r, [])
        last[r] = max((e.get("seq", 0) for e in evs), default=0)
        for e in evs:
            by_seq.setdefault(e.get("seq"), {})[r] = e
    frontier = max(last.values(), default=0)
    stalled = []
    for r in ranks:
        if last[r] < frontier:
            missing = last[r] + 1
            peer = next(iter(by_seq.get(missing, {}).values()), {})
            stalled.append({
                "rank": r, "last_seq": last[r], "missing_seq": missing,
                "op": peer.get("op"), "bytes": peer.get("bytes"),
                "entered_by": sorted(by_seq.get(missing, {})),
            })
    mismatches = []
    for seq in sorted(by_seq):
        sigs = {r: (e.get("op"), e.get("bytes"))
                for r, e in by_seq[seq].items()}
        if len(set(sigs.values())) > 1:
            mismatches.append({
                "seq": seq,
                "detail": {r: {"op": op, "bytes": b}
                           for r, (op, b) in sorted(sigs.items())},
            })
    return {"ranks": ranks, "frontier_seq": frontier, "last_seq": last,
            "stalled": stalled, "mismatches": mismatches}


def straggler_report(events_by_rank: dict, percentiles=(50, 95, 99)) -> dict:
    """Per-collective entry-time skew: for every seq that more than one
    rank entered, the lag of each rank behind the earliest entrant.
    Reports slowest-rank lag percentiles overall and per op kind, plus
    per-rank mean/max lag and the worst offender."""
    by_seq: dict = {}
    for r, evs in events_by_rank.items():
        for e in evs:
            if e.get("t_enter") is not None:
                by_seq.setdefault(e.get("seq"), {})[r] = e
    skews = []                      # (seq, op, skew, slowest_rank)
    per_rank: dict = {}
    for seq, entries in by_seq.items():
        if len(entries) < 2:
            continue
        t0 = min(e["t_enter"] for e in entries.values())
        slowest_rank, skew = None, 0.0
        op = next(iter(entries.values())).get("op")
        for r, e in entries.items():
            lag = e["t_enter"] - t0
            per_rank.setdefault(r, []).append(lag)
            if lag >= skew:
                skew, slowest_rank = lag, r
        skews.append((seq, op, skew, slowest_rank))
    all_skews = sorted(s for _, _, s, _ in skews)
    by_op: dict = {}
    for _, op, s, slow in skews:
        by_op.setdefault(op, []).append((s, slow))
    op_stats = {}
    for op, pairs in by_op.items():
        vals = sorted(s for s, _ in pairs)
        worst = max(pairs, key=lambda p: p[0])
        op_stats[str(op)] = {
            "count": len(vals),
            **{f"p{p}_s": _pctl(vals, p) for p in percentiles},
            "max_s": vals[-1], "slowest_rank": worst[1],
        }
    rank_stats = {
        r: {"mean_s": sum(v) / len(v), "max_s": max(v), "n": len(v)}
        for r, v in per_rank.items() if v
    }
    slowest = max(rank_stats, key=lambda r: rank_stats[r]["mean_s"],
                  default=None)
    return {
        "n_seqs": len(skews),
        "skew_percentiles": {f"p{p}": _pctl(all_skews, p)
                             for p in percentiles},
        "max_skew_s": all_skews[-1] if all_skews else 0.0,
        "by_op": op_stats,
        "per_rank_lag": rank_stats,
        "slowest_rank": slowest,
    }


# ---------------------------------------------------------------------------
# cross-rank aggregation over the elastic KV store
# ---------------------------------------------------------------------------


def publish_snapshot(store, rank=None) -> dict:
    """Deposit this rank's flight snapshot (metrics + collective state)
    under ``flight/rank/<r>`` in any elastic KV store (``TcpKVStore`` /
    ``MemKVStore`` / ``FileKVStore``)."""
    snap = get_flight_recorder().snapshot(rank=rank)
    store.put(f"{KV_PREFIX}{snap['rank']}", snap)
    return snap


def publish_component_state(store, name, state) -> dict:
    """Deposit one named component's state dict into an elastic KV store
    — the serving fleet's replica-heartbeat path (same transport as
    :func:`publish_snapshot`; the store's own value timestamp makes TTL
    liveness checks via ``store.age`` work unchanged)."""
    payload = {"component": name, "state": state}
    if _ENABLED:
        # straight to the ring, NOT record_event: per-heartbeat publish
        # traffic must not flood the structured event log
        get_flight_recorder().record("component_state", component=name)
    store.put(name, payload)
    return payload


def gather_component_states(store, prefix) -> dict:
    """{key: state} for every component published under ``prefix``."""
    out = {}
    for key in store.keys(prefix):
        v = store.get(key)
        if isinstance(v, dict) and "component" in v:
            out[key] = v.get("state")
    return out


def gather_snapshots(store) -> dict:
    """{rank: snapshot} for every rank that published."""
    out = {}
    for key in store.keys(KV_PREFIX):
        v = store.get(key)
        if isinstance(v, dict) and "rank" in v:
            out[int(v["rank"])] = v
    return out


def merge_rank_snapshots(metrics_by_rank: dict) -> dict:
    """Union per-rank ``MetricRegistry.collect()`` dicts into ONE
    registry view: every family gains a leading ``rank`` label and each
    rank's series ride side by side."""
    merged: dict = {}
    for rank in sorted(metrics_by_rank):
        for name, fam in (metrics_by_rank[rank] or {}).items():
            m = merged.setdefault(name, {
                "type": fam.get("type", "untyped"),
                "help": fam.get("help", ""),
                "label_names": ["rank"] + list(fam.get("label_names", [])),
                "series": {},
            })
            for key, val in fam.get("series", {}).items():
                m["series"][f"{rank},{key}" if key else str(rank)] = val
    return merged


def gather_metrics(store=None) -> dict:
    """Cross-rank registry view. With a KV ``store``, merges every
    published rank snapshot (:func:`publish_snapshot`) rank-labeled into
    one view and attaches desync/straggler analysis; with no store,
    returns the local recorder's view (single rank)."""
    if store is None:
        fr = get_flight_recorder()
        snaps = {r: fr.snapshot(rank=r) for r in fr.known_ranks()}
    else:
        snaps = gather_snapshots(store)
    events_by_rank = {r: s.get("collectives", []) for r, s in snaps.items()}
    return {
        "ranks": sorted(snaps),
        "last_seq": {r: s.get("last_seq", 0) for r, s in snaps.items()},
        "merged": merge_rank_snapshots(
            {r: s.get("metrics", {}) for r, s in snaps.items()}),
        "desync": desync_report(events_by_rank),
        "straggler": straggler_report(events_by_rank),
    }


# ---------------------------------------------------------------------------
# chrome trace merging
# ---------------------------------------------------------------------------


def merge_chrome_traces(traces_by_rank: dict) -> dict:
    """Union per-rank (or per-replica) chrome traces into one: every
    event's ``pid`` becomes its lane key (plus a ``process_name``
    metadata event per lane), so Perfetto shows one process lane per
    rank/replica.

    Request flows: any merged event carrying ``args.trace_id`` (the
    per-request spans ``request_trace.timeline_to_chrome`` emits) is
    linked to the other events of the same trace_id with chrome flow
    events (``ph`` s/t/f, ``id`` = trace_id) — a disaggregated request
    renders as ONE arrow-connected flow from its prefill lane through
    the handoff to its decode lane.

    ``traces_by_rank``: {rank: trace dict | traceEvents list | path}."""
    events = []
    # ints (ranks) sort numerically, strings (replica lanes) after
    for rank in sorted(traces_by_rank,
                       key=lambda r: ((0, r, "") if isinstance(r, int)
                                      else (1, 0, str(r)))):
        t = traces_by_rank[rank]
        if isinstance(t, (str, os.PathLike)):
            with open(t) as f:
                t = json.load(f)
        evs = t.get("traceEvents", []) if isinstance(t, dict) else t
        events.append({"name": "process_name", "ph": "M", "pid": rank,
                       "tid": 0, "args": {"name": f"rank {rank}"}})
        for e in evs:
            e = dict(e)
            e["pid"] = rank
            events.append(e)
    flows: dict = {}
    for e in events:
        tid_ = (e.get("args") or {}).get("trace_id")
        if tid_ is not None and e.get("ph", "X") == "X":
            flows.setdefault(str(tid_), []).append(e)
    flow_events = []
    for trace_id, evs in sorted(flows.items()):
        if len(evs) < 2:
            continue
        evs.sort(key=lambda e: e.get("ts", 0))
        last = len(evs) - 1
        for i, e in enumerate(evs):
            ph = "s" if i == 0 else ("f" if i == last else "t")
            fe = {"name": f"request {trace_id}", "cat": "request",
                  "ph": ph, "id": trace_id, "pid": e["pid"],
                  "tid": e.get("tid", 0), "ts": e.get("ts", 0)}
            if ph == "f":
                fe["bp"] = "e"
            flow_events.append(fe)
    return {"traceEvents": events + flow_events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# env auto-enable
# ---------------------------------------------------------------------------


def _env_truthy(v) -> bool:
    return v not in (None, "", "0", "false", "False", "no")


if _env_truthy(os.environ.get("PADDLE_FLIGHT_RECORDER")):   # pragma: no cover
    enable(watchdog=_env_truthy(
        os.environ.get("PADDLE_FLIGHT_WATCHDOG", "1")))
