"""Compile observatory: runtime program-cache accounting with
retrace-**cause** attribution (ISSUE 18).

The paper's ``to_static``/Program-IR heritage makes *compiled program
identity* the unit of TPU performance: the ragged token buckets, pow2
draft-batch buckets and q-block grids exist precisely so steady-state
traffic re-enters warm programs. But until this module, compiles were a
static ``check_inventory`` concept — nothing at serve time recorded
whether a forward actually hit a warm signature, and a bucket
off-by-one showed up only as mysterious p99s. The observatory makes
every jit/compile boundary a first-class observed event:

* each instrumented call site (ragged tick, legacy prefill chunk,
  fixed-shape decode, batched draft forward, guarded-kernel proofs,
  donated training steps) reports its **program family** plus a full
  **argument signature** (array shapes/dtypes and static args) via
  :func:`observe`;
* a signature seen before for its family is a cache **hit**; an unseen
  one is a **miss** (a trace/compile), and the observatory diffs it
  against the *last signature seen* for that family to emit a
  structured retrace cause — ``arg `tokens` dim0 136∉{8,16}: bucket
  miss``, ``static arg `weight_dtype` int8→bf16``, ``new family`` —
  naming the exact argument and offending dimension;
* hits/misses/compile-seconds surface as ``paddle_compile_*`` metrics
  (with a ``family="all"`` rollup series so
  :func:`paddle_tpu.profiler.alerts.recompile_storm_rule` can burn-rate
  them), every miss is appended to the correlated eventlog (kind
  ``compile``), a bounded :func:`snapshot` backs the ``/compile``
  exporter route and flight-recorder dumps, and per-family compile
  seconds fold into ``profiler.cost_table()``;
* engines *declare* their program families up front
  (:func:`declare_family`, with per-arg bucket sets and a registered
  warmup entry) so the observatory can distinguish "legitimate warmup
  of a declared bucket" from "undeclared shape churn" — a family
  observed at serve time that CI never declared raises the
  ``paddle_compile_undeclared_families`` gauge (alertable via
  :func:`paddle_tpu.profiler.alerts.family_drift_rule`).

``PADDLE_COMPILE_OBSERVATORY=0`` disables the whole plane (call sites
are one bool check away from free); the module is stdlib-only so the
eventlog/report tooling can consume its records anywhere.
"""
from __future__ import annotations

import os
import threading

__all__ = [
    "CompileObservatory", "get_observatory", "observe", "declare_family",
    "register_warmup", "declared_families", "warmup_entries", "run_warmup",
    "undeclared_families", "snapshot", "cost_section", "tensor_arg",
    "static_arg", "format_signature", "enable", "disable", "reset",
    "is_enabled",
]

SCHEMA = "paddle_compile_observatory/1"

#: retained cause records per family (newest kept) and total distinct
#: signatures tracked per family — bounds memory under pathological churn
MAX_CAUSES_PER_FAMILY = 64
MAX_SIGNATURES_PER_FAMILY = 4096


def _env_truthy(v) -> bool:
    return str(v).lower() not in ("", "0", "false", "none")


_ENABLED = _env_truthy(os.environ.get("PADDLE_COMPILE_OBSERVATORY", "1"))


def is_enabled() -> bool:
    return _ENABLED


def enable():
    global _ENABLED
    _ENABLED = True


def disable():
    global _ENABLED
    _ENABLED = False


# ---------------------------------------------------------------------------
# signature descriptors


def tensor_arg(shape, dtype):
    """Signature descriptor for an array argument: shape + dtype. Any
    shape-like (tuple/list/np shape) and any dtype-like accepted."""
    return ("array", tuple(int(d) for d in shape), str(dtype))


def static_arg(value):
    """Signature descriptor for a static (non-array) argument. Values
    must be hashable; anything exotic is stringified."""
    if isinstance(value, (int, float, bool, str, bytes, type(None))):
        return ("static", value)
    return ("static", str(value))


def _fmt_desc(desc):
    if desc[0] == "array":
        shape = "x".join(str(d) for d in desc[1])
        return f"{desc[2]}[{shape}]"
    return repr(desc[1])


def format_signature(sig) -> str:
    """Human form of a canonical signature, e.g.
    ``tokens=int64[16], weight_dtype='int8'``."""
    return ", ".join(f"{k}={_fmt_desc(v)}" for k, v in sig)


def _canonical(signature):
    """dict name -> descriptor  =>  hashable, order-stable tuple."""
    return tuple(sorted((str(k), v) for k, v in signature.items()))


def _bucket_set(buckets, arg, dim):
    """Declared bucket values for (arg, dim), or None if undeclared.
    ``buckets`` maps arg name -> iterable of ints (dim 0) or
    dict dim -> iterable of ints."""
    if not buckets:
        return None
    per_arg = buckets.get(arg)
    if per_arg is None:
        return None
    if isinstance(per_arg, dict):
        vals = per_arg.get(dim)
        return None if vals is None else set(int(v) for v in vals)
    return set(int(v) for v in per_arg) if dim == 0 else None


def _diff_cause(prev, sig, buckets) -> str:
    """The structured retrace cause: diff the missing signature against
    the last one seen for its family."""
    if prev is None:
        return "new family"
    prev_d, sig_d = dict(prev), dict(sig)
    causes = []
    for k, v in sig_d.items():
        pv = prev_d.get(k)
        if pv == v:
            continue
        if pv is None:
            causes.append(f"new arg `{k}` {_fmt_desc(v)}")
            continue
        if v[0] == "array" and pv[0] == "array":
            pshape, shape = pv[1], v[1]
            if len(pshape) != len(shape):
                causes.append(
                    f"arg `{k}` rank {len(pshape)}→{len(shape)}")
            else:
                for d, (a, b) in enumerate(zip(pshape, shape)):
                    if a == b:
                        continue
                    declared = _bucket_set(buckets, k, d)
                    if declared is not None and b not in declared:
                        decl = ",".join(str(x) for x in sorted(declared))
                        causes.append(f"arg `{k}` dim{d} "
                                      f"{b}∉{{{decl}}}: bucket miss")
                    elif declared is not None:
                        causes.append(
                            f"arg `{k}` dim{d} {a}→{b}: new bucket")
                    else:
                        causes.append(f"arg `{k}` dim{d} {a}→{b}")
            if pv[2] != v[2]:
                causes.append(f"arg `{k}` dtype {pv[2]}→{v[2]}")
        elif v[0] == "static" and pv[0] == "static":
            causes.append(f"static arg `{k}` {pv[1]}→{v[1]}")
        else:
            causes.append(f"arg `{k}` kind {pv[0]}→{v[0]}")
    for k, pv in prev_d.items():
        if k not in sig_d:
            causes.append(f"arg `{k}` removed")
    return "; ".join(causes) or "signature churn"


# ---------------------------------------------------------------------------
# observatory


class _Family:
    __slots__ = ("signatures", "last_sig", "hits", "misses",
                 "compile_s", "causes", "overflowed")

    def __init__(self):
        self.signatures = {}     # canonical sig -> observation count
        self.last_sig = None
        self.hits = 0
        self.misses = 0
        self.compile_s = 0.0
        self.causes = []         # newest-last [{cause, signature, seconds}]
        self.overflowed = False


class CompileObservatory:
    """Process-wide program-cache model: per-family signature tables,
    hit/miss accounting, cause attribution, declared-inventory drift."""

    def __init__(self):
        self._lock = threading.RLock()
        self._families = {}      # name -> _Family
        self._declared = {}      # name -> {"buckets": ..., "static": ...}
        self._warmups = {}       # name -> callable
        self._tele = None
        self._provider = False

    # -- declaration -------------------------------------------------------

    def declare_family(self, name, buckets=None, warmup=None, static=None):
        """Declare a program family: its per-arg bucket sets (arg name ->
        iterable of dim-0 sizes, or dict dim -> iterable) and optionally
        a warmup entry — a zero-arg callable that compiles every
        declared signature of the family up front. Idempotent; the
        latest declaration wins (one serving config per process)."""
        name = str(name)
        with self._lock:
            self._declared[name] = {
                "buckets": dict(buckets) if buckets else {},
                "static": dict(static) if static else {},
            }
            if warmup is not None:
                self._warmups[name] = warmup
        return name

    def register_warmup(self, name, fn):
        with self._lock:
            self._warmups[str(name)] = fn

    def declared_families(self):
        with self._lock:
            return dict(self._declared)

    def warmup_entries(self):
        with self._lock:
            return dict(self._warmups)

    def undeclared_families(self):
        """Families observed at runtime that were never declared — the
        drift the inventory guard exists to prevent."""
        with self._lock:
            return sorted(set(self._families) - set(self._declared))

    def run_warmup(self, families=None):
        """Execute registered warmup entries (all, or the named subset);
        returns {family: result} — each entry pre-compiles its family's
        declared signatures so steady-state traffic sees zero misses."""
        with self._lock:
            entries = [(n, fn) for n, fn in sorted(self._warmups.items())
                       if families is None or n in families]
        return {n: fn() for n, fn in entries}

    # -- observation -------------------------------------------------------

    def observe(self, family, signature, seconds=None, trace_id=None):
        """Record one program-boundary execution. ``signature`` maps arg
        name -> :func:`tensor_arg`/:func:`static_arg` descriptor;
        ``seconds`` is the call's wall time (attributed as compile cost
        on a miss — the first execution of a shape pays trace+compile).
        Returns ``{"family", "miss", "cause", "seconds"}``."""
        family = str(family)
        sig = _canonical(signature)
        with self._lock:
            fam = self._families.get(family)
            if fam is None:
                fam = self._families[family] = _Family()
            known = sig in fam.signatures
            declared = self._declared.get(family)
            if known:
                fam.hits += 1
                fam.signatures[sig] += 1
                cause = None
            else:
                fam.misses += 1
                fam.compile_s += float(seconds or 0.0)
                buckets = declared["buckets"] if declared else None
                cause = _diff_cause(fam.last_sig, sig, buckets)
                if declared is None:
                    cause = f"{cause} (family undeclared)"
                if len(fam.signatures) < MAX_SIGNATURES_PER_FAMILY:
                    fam.signatures[sig] = 1
                else:
                    fam.overflowed = True
                fam.causes.append({"cause": cause,
                                   "signature": format_signature(sig),
                                   "seconds": float(seconds or 0.0)})
                del fam.causes[:-MAX_CAUSES_PER_FAMILY]
            fam.last_sig = sig
            n_undeclared = len(set(self._families) - set(self._declared))
        self._record_metrics(family, known, seconds, n_undeclared)
        if not known:
            self._record_event(family, cause, seconds, trace_id, sig)
        return {"family": family, "miss": not known, "cause": cause,
                "seconds": float(seconds or 0.0)}

    def _telemetry(self):
        if self._tele is None:
            from .telemetry import get_registry
            reg = get_registry()
            self._tele = {
                "hits": reg.counter(
                    "paddle_compile_hits_total",
                    "program-cache hits per family (signature seen "
                    "before; family=\"all\" is the cross-family rollup "
                    "the recompile-storm burn-rate rule consumes)",
                    labels=("family",)),
                "misses": reg.counter(
                    "paddle_compile_misses_total",
                    "trace/compile events per family (unseen signature; "
                    "family=\"all\" rollup)", labels=("family",)),
                "seconds": reg.histogram(
                    "paddle_compile_seconds",
                    "wall seconds of compile (miss) executions per "
                    "program family", labels=("family",)),
                "undeclared": reg.gauge(
                    "paddle_compile_undeclared_families",
                    "program families observed at runtime that the "
                    "declared inventory does not contain (drift)"),
            }
        if not self._provider:
            self._provider = True
            try:
                from . import flight_recorder
                flight_recorder.register_state_provider(
                    "compile_observatory", self.snapshot)
            except Exception:
                pass
        return self._tele

    def _record_metrics(self, family, known, seconds, n_undeclared):
        try:
            tele = self._telemetry()
            kind = "hits" if known else "misses"
            tele[kind].inc(family=family)
            tele[kind].inc(family="all")
            if not known and seconds:
                tele["seconds"].observe(float(seconds), family=family)
            tele["undeclared"].set(float(n_undeclared))
        except Exception:
            pass

    def _record_event(self, family, cause, seconds, trace_id, sig):
        try:
            from . import eventlog
            eventlog.log_event("compile", trace_id=trace_id,
                               src="compile_observatory", family=family,
                               cause=cause,
                               seconds=round(float(seconds or 0.0), 6),
                               signature=format_signature(sig))
        except Exception:
            pass

    # -- introspection -----------------------------------------------------

    def snapshot(self):
        """Bounded JSON-safe view: the ``/compile`` exporter route, the
        flight-recorder state provider, and ``compile_report --fleet``
        all serve this."""
        with self._lock:
            families = {}
            for name, fam in sorted(self._families.items()):
                families[name] = {
                    "hits": fam.hits,
                    "misses": fam.misses,
                    "compile_s": round(fam.compile_s, 6),
                    "signatures": len(fam.signatures),
                    "declared": name in self._declared,
                    "warmup": name in self._warmups,
                    "overflowed": fam.overflowed,
                    "last_causes": list(fam.causes[-8:]),
                }
            declared_only = sorted(set(self._declared) -
                                   set(self._families))
            undeclared = sorted(set(self._families) - set(self._declared))
            return {
                "schema": SCHEMA,
                "enabled": _ENABLED,
                "families": families,
                "declared_unobserved": declared_only,
                "undeclared": undeclared,
                "totals": {
                    "hits": sum(f.hits for f in self._families.values()),
                    "misses": sum(f.misses
                                  for f in self._families.values()),
                    "compile_s": round(sum(
                        f.compile_s for f in self._families.values()), 6),
                },
            }

    def cost_section(self):
        """Per-family compile cost for ``profiler.cost_table()``: the
        planner weighs warmup/compile seconds against steady-state
        gains when picking bucket sets."""
        with self._lock:
            out = {}
            for name, fam in sorted(self._families.items()):
                if not fam.misses:
                    continue
                out[name] = {
                    "compiles": fam.misses,
                    "compile_s": round(fam.compile_s, 6),
                    "mean_compile_s": round(fam.compile_s / fam.misses, 6),
                }
            return out

    def reset(self):
        with self._lock:
            self._families.clear()
            self._declared.clear()
            self._warmups.clear()


# ---------------------------------------------------------------------------
# module facade (the wired call-site surface: one bool check when off)

_OBSERVATORY = CompileObservatory()


def get_observatory() -> CompileObservatory:
    return _OBSERVATORY


def observe(family, signature, seconds=None, trace_id=None):
    """Gate-checked :meth:`CompileObservatory.observe`; returns None
    when the observatory is disabled (call sites branch on that)."""
    if not _ENABLED:
        return None
    return _OBSERVATORY.observe(family, signature, seconds=seconds,
                                trace_id=trace_id)


def declare_family(name, buckets=None, warmup=None, static=None):
    return _OBSERVATORY.declare_family(name, buckets=buckets,
                                       warmup=warmup, static=static)


def register_warmup(name, fn):
    _OBSERVATORY.register_warmup(name, fn)


def declared_families():
    return _OBSERVATORY.declared_families()


def warmup_entries():
    return _OBSERVATORY.warmup_entries()


def run_warmup(families=None):
    return _OBSERVATORY.run_warmup(families=families)


def undeclared_families():
    return _OBSERVATORY.undeclared_families()


def snapshot():
    return _OBSERVATORY.snapshot()


def cost_section():
    return _OBSERVATORY.cost_section()


def reset():
    """Clear all observed/declared state and re-read the env gate."""
    global _ENABLED
    _OBSERVATORY.reset()
    _ENABLED = _env_truthy(os.environ.get("PADDLE_COMPILE_OBSERVATORY",
                                          "1"))
