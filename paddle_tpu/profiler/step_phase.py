"""Step-phase spans: where a training step's wall time goes (ISSUE 12 —
the per-phase half of the training observatory).

One training step decomposes into four phases the planner's cost model
(ROADMAP item 1) needs separately — forward, backward, comm-wait (the
gradient exchange the overlap scheduler could not hide), optimizer —
and today only the total is measured. This module is the shared clock:

* wired call sites — ``hapi.Model.fit`` / ``Model.train_batch`` wrap
  the net forward, ``Tensor.backward`` wraps ``tape.run_backward``,
  ``ReadyBucketScheduler.finish`` / ``GradientBucketer.sync_grads``
  report the gradient-exchange wait, and ``Optimizer.step`` wraps the
  update — each a :func:`record_phase` call that is one bool check when
  the layer is off;
* every recorded span lands in the
  ``paddle_step_phase_seconds{phase}`` histogram AND in the cumulative
  per-phase totals :func:`breakdown` serves (phase fractions — the
  ``train_phase_breakdown`` bench metric and the ``phases`` section of
  ``profiler.cost_table()`` schema v2);
* every phase boundary is also a memory-timeline sample point
  (:func:`profiler.memory.phase_sample`) so the live-bytes timeline is
  attributable to the phase that produced the peak.

Zero overhead disabled (flight-recorder-style module bool):
``PADDLE_STEP_PHASE=1`` enables at import; ``TelemetryCallback``
enables it for the duration of a ``fit`` (``track_phases=True``, the
default) the same way it enables op telemetry.
"""
from __future__ import annotations

import os
import threading
import time

__all__ = [
    "PHASES", "enable", "disable", "is_enabled", "reset", "clock",
    "record_phase", "span", "step_begin", "step_end", "breakdown",
    "steps_recorded",
]

#: the step decomposition (stable label set for the histogram)
PHASES = ("forward", "backward", "comm_wait", "optimizer")

_ENABLED = False
_LOCK = threading.Lock()
_TOTALS: dict = {}        # phase -> [seconds, count]
_STEPS = [0]              # step_begin() calls observed
_TELE = [None]


def _telemetry():
    if _TELE[0] is None:
        from .telemetry import get_registry
        _TELE[0] = get_registry().histogram(
            "paddle_step_phase_seconds",
            "wall seconds per training-step phase "
            "(forward/backward/comm_wait/optimizer)",
            labels=("phase",))
    return _TELE[0]


def is_enabled() -> bool:
    return _ENABLED


def enable():
    global _ENABLED
    _ENABLED = True


def disable():
    global _ENABLED
    _ENABLED = False


def reset():
    """Drop the cumulative totals (tests / between jobs). Keeps the
    enabled flag; the histogram family persists like every registry
    family."""
    with _LOCK:
        _TOTALS.clear()
        _STEPS[0] = 0


def clock():
    """``time.perf_counter()`` when the layer is on, else ``None`` —
    the cheap begin half of a hand-rolled span (wired call sites pair
    it with :func:`record_phase`)."""
    return time.perf_counter() if _ENABLED else None


def record_phase(phase: str, seconds: float):
    """One measured phase span. No-op (one bool check) when disabled."""
    if not _ENABLED:
        return
    _telemetry().observe(seconds, phase=phase)
    with _LOCK:
        tot = _TOTALS.get(phase)
        if tot is None:
            tot = _TOTALS[phase] = [0.0, 0]
        tot[0] += float(seconds)
        tot[1] += 1
    # a phase boundary is a memory-timeline sample point
    from . import memory as _memory
    _memory.phase_sample(phase)


class _PhaseSpan:
    __slots__ = ("phase", "_t0")

    def __init__(self, phase):
        self.phase = phase
        self._t0 = None

    def __enter__(self):
        if _ENABLED:
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._t0 is not None:
            record_phase(self.phase, time.perf_counter() - self._t0)
            self._t0 = None
        return False


def span(phase: str) -> _PhaseSpan:
    """Context manager measuring one phase span (inert when off)."""
    return _PhaseSpan(phase)


def step_begin(step: int | None = None):
    """Step boundary (``TelemetryCallback.on_train_batch_begin``):
    counts steps and forwards the boundary to the memory timeline."""
    if not _ENABLED:
        return
    with _LOCK:
        _STEPS[0] += 1
    from . import memory as _memory
    _memory.step_begin(step)


def step_end():
    """Step boundary (``TelemetryCallback.on_train_batch_end``): a
    final memory sample so the timeline sees post-step live bytes."""
    if not _ENABLED:
        return
    from . import memory as _memory
    _memory.phase_sample("step")


def steps_recorded() -> int:
    return _STEPS[0]


def breakdown() -> dict:
    """Cumulative per-phase seconds/count/fraction — the
    ``train_phase_breakdown`` shape and ``cost_table()['phases']``.
    Fractions are of the summed phase time (phases can overlap the
    step's untracked tail, so they are fractions of *attributed* time,
    not of wall step time)."""
    with _LOCK:
        tot = {ph: (s, n) for ph, (s, n) in _TOTALS.items()}
        steps = _STEPS[0]
    total_s = sum(s for s, _ in tot.values())
    out = {}
    for ph in list(PHASES) + sorted(set(tot) - set(PHASES)):
        if ph not in tot:
            continue
        s, n = tot[ph]
        out[ph] = {
            "seconds": s,
            "count": n,
            "fraction": (s / total_s) if total_s > 0 else 0.0,
        }
    return {"phases": out, "total_seconds": total_s, "steps": steps}


def _env_truthy(v) -> bool:
    return v not in (None, "", "0", "false", "False", "no")


if _env_truthy(os.environ.get("PADDLE_STEP_PHASE")):   # pragma: no cover
    enable()
