"""Alert rules over the metric history: static thresholds + multi-window
SLO burn-rate alerting (ISSUE 11 — the "should a pager fire" half of the
fleet load observatory).

Rules evaluate against :class:`~.timeseries.MetricsHistory` (never the
instantaneous registry — an alert is a statement about a *window*, not a
moment):

* :class:`ThresholdRule` — fire when a series' latest sample (held for
  ``for_s`` seconds, optional) sits above/below a bound. The classic
  "replicas_alive < 2" page.
* :class:`BurnRateRule` — Prometheus-style multi-window SLO burn rate
  over the :class:`~.request_trace.SLOMonitor` counters
  (``paddle_slo_violations_total`` / ``paddle_slo_goodput_total``):
  ``burn = (violations / total) / budget`` computed over a **fast**
  window (1x base — catches the burst quickly) AND a **slow** window
  (N x base — keeps one noisy request from paging); the rule fires only
  when both exceed ``factor``. Fast-window-only also *clears* quickly
  once the burst drains, which is what makes time-to-recover
  measurable.

Firing / clearing transitions land in three places at once: a
flight-recorder event (``kind="alert"``), the
``paddle_alerts_total{rule,severity}`` counter +
``paddle_alert_active{rule}`` gauge, and the ``alerts`` state provider
captured into every watchdog dump.

Rules register programmatically (:meth:`AlertEngine.add_rule`) or via
the ``PADDLE_ALERT_RULES`` env grammar — ``;``-separated
``kind:key=value,...`` directives, same shape as ``PADDLE_FAULT_PLAN``::

    PADDLE_ALERT_RULES="threshold:metric=paddle_fleet_replicas_alive,below=2,severity=page"
    PADDLE_ALERT_RULES="burn_rate:slo=request,budget=0.1,fast=30,slow=120,factor=1.0"

The global engine hooks itself onto the history's tick observers, so
rules evaluate on the exact sample timeline — deterministic under
``tick(now=)`` in tests. Everything here is stdlib-only.
"""
from __future__ import annotations

import os
import threading
import time

__all__ = [
    "AlertRule", "ThresholdRule", "BurnRateRule", "AlertEngine",
    "parse_rules", "get_alert_engine", "reset_alert_engine",
    "active_alerts", "DEFAULT_SLO_BUDGET",
    "recompile_storm_rule", "family_drift_rule",
    "DEFAULT_RECOMPILE_BUDGET",
]

#: default SLO error budget (fraction of requests allowed to violate)
DEFAULT_SLO_BUDGET = 0.05

_SEVERITIES = ("info", "warn", "page")


class AlertRule:
    """Base rule: a named predicate over the history. Subclasses
    implement :meth:`value` (the measured quantity) and
    :meth:`breached` (is the condition met at ``now``)."""

    kind = "rule"

    def __init__(self, name, severity="warn"):
        self.name = str(name)
        if severity not in _SEVERITIES:
            raise ValueError(f"unknown severity {severity!r} "
                             f"(one of {'/'.join(_SEVERITIES)})")
        self.severity = severity

    def value(self, history, now):          # pragma: no cover - interface
        raise NotImplementedError

    def breached(self, history, now) -> bool:   # pragma: no cover
        raise NotImplementedError

    def describe(self) -> dict:
        return {"name": self.name, "kind": self.kind,
                "severity": self.severity}


class ThresholdRule(AlertRule):
    """Fire when the latest sample of ``metric{labels}`` is ``above``
    (strictly greater) or ``below`` (strictly less) the bound, and has
    been for at least ``for_s`` seconds (every sample in the trailing
    ``for_s`` window must breach — one blip does not page)."""

    kind = "threshold"

    def __init__(self, name=None, metric=None, labels="", above=None,
                 below=None, for_s=0.0, severity="warn"):
        if metric is None:
            raise ValueError("ThresholdRule needs metric=")
        if (above is None) == (below is None):
            raise ValueError("ThresholdRule needs exactly one of "
                             "above= / below=")
        super().__init__(name or f"threshold_{metric}", severity=severity)
        self.metric = str(metric)
        self.labels = labels
        self.above = None if above is None else float(above)
        self.below = None if below is None else float(below)
        self.for_s = float(for_s)

    def _breach(self, v) -> bool:
        if self.above is not None:
            return v > self.above
        return v < self.below

    def value(self, history, now):
        p = history.latest(self.metric, self.labels)
        return p[1] if p else None

    def breached(self, history, now) -> bool:
        pts = history.points(self.metric, self.labels)
        if not pts:
            return False
        if self.for_s <= 0:
            return self._breach(pts[-1][1])
        lo = now - self.for_s
        window = [(t, v) for t, v in pts if t >= lo]
        if not window or window[0][0] > lo + 1e-9:
            # the condition must be OBSERVED across the whole hold
            # window; too-young series (or a gap) cannot page yet
            return False
        return all(self._breach(v) for _, v in window)

    def describe(self) -> dict:
        d = super().describe()
        d.update(metric=self.metric, labels=str(self.labels),
                 above=self.above, below=self.below, for_s=self.for_s)
        return d


class BurnRateRule(AlertRule):
    """Multi-window SLO burn rate (Prometheus SRE-workbook style).

    ``burn(window) = (bad / (bad + good)) / budget`` where bad/good are
    reset-aware counter increases of ``bad_metric{slo}`` /
    ``good_metric{slo}`` over the window. Fires when **both** the fast
    window (``fast_window_s``) and the slow window (``slow_window_s``,
    conventionally N x fast) burn at >= ``factor``; windows with no
    traffic burn 0. ``factor=1`` means "violations are eating budget
    exactly at the rate that exhausts it"."""

    kind = "burn_rate"

    def __init__(self, name=None, slo="request", budget=None,
                 fast_window_s=60.0, slow_window_s=300.0, factor=1.0,
                 severity="page",
                 good_metric="paddle_slo_goodput_total",
                 bad_metric="paddle_slo_violations_total"):
        super().__init__(name or f"slo_burn_{slo}", severity=severity)
        self.slo = str(slo)
        if budget is None:
            budget = DEFAULT_SLO_BUDGET
        self.budget = float(budget)
        if not (0.0 < self.budget <= 1.0):
            raise ValueError(f"budget must be in (0, 1], got {budget}")
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        if self.slow_window_s < self.fast_window_s:
            raise ValueError("slow_window_s must be >= fast_window_s")
        self.factor = float(factor)
        self.good_metric = good_metric
        self.bad_metric = bad_metric

    def burn(self, history, window_s, now) -> float:
        bad = history.increase(self.bad_metric, self.slo,
                               window_s=window_s, now=now)
        good = history.increase(self.good_metric, self.slo,
                                window_s=window_s, now=now)
        total = bad + good
        if total <= 0:
            return 0.0
        return (bad / total) / self.budget

    def value(self, history, now):
        return self.burn(history, self.fast_window_s, now)

    def breached(self, history, now) -> bool:
        return (self.burn(history, self.fast_window_s, now) >= self.factor
                and self.burn(history, self.slow_window_s, now)
                >= self.factor)

    def describe(self) -> dict:
        d = super().describe()
        d.update(slo=self.slo, budget=self.budget,
                 fast_window_s=self.fast_window_s,
                 slow_window_s=self.slow_window_s, factor=self.factor)
        return d


#: default tolerated steady-state trace-cache miss fraction for the
#: recompile-storm burn rate: >2% of program lookups missing (over both
#: windows) means shapes are churning past the declared buckets
DEFAULT_RECOMPILE_BUDGET = 0.02


def recompile_storm_rule(budget=None, fast_window_s=60.0,
                         slow_window_s=300.0, factor=1.0,
                         severity="page", name="recompile_storm",
                         **_ignored):
    """Burn-rate rule over the compile observatory's hit/miss counters
    (the ``family="all"`` rollup series): fires when trace-cache misses
    eat the recompile budget in both windows — a recompile storm. The
    offending argument/dimension is in the miss events' ``cause``
    strings (``tools/compile_report.py`` or the ``/compile`` scrape)."""
    if budget is None:
        budget = DEFAULT_RECOMPILE_BUDGET
    return BurnRateRule(
        name=name, slo="all", budget=budget,
        fast_window_s=fast_window_s, slow_window_s=slow_window_s,
        factor=factor, severity=severity,
        good_metric="paddle_compile_hits_total",
        bad_metric="paddle_compile_misses_total")


def family_drift_rule(for_s=0.0, severity="warn",
                      name="compile_family_drift", **_ignored):
    """Threshold rule on ``paddle_compile_undeclared_families``: any
    serve-time program family never declared in the inventory (a code
    path compiling programs the fleet doesn't account for) is drift."""
    return ThresholdRule(name=name,
                         metric="paddle_compile_undeclared_families",
                         above=0.0, for_s=for_s, severity=severity)


# ---------------------------------------------------------------------------
# env grammar (PADDLE_ALERT_RULES — same directive shape as the
# PADDLE_FAULT_PLAN grammar from PR 6)
# ---------------------------------------------------------------------------

_RULE_KINDS = {"threshold": ThresholdRule, "burn_rate": BurnRateRule,
               "recompile_storm": recompile_storm_rule,
               "family_drift": family_drift_rule}

#: grammar key -> constructor kwarg (+ coercion)
_KEY_MAP = {
    "threshold": {"metric": str, "labels": str, "above": float,
                  "below": float, "for": ("for_s", float),
                  "name": str, "severity": str},
    "burn_rate": {"slo": str, "budget": float, "fast": ("fast_window_s",
                                                        float),
                  "slow": ("slow_window_s", float), "factor": float,
                  "name": str, "severity": str},
    "recompile_storm": {"budget": float, "fast": ("fast_window_s",
                                                  float),
                        "slow": ("slow_window_s", float),
                        "factor": float, "name": str, "severity": str},
    "family_drift": {"for": ("for_s", float), "name": str,
                     "severity": str},
}


def parse_rules(spec: str) -> list:
    """Parse the ``PADDLE_ALERT_RULES`` grammar into rule objects."""
    rules = []
    for directive in str(spec).split(";"):
        directive = directive.strip()
        if not directive:
            continue
        kind, _, rest = directive.partition(":")
        kind = kind.strip()
        cls = _RULE_KINDS.get(kind)
        if cls is None:
            raise ValueError(
                f"unknown alert rule kind {kind!r} in {directive!r} "
                f"(one of {'/'.join(sorted(_RULE_KINDS))})")
        kwargs = {}
        for pair in filter(None, (p.strip() for p in rest.split(","))):
            k, _, v = pair.partition("=")
            k = k.strip()
            mapping = _KEY_MAP[kind].get(k)
            if mapping is None:
                raise ValueError(f"unknown key {k!r} for alert rule "
                                 f"{kind!r} (in {directive!r})")
            if isinstance(mapping, tuple):
                dest, coerce = mapping
            else:
                dest, coerce = k, mapping
            kwargs[dest] = coerce(v.strip())
        rules.append(cls(**kwargs))
    return rules


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------


class AlertEngine:
    """Holds the rules, tracks firing state, and emits the transitions.

    ``evaluate(now=)`` runs every rule; an inactive rule whose condition
    breaches becomes *active* (counter tick + gauge 1 + flight event),
    an active rule whose condition clears becomes *inactive* (gauge 0 +
    flight event). The engine hooks itself onto a history's tick
    observers (:meth:`attach`) so evaluation rides the sample timeline.
    """

    def __init__(self, history=None, rules=None):
        self._history = history
        self._lock = threading.RLock()
        self.rules: dict = {}             # name -> rule
        self.active: dict = {}            # name -> {since, severity, value}
        self.transitions: list = []       # bounded recent fire/clear log
        self._tele = None
        self._attached = None
        for r in rules or ():
            self.add_rule(r)

    def _telemetry(self):
        if self._tele is None:
            from .telemetry import get_registry
            r = get_registry()
            self._tele = {
                "fired": r.counter(
                    "paddle_alerts_total",
                    "alert rule firings (active transitions)",
                    labels=("rule", "severity")),
                "active": r.gauge(
                    "paddle_alert_active",
                    "1 while the rule's condition holds, else 0",
                    labels=("rule",)),
            }
        return self._tele

    def history(self):
        if self._history is None:
            from .timeseries import get_history
            self._history = get_history()
        return self._history

    # -- rule management -----------------------------------------------------
    def add_rule(self, rule) -> AlertRule:
        with self._lock:
            self.rules[rule.name] = rule
        # the gauge exists (at 0) from registration, not first firing —
        # dashboards can tell "healthy" from "never evaluated"
        self._telemetry()["active"].set(0, rule=rule.name)
        return rule

    def add_rules(self, spec_or_rules) -> list:
        rules = (parse_rules(spec_or_rules)
                 if isinstance(spec_or_rules, str) else list(spec_or_rules))
        return [self.add_rule(r) for r in rules]

    def remove_rule(self, name):
        with self._lock:
            self.rules.pop(str(name), None)
            self.active.pop(str(name), None)

    # -- evaluation ----------------------------------------------------------
    def evaluate(self, now=None) -> list:
        """Evaluate every rule at ``now``; returns the transitions made
        (``[{rule, action, value, t}, ...]``)."""
        h = self.history()
        now = h.now() if now is None else float(now)
        tele = self._telemetry()
        out = []
        with self._lock:
            rules = list(self.rules.values())
        for rule in rules:
            try:
                breached = rule.breached(h, now)
                val = rule.value(h, now)
            except Exception:      # a broken rule must not kill the tick
                continue
            with self._lock:
                was = rule.name in self.active
                if breached and not was:
                    self.active[rule.name] = {
                        "since": now, "severity": rule.severity,
                        "value": val, "rule": rule.describe()}
                    tr = {"rule": rule.name, "action": "fired",
                          "severity": rule.severity, "value": val,
                          "t": now, "wall": time.time()}
                elif not breached and was:
                    ent = self.active.pop(rule.name)
                    tr = {"rule": rule.name, "action": "cleared",
                          "severity": rule.severity, "value": val,
                          "t": now, "wall": time.time(),
                          "active_s": now - ent["since"]}
                else:
                    if was:
                        self.active[rule.name]["value"] = val
                    continue
                self.transitions.append(tr)
                del self.transitions[:-64]
            out.append(tr)
            if tr["action"] == "fired":
                tele["fired"].inc(rule=rule.name, severity=rule.severity)
                tele["active"].set(1, rule=rule.name)
            else:
                tele["active"].set(0, rule=rule.name)
            from . import flight_recorder
            flight_recorder.record_event(
                "alert", rule=rule.name, action=tr["action"],
                severity=rule.severity,
                value=None if val is None else float(val))
        return out

    def _on_tick(self, history, now):
        self.evaluate(now=now)

    def attach(self, history=None):
        """Evaluate on every history tick (idempotent)."""
        h = history if history is not None else self.history()
        self._history = h
        if self._attached is not h:
            h.add_tick_observer(self._on_tick)
            self._attached = h
        return self

    def detach(self):
        if self._attached is not None:
            self._attached.remove_tick_observer(self._on_tick)
            self._attached = None

    # -- observability -------------------------------------------------------
    def state(self) -> dict:
        """The ``alerts`` state-provider payload (watchdog dumps and
        the fleet console)."""
        with self._lock:
            return {
                "rules": [r.describe() for r in self.rules.values()],
                "active": {n: dict(e) for n, e in self.active.items()},
                "recent_transitions": list(self.transitions[-16:]),
            }


_ENGINE: "AlertEngine | None" = None
_ENGINE_LOCK = threading.Lock()


def get_alert_engine() -> AlertEngine:
    """The process-global engine: attached to the global history,
    seeded from ``PADDLE_ALERT_RULES`` (if set), and registered as the
    ``alerts`` state provider so active alerts ride into every
    watchdog dump."""
    global _ENGINE
    if _ENGINE is None:
        with _ENGINE_LOCK:
            if _ENGINE is None:
                eng = AlertEngine()
                spec = os.environ.get("PADDLE_ALERT_RULES")
                if spec:
                    eng.add_rules(spec)
                eng.attach()
                from . import flight_recorder
                flight_recorder.register_state_provider(
                    "alerts", eng.state)
                _ENGINE = eng
    return _ENGINE


def reset_alert_engine() -> None:
    """Drop the global engine (tests / between jobs)."""
    global _ENGINE
    with _ENGINE_LOCK:
        if _ENGINE is not None:
            _ENGINE.detach()
            from . import flight_recorder
            flight_recorder.unregister_state_provider("alerts")
            _ENGINE = None


def active_alerts() -> dict:
    """``paddle.profiler.active_alerts()`` — {rule: entry} currently
    firing (empty when no engine was ever built)."""
    if _ENGINE is None:
        return {}
    with _ENGINE._lock:
        return {n: dict(e) for n, e in _ENGINE.active.items()}
