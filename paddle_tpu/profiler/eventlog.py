"""Correlated structured event log — the missing "logs" pillar of the
telemetry plane (ISSUE 15; metrics live in :mod:`.telemetry`, traces in
:mod:`.request_trace`, and this module gives every lifecycle edge a
durable, greppable line that outlives the process).

One append-only JSONL file per process: flight-recorder events
(replica deaths, controller actions, alert firings — everything routed
through :func:`~.flight_recorder.record_event`), request-trace
spans/edges (admission, route, requeue, delivered — teed from
:func:`~.request_trace.add_span`), and ledger divergences, all with
uniform correlation fields:

* ``ts`` — wall-clock seconds (the cross-replica join key);
* ``rank`` / ``replica`` — who wrote it (thread-sim rank aware);
* ``kind`` — the event name (``route``, ``requeue``, ``delivered``,
  ``fleet_replica_dead``, ``controller``, ``alert``,
  ``ledger_divergence``, ...);
* ``trace_id`` — when the event belongs to a request, so one request's
  whole story is reconstructable across every replica's log after the
  processes are gone (``tools/log_query.py --trace <id>``).

Durability discipline: each record goes down in a **single**
``os.write`` on an ``O_APPEND`` fd, so concurrent writers (threads here,
processes in the one-process-per-replica future) interleave only whole
lines, never torn ones. Size-based rotation (``PADDLE_EVENTLOG_MAX_MB``,
default 64, 0 disables) moves the full file to ``<path>.1`` via atomic
``os.replace`` before the append that would overflow it.

Zero overhead disabled: :func:`log_event` is a plain bool check when the
layer is off. ``PADDLE_EVENTLOG=<path>`` enables at import.
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = [
    "EventLog", "get_event_log", "enable", "disable", "is_enabled",
    "reset", "log_event", "EVENTLOG_SCHEMA", "DEFAULT_EVENTLOG_MAX_MB",
]

EVENTLOG_SCHEMA = "paddle_eventlog/1"
DEFAULT_EVENTLOG_MAX_MB = 64.0

_ENABLED = False
_LOG: "EventLog | None" = None
_MODULE_LOCK = threading.Lock()
_TELE = None


def _telemetry():
    global _TELE
    if _TELE is None:
        from .telemetry import get_registry
        r = get_registry()
        _TELE = {
            "records": r.counter(
                "paddle_eventlog_records_total",
                "structured events appended to the event log"),
            "rotations": r.counter(
                "paddle_eventlog_rotations_total",
                "size-triggered event-log rotations (full file moved "
                "to <path>.1)"),
        }
    return _TELE


def _env_max_mb():
    try:
        return float(os.environ.get("PADDLE_EVENTLOG_MAX_MB",
                                    str(DEFAULT_EVENTLOG_MAX_MB)))
    except ValueError:
        return DEFAULT_EVENTLOG_MAX_MB


class EventLog:
    """One append-only JSONL event log (single-``os.write`` lines on an
    ``O_APPEND`` fd, atomic size-based rotation)."""

    def __init__(self, path, max_mb=None):
        self.path = str(path)
        self.max_bytes = int((_env_max_mb() if max_mb is None
                              else float(max_mb)) * (1 << 20))
        self._lock = threading.Lock()
        self._fd = None
        self.records = 0
        self.rotations = 0
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)

    # -- internals -----------------------------------------------------------
    def _open_locked(self):
        if self._fd is None:
            self._fd = os.open(self.path,
                               os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                               0o644)
        return self._fd

    def _rotate_locked(self):
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None
        try:
            os.replace(self.path, f"{self.path}.1")
            self.rotations += 1
            _telemetry()["rotations"].inc()
        except OSError:
            pass               # raced with another rotator: append fresh

    # -- API -----------------------------------------------------------------
    def append(self, kind, trace_id=None, replica=None, rank=None,
               **fields) -> dict:
        """Append one structured event; returns the record written."""
        rec = {"ts": time.time(), "kind": str(kind)}
        if rank is None:
            from .flight_recorder import _rank
            rank = _rank()
        rec["rank"] = rank
        if replica is not None:
            rec["replica"] = str(replica)
        if trace_id is not None:
            rec["trace_id"] = str(trace_id)
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        line = (json.dumps(rec, default=str) + "\n").encode()
        with self._lock:
            if self.max_bytes > 0:
                try:
                    if (os.path.getsize(self.path) + len(line)
                            > self.max_bytes):
                        self._rotate_locked()
                except OSError:
                    pass           # no file yet: the open below creates it
            os.write(self._open_locked(), line)
            self.records += 1
        _telemetry()["records"].inc()
        return rec

    def close(self):
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None


# ---------------------------------------------------------------------------
# module facade (a plain bool check when the layer is off)
# ---------------------------------------------------------------------------


def get_event_log() -> "EventLog | None":
    return _LOG


def is_enabled() -> bool:
    return _ENABLED


def enable(path=None, max_mb=None) -> EventLog:
    """Open the process event log at ``path`` (default: the
    ``PADDLE_EVENTLOG`` env knob) and start teeing events into it."""
    global _ENABLED, _LOG
    if path is None:
        path = os.environ.get("PADDLE_EVENTLOG")
    if not path:
        raise ValueError("eventlog.enable() needs a path (or the "
                         "PADDLE_EVENTLOG env knob)")
    with _MODULE_LOCK:
        if _LOG is None or _LOG.path != str(path):
            if _LOG is not None:
                _LOG.close()
            _LOG = EventLog(path, max_mb=max_mb)
        _ENABLED = True
    return _LOG


def disable():
    global _ENABLED
    _ENABLED = False
    with _MODULE_LOCK:
        if _LOG is not None:
            _LOG.close()


def reset():
    """Drop the global log (tests / between jobs)."""
    global _ENABLED, _LOG
    with _MODULE_LOCK:
        if _LOG is not None:
            _LOG.close()
        _LOG = None
        _ENABLED = False


def log_event(kind, trace_id=None, replica=None, **fields):
    """The wired call site: one appended record IF the layer is enabled
    (plain bool check when off — the disabled path costs nothing)."""
    if not _ENABLED:
        return None
    log = _LOG
    if log is None:
        return None
    try:
        return log.append(kind, trace_id=trace_id, replica=replica,
                          **fields)
    except Exception:          # a full disk must never kill the caller
        return None


if os.environ.get("PADDLE_EVENTLOG"):   # pragma: no cover
    enable()
