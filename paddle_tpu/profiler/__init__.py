"""paddle.profiler (reference: ``python/paddle/profiler/profiler.py`` —
``Profiler(targets, scheduler, on_trace_ready)``, ``make_scheduler`` step
windows, ``RecordEvent`` annotations, chrome-trace export, summary tables,
``benchmark()`` ips timer; C++ side host tracer + CUPTI — SURVEY.md §5.1).

TPU-native: device/kernel timelines come from ``jax.profiler`` (XPlane →
TensorBoard/Perfetto — the CUPTI analogue); host-side per-op wall times come
from the eager tape's dispatch hook, giving the op summary table without a
native tracer. ``RecordEvent`` maps to ``jax.profiler.TraceAnnotation`` so
user annotations show up inside the device trace.
"""
from __future__ import annotations

import contextlib
import enum
import json
import os
import time
from collections import defaultdict

import jax

from .telemetry import (  # noqa: F401  (re-exported facade)
    MetricRegistry, SpanTracer, Span, get_registry, get_tracer,
    metrics, metrics_text, enable_op_telemetry, disable_op_telemetry,
    op_telemetry, spans_to_chrome,
)
from . import flight_recorder  # noqa: F401
from .flight_recorder import (  # noqa: F401  (re-exported facade)
    FlightRecorder, Watchdog, get_flight_recorder, gather_metrics,
    publish_snapshot, publish_component_state, gather_component_states,
    merge_chrome_traces, merge_rank_snapshots,
    desync_report, straggler_report,
)
from . import request_trace  # noqa: F401
from .request_trace import (  # noqa: F401  (re-exported facade)
    TraceContext, RequestTraceStore, SLOMonitor, start_request,
    finish_request, request_timeline, recent_timelines,
    timeline_to_chrome, get_slo_monitor, reset_slo_monitor, slo_report,
    cost_table, get_trace_store,
)
from . import timeseries  # noqa: F401
from .timeseries import (  # noqa: F401  (re-exported facade)
    MetricsHistory, get_history, history, history_tick,
)
from . import alerts  # noqa: F401
from .alerts import (  # noqa: F401  (re-exported facade)
    AlertEngine, AlertRule, ThresholdRule, BurnRateRule,
    get_alert_engine, active_alerts,
)
from . import step_phase  # noqa: F401
from . import memory  # noqa: F401
from .memory import (  # noqa: F401  (re-exported facade)
    MemoryTimeline, module_breakdown, register_model_breakdown,
)
from . import tensor_stats  # noqa: F401
from .tensor_stats import (  # noqa: F401  (re-exported facade)
    NumericsSentinel, NonFiniteGradError, get_sentinel,
)
from . import ledger  # noqa: F401
from .ledger import (  # noqa: F401  (re-exported facade)
    StepLedger, DivergenceError, get_ledger, tensor_digest,
    first_divergence, publish_ledger, gather_ledgers, compare_store,
    export_golden,
)
from . import exporter  # noqa: F401
from .exporter import (  # noqa: F401  (re-exported facade)
    TelemetryServer, maybe_start_exporter, exporter_enabled,
)
from . import scrape  # noqa: F401
from .scrape import (  # noqa: F401  (re-exported facade)
    FleetScraper, fleet_metrics, fleet_metrics_text, parse_metrics_text,
    start_fleet_scraper, stop_fleet_scraper, get_fleet_scraper,
)
from . import eventlog  # noqa: F401
from .eventlog import (  # noqa: F401  (re-exported facade)
    EventLog, log_event, get_event_log,
)
from . import compile_observatory  # noqa: F401
from .compile_observatory import (  # noqa: F401  (re-exported facade)
    CompileObservatory, get_observatory,
)

__all__ = [
    "Profiler", "ProfilerTarget", "ProfilerState", "make_scheduler",
    "export_chrome_tracing", "export_protobuf", "RecordEvent", "load_profiler_result",
    "benchmark", "comm_stats",
    "MetricRegistry", "SpanTracer", "get_registry", "get_tracer",
    "metrics", "metrics_text", "enable_op_telemetry", "disable_op_telemetry",
    "FlightRecorder", "Watchdog", "get_flight_recorder", "gather_metrics",
    "publish_snapshot", "publish_component_state",
    "gather_component_states", "merge_chrome_traces",
    "merge_rank_snapshots", "desync_report", "straggler_report",
    "TraceContext", "RequestTraceStore", "SLOMonitor", "start_request",
    "finish_request", "request_timeline", "recent_timelines",
    "timeline_to_chrome", "get_slo_monitor", "reset_slo_monitor",
    "slo_report", "cost_table", "get_trace_store",
    "MetricsHistory", "get_history", "history", "history_tick",
    "AlertEngine", "AlertRule", "ThresholdRule", "BurnRateRule",
    "get_alert_engine", "active_alerts",
    "step_phase", "memory", "tensor_stats", "ledger",
    "MemoryTimeline", "module_breakdown", "register_model_breakdown",
    "NumericsSentinel", "NonFiniteGradError", "get_sentinel",
    "StepLedger", "DivergenceError", "get_ledger", "tensor_digest",
    "first_divergence", "publish_ledger", "gather_ledgers",
    "compare_store", "export_golden",
    "exporter", "scrape", "eventlog",
    "TelemetryServer", "maybe_start_exporter", "exporter_enabled",
    "FleetScraper", "fleet_metrics", "fleet_metrics_text",
    "parse_metrics_text", "start_fleet_scraper", "stop_fleet_scraper",
    "get_fleet_scraper", "EventLog", "log_event", "get_event_log",
    "compile_observatory", "CompileObservatory", "get_observatory",
]


def comm_stats(reset=False):
    """Snapshot of the gradient-communication counters
    (``distributed.comm.CommStats``): collective calls, logical vs wire
    bytes, compression ratio, max quantization error. ``reset=True``
    zeroes the counters after reading (per-window accounting)."""
    from ..distributed.comm import get_comm_stats, reset_comm_stats
    d = get_comm_stats().as_dict()
    if reset:
        reset_comm_stats()
    return d


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1          # alias: the accelerator
    TPU = 1
    CUSTOM_DEVICE = 2


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    """Step-window state machine (reference ``make_scheduler``): per cycle,
    ``closed`` steps off, ``ready`` steps warming, ``record`` steps on;
    repeated ``repeat`` times (0 = forever), after ``skip_first`` steps."""
    cycle = closed + ready + record
    assert cycle > 0

    def scheduler(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def _default_scheduler(step):
    return ProfilerState.RECORD


def export_chrome_tracing(dir_name, worker_name=None):
    """on_trace_ready callback: dump the recorded spans as a
    chrome-tracing JSON next to the jax xplane dump. Events carry REAL
    per-span begin timestamps, durations and per-thread ``tid`` from the
    span tracer (readable in Perfetto) — not a fabricated sequential
    timeline from cumulative op totals."""
    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"worker_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}.pt.trace.json")
        events = spans_to_chrome(prof._drain_spans())
        if not events:
            # timer_only / span-less window: fall back to the op summary
            # (still one event per op, zero-based synthetic timeline,
            # flagged as such so consumers can tell)
            t = 0
            for op, (cnt, total) in sorted(prof._op_stats.items()):
                events.append({"name": op, "ph": "X", "pid": 0, "tid": 0,
                               "ts": t, "dur": max(total * 1e6, 1),
                               "args": {"calls": cnt, "synthetic_ts": True}})
                t += max(total * 1e6, 1)
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)
        prof._exported_path = path
    return handler


def export_protobuf(dir_name, worker_name=None):
    return export_chrome_tracing(dir_name, worker_name)


class RecordEvent:
    """User annotation: a real nested span in the host trace (span tracer:
    wall-clock begin/duration, thread id, parent linkage) plus a
    TraceAnnotation in the device trace. Usable as context manager or
    begin()/end()."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ann = None
        self._t0 = None
        self._span = None

    def begin(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        self._span = get_tracer().begin(self.name, kind="user")
        prof = Profiler._current
        if prof is not None and prof._recording:
            prof._open_events.append(self)

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None
        if self._span is not None:
            get_tracer().end(self._span)
            self._span = None
        prof = Profiler._current
        if prof is not None and prof._recording and self._t0 is not None:
            dt = time.perf_counter() - self._t0
            cnt, total = prof._op_stats[f"user::{self.name}"]
            prof._op_stats[f"user::{self.name}"] = (cnt + 1, total + dt)

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *a):
        self.end()


class Profiler:
    """paddle.profiler.Profiler facade.

    with Profiler(targets=[ProfilerTarget.CPU, ProfilerTarget.GPU],
                  scheduler=make_scheduler(closed=1, ready=1, record=2),
                  on_trace_ready=export_chrome_tracing('./log')) as p:
        for batch in loader:
            train_step(batch)
            p.step()
    p.summary()
    """

    _current = None

    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False, emit_nvtx=False):
        if callable(scheduler):
            self._scheduler = scheduler
        elif isinstance(scheduler, (tuple, list)) and len(scheduler) == 2:
            lo, hi = scheduler
            self._scheduler = make_scheduler(closed=lo, ready=0,
                                             record=hi - lo, repeat=1)
        else:
            self._scheduler = _default_scheduler
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self.targets = targets or [ProfilerTarget.CPU]
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._recording = False
        self._op_stats = defaultdict(lambda: (0, 0.0))
        self._open_events = []
        self._step_times = []
        self._t_step = None
        self._jax_tracing = False
        self._trace_dir = None
        self._exported_path = None
        self._spans = []

    # -- tape hook ----------------------------------------------------------
    def _record_op(self, op_name, dt):
        cnt, total = self._op_stats[op_name]
        self._op_stats[op_name] = (cnt + 1, total + dt)
        tracer = get_tracer()
        if tracer.enabled:
            tracer.add_complete(op_name, dt, kind="op")

    def _drain_spans(self):
        """Spans recorded since the last drain (tracer + carried-over)."""
        self._spans.extend(get_tracer().drain())
        out, self._spans = self._spans, []
        return out

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        Profiler._current = self
        # session hygiene: a prior profiler with no on_trace_ready leaves
        # its completed spans queued in the process-global tracer — this
        # session's exports must not inherit them
        get_tracer().drain()
        self._spans = []
        from ..autograd import tape
        tape._profiler = self
        self._transition(self._scheduler(self._step))
        self._t_step = time.perf_counter()
        return self

    def stop(self):
        if self._state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._stop_recording()
            if self._on_trace_ready:
                self._on_trace_ready(self)
        from ..autograd import tape
        tape._profiler = None
        Profiler._current = None
        self._state = ProfilerState.CLOSED

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._t_step is not None:
            self._step_times.append((now - self._t_step, num_samples))
        self._t_step = now
        self._step += 1
        new = self._scheduler(self._step)
        # fire once per RETURNING step, not once per state CHANGE: a
        # scheduler yielding RECORD_AND_RETURN on consecutive steps must
        # export each completed window, not silently skip all but the
        # first (each export drains the spans/ops of its own window)
        ret = self._state == ProfilerState.RECORD_AND_RETURN
        if new != self._state:
            self._transition(new)
        if ret and self._on_trace_ready:
            self._on_trace_ready(self)

    def _transition(self, new):
        rec_states = (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        was = self._state in rec_states
        want = new in rec_states
        if want and not was:
            self._start_recording()
        elif was and not want:
            self._stop_recording()
        self._state = new

    def _start_recording(self):
        self._recording = True
        get_tracer().enable()
        if not self._timer_only and any(t != ProfilerTarget.CPU
                                        for t in self.targets):
            self._trace_dir = os.environ.get("PADDLE_PROFILER_XPLANE_DIR",
                                             "/tmp/paddle_tpu_xplane")
            try:
                jax.profiler.start_trace(self._trace_dir)
                self._jax_tracing = True
            except (RuntimeError, ValueError):
                self._jax_tracing = False

    def _stop_recording(self):
        self._recording = False
        tracer = get_tracer()
        if tracer.enabled:
            tracer.disable()
            # completed spans of this window stay queued in the tracer
            # until the export handler (or the next one) drains them
        if self._jax_tracing:
            try:
                jax.profiler.stop_trace()
            except (RuntimeError, ValueError):
                pass
            self._jax_tracing = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *a):
        self.stop()

    # -- reporting ----------------------------------------------------------
    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        unit = {"s": 1.0, "ms": 1e3, "us": 1e6}[time_unit]
        lines = ["-" * 64,
                 f"{'Name':<36}{'Calls':>8}{'Total(' + time_unit + ')':>14}",
                 "-" * 64]
        for op, (cnt, total) in sorted(self._op_stats.items(),
                                       key=lambda kv: -kv[1][1]):
            lines.append(f"{op:<36}{cnt:>8}{total * unit:>14.3f}")
        if self._step_times:
            times = [t for t, _ in self._step_times]
            lines.append("-" * 64)
            lines.append(f"steps: {len(times)}  avg step "
                         f"{sum(times) / len(times) * unit:.3f}{time_unit}")
        out = "\n".join(lines)
        print(out)
        return out

    @property
    def averages(self):
        return {op: total / max(cnt, 1)
                for op, (cnt, total) in self._op_stats.items()}


def load_profiler_result(path):
    with open(path) as f:
        return json.load(f)


class _Benchmark:
    """paddle.profiler.utils benchmark timer — reports ips (reference:
    Profiler.timer_only path / hapi ips metric)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._t0 = None
        self._samples = 0
        self._steps = 0
        self._elapsed = 0.0

    def begin(self):
        self.reset()
        self._t0 = time.perf_counter()

    def step(self, num_samples=None):
        self._steps += 1
        if num_samples:
            self._samples += num_samples

    def end(self):
        if self._t0 is not None:
            self._elapsed = time.perf_counter() - self._t0
            self._t0 = None            # timer stopped; elapsed is final

    def ips(self):
        # while the timer is RUNNING, throughput is live (elapsed up to
        # now) — the old implicit end() latched _elapsed on the first
        # read and every later ips() reported that stale window
        if self._t0 is not None:
            elapsed = time.perf_counter() - self._t0
        else:
            elapsed = self._elapsed
        denom = elapsed or 1e-9
        return (self._samples or self._steps) / denom

    def step_info(self, unit="samples"):
        return f"ips: {self.ips():.2f} {unit}/s"


_benchmark = _Benchmark()


def benchmark():
    return _benchmark
