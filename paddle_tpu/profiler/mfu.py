"""MFU accounting (SURVEY.md §7.1 M5 "MFU dashboard", §6 sanity anchors).

Model-flops utilization = achieved FLOP/s ÷ peak FLOP/s. Transformer FLOPs
use the standard 6·N·tokens fwd+bwd estimate plus the attention term
12·L·h·s²·(causal ½) — the same accounting the reference community uses for
Megatron/PaddleNLP MFU claims.
"""
from __future__ import annotations

import time

# bf16 peak FLOP/s per chip
PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
    "cpu": 1e12,          # nominal; for smoke runs only
}


def chip_kind(device=None):
    """Map a jax device to a PEAK_FLOPS key (e.g. 'TPU v5 lite' -> 'v5e')."""
    if device is None:
        import jax
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "") or ""
    k = kind.lower()
    if "v5 lite" in k or "v5e" in k or "v5litepod" in k:
        return "v5e"
    if "v5p" in k or "v5" in k:
        return "v5p"
    if "v6" in k:
        return "v6e"
    if "v4" in k:
        return "v4"
    return "cpu" if device.platform == "cpu" else "v5p"


def transformer_train_flops(num_params, tokens, num_layers=None,
                            hidden_size=None, seq_len=None, causal=True):
    """6·N·tokens (fwd 2N + bwd 4N) + attention 12·L·h·s²·b term."""
    total = 6.0 * num_params * tokens
    if num_layers and hidden_size and seq_len:
        batch_tokens = tokens / seq_len
        attn = 12.0 * num_layers * hidden_size * (seq_len ** 2) * batch_tokens
        if causal:
            attn *= 0.5
        total += attn
    return total


def llama_train_flops(config, batch, seq_len):
    """FLOPs of one train step of a Llama-config model."""
    n = llama_param_count(config)
    return transformer_train_flops(
        n, batch * seq_len, num_layers=config.num_hidden_layers,
        hidden_size=config.hidden_size, seq_len=seq_len)


def llama_param_count(config):
    h = config.hidden_size
    i = config.intermediate_size
    v = config.vocab_size
    kvh = config.num_key_value_heads * config.head_dim
    per_layer = (h * h + 2 * h * kvh + h * h    # q, k, v, o
                 + 3 * h * i                    # gate, up, down
                 + 2 * h)                       # norms
    n = config.num_hidden_layers * per_layer + v * h + h
    if not getattr(config, "tie_word_embeddings", False):
        n += v * h
    return n


class MFUMonitor:
    """Per-step MFU/throughput meter.

    monitor = MFUMonitor(step_flops=llama_train_flops(cfg, b, s),
                         chip="v5p", n_chips=64)
    for ...: step(); monitor.step(tokens=b*s)
    print(monitor.summary())
    """

    def __init__(self, step_flops, chip="v5p", n_chips=1, peak_flops=None):
        self.step_flops = float(step_flops)
        self.peak = (peak_flops if peak_flops is not None
                     else PEAK_FLOPS.get(chip, PEAK_FLOPS["v5p"])) * n_chips
        self.reset()

    def reset(self):
        self._t0 = time.perf_counter()
        self._steps = 0
        self._tokens = 0

    def step(self, tokens=0):
        self._steps += 1
        self._tokens += tokens

    @property
    def elapsed(self):
        return time.perf_counter() - self._t0

    def mfu(self):
        if not self._steps:
            return 0.0
        achieved = self.step_flops * self._steps / max(self.elapsed, 1e-9)
        return achieved / self.peak

    def tokens_per_sec(self):
        return self._tokens / max(self.elapsed, 1e-9)

    def summary(self):
        return (f"steps={self._steps} "
                f"tokens/s={self.tokens_per_sec():,.0f} "
                f"MFU={self.mfu() * 100:.1f}%")
