"""Fleet-wide scrape aggregation: one merged, instance-labeled registry
view over every replica's ``/metrics`` endpoint (ISSUE 15; the remote
twin of :func:`~.flight_recorder.gather_metrics` — same merge shape,
but over HTTP against live processes instead of KV snapshots).

:class:`FleetScraper` discovers endpoints from the elastic KV store
(``keys("fleet/telemetry/")`` over :class:`TelemetryServer` discovery
records — composes with ``MemKVStore`` and ``TcpKVStore`` alike) or
from a static ``{instance: "host:port"}`` map, scrapes each on an
interval through :func:`parse_metrics_text` (a **strict**
Prometheus-exposition parser — malformed bodies raise instead of
silently merging garbage), and:

* merges the per-instance families into one view with a leading
  ``instance`` label (:meth:`~FleetScraper.merged`,
  ``paddle.profiler.fleet_metrics()`` /
  :func:`fleet_metrics_text`);
* folds every scrape into a :class:`~.timeseries.MetricsHistory`
  (tick-per-scrape), so PR-11 burn-rate alert rules evaluate over the
  *fleet* view exactly as they do over the in-process one;
* degrades gracefully: a dead endpoint is marked **stale** after
  ``PADDLE_TELEMETRY_STALE_S`` seconds without a successful scrape
  (ticking the ``paddle_telemetry_stale_instances`` gauge and dropping
  it from the merged view), never blocks the loop (per-endpoint
  timeout), and recovers the moment the endpoint answers again.

Module-level imports here are stdlib-only on purpose:
``tools/fleet_console.py --scrape`` loads this file standalone (no
paddle_tpu / jax import) for its live-fleet mode.
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
import urllib.request

__all__ = [
    "FleetScraper", "parse_metrics_text", "render_metrics_text",
    "merge_instances", "fleet_metrics", "fleet_metrics_text",
    "fetch_compile", "merge_compile_snapshots",
    "start_fleet_scraper", "stop_fleet_scraper", "get_fleet_scraper",
    "DEFAULT_STALE_S", "DEFAULT_SCRAPE_INTERVAL_S",
]

DEFAULT_STALE_S = 10.0
DEFAULT_SCRAPE_INTERVAL_S = 2.0

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(\S+)$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return (v.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def _parse_value(raw: str) -> float:
    low = raw.lower()
    if low in ("+inf", "inf"):
        return float("inf")
    if low == "-inf":
        return float("-inf")
    return float(raw)          # strict: ValueError propagates


def parse_metrics_text(text: str) -> dict:
    """STRICT Prometheus-exposition parser -> the
    ``MetricRegistry.collect()`` shape: ``{name: {type, help,
    label_names, series: {label_key: value | histogram_snapshot}}}``.

    Strictness contract (the acceptance round-trip leans on it):
    every sample line must parse, every sampled family must carry a
    ``# TYPE`` declaration, label names must be consistent inside a
    family, and histogram ``_bucket``/``_sum``/``_count`` lines must
    belong to a declared histogram. Violations raise ``ValueError``.
    """
    families: dict = {}
    types: dict = {}
    helps: dict = {}

    def base_name(name):
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and \
                    types.get(name[:-len(suffix)]) == "histogram":
                return name[:-len(suffix)], suffix
        return name, ""

    for lineno, line in enumerate(text.splitlines(), start=1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                types[parts[2]] = parts[3] if len(parts) > 3 else "untyped"
            elif len(parts) >= 3 and parts[1] == "HELP":
                helps[parts[2]] = parts[3] if len(parts) > 3 else ""
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: malformed sample {line!r}")
        raw_name, _, raw_labels, raw_value = m.groups()
        value = _parse_value(raw_value)
        name, suffix = base_name(raw_name)
        kind = types.get(name)
        if kind is None:
            raise ValueError(f"line {lineno}: sample {raw_name!r} has no "
                             f"# TYPE declaration")
        labels = []
        if raw_labels:
            consumed = 0
            for lm in _LABEL_RE.finditer(raw_labels):
                labels.append((lm.group(1), _unescape(lm.group(2))))
                consumed = lm.end()
            leftover = raw_labels[consumed:].strip().strip(",")
            if leftover:
                raise ValueError(f"line {lineno}: malformed labels "
                                 f"{raw_labels!r}")
        le = None
        if kind == "histogram" and suffix == "_bucket":
            le_pairs = [v for k, v in labels if k == "le"]
            if not le_pairs:
                raise ValueError(f"line {lineno}: histogram bucket "
                                 f"without le label")
            le = le_pairs[0]
            labels = [(k, v) for k, v in labels if k != "le"]
        label_names = [k for k, _ in labels]
        fam = families.setdefault(name, {
            "type": kind, "help": helps.get(name, ""),
            "label_names": label_names, "series": {},
        })
        if fam["label_names"] != label_names:
            raise ValueError(
                f"line {lineno}: inconsistent label names for {name!r}: "
                f"{label_names} vs {fam['label_names']}")
        key = ",".join(v for _, v in labels)
        if kind == "histogram":
            snap = fam["series"].setdefault(
                key, {"count": 0, "sum": 0.0, "buckets": {}})
            if suffix == "_bucket":
                snap["buckets"]["+Inf" if le in ("+Inf", "inf")
                                else le] = value
            elif suffix == "_sum":
                snap["sum"] = value
            elif suffix == "_count":
                snap["count"] = value
            else:
                raise ValueError(f"line {lineno}: bare sample "
                                 f"{raw_name!r} for histogram {name!r}")
        else:
            fam["series"][key] = value
    return families


def render_metrics_text(families: dict) -> str:
    """The inverse of :func:`parse_metrics_text`: a ``collect()``-shaped
    dict back to Prometheus text exposition (the merged fleet view as
    one scrapeable body)."""
    lines = []
    for name in sorted(families):
        fam = families[name]
        kind = fam.get("type", "untyped")
        lines.append(f"# HELP {name} {fam.get('help') or name}")
        lines.append(f"# TYPE {name} {kind}")
        label_names = list(fam.get("label_names", []))
        for key in sorted(fam.get("series", {})):
            val = fam["series"][key]
            values = key.split(",") if key else []
            labelstr = _fmt_labels(label_names, values)
            if isinstance(val, dict):       # histogram snapshot
                buckets = val.get("buckets", {})

                def _b(b):
                    try:
                        return (0, float(b))
                    except ValueError:
                        return (1, float("inf"))
                for b in sorted(buckets, key=_b):
                    ls = _fmt_labels(label_names + ["le"], values + [b])
                    lines.append(f"{name}_bucket{ls} {buckets[b]:g}")
                lines.append(f"{name}_sum{labelstr} "
                             f"{val.get('sum', 0.0):g}")
                lines.append(f"{name}_count{labelstr} "
                             f"{val.get('count', 0):g}")
            else:
                lines.append(f"{name}{labelstr} {val:g}")
    return "\n".join(lines) + ("\n" if lines else "")


def _fmt_labels(names, values) -> str:
    if not names:
        return ""
    def esc(v):
        return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
                .replace('"', '\\"'))
    inner = ",".join(f'{n}="{esc(v)}"' for n, v in zip(names, values))
    return "{" + inner + "}"


def merge_instances(by_instance: dict) -> dict:
    """Union per-instance family dicts into ONE view: every family gains
    a leading ``instance`` label (the
    :func:`~.flight_recorder.merge_rank_snapshots` convention, keyed by
    endpoint instance instead of rank)."""
    merged: dict = {}
    for instance in sorted(by_instance):
        for name, fam in (by_instance[instance] or {}).items():
            m = merged.setdefault(name, {
                "type": fam.get("type", "untyped"),
                "help": fam.get("help", ""),
                "label_names": ["instance"]
                + list(fam.get("label_names", [])),
                "series": {},
            })
            for key, val in fam.get("series", {}).items():
                m["series"][f"{instance},{key}" if key
                            else str(instance)] = val
    return merged


def fetch_metrics(endpoint: str, timeout_s=2.0) -> dict:
    """GET ``http://<endpoint>/metrics`` and strictly parse the body."""
    with urllib.request.urlopen(f"http://{endpoint}/metrics",
                                timeout=timeout_s) as resp:
        body = resp.read().decode("utf-8", errors="replace")
    return parse_metrics_text(body)


def fetch_compile(endpoint: str, timeout_s=2.0) -> dict:
    """GET ``http://<endpoint>/compile`` — one instance's
    compile-observatory snapshot (per-family hit/miss/compile-seconds
    plus recent retrace causes)."""
    with urllib.request.urlopen(f"http://{endpoint}/compile",
                                timeout=timeout_s) as resp:
        body = resp.read().decode("utf-8", errors="replace")
    return json.loads(body)


def merge_compile_snapshots(by_instance: dict) -> dict:
    """Fold per-instance ``/compile`` snapshots into one fleet rollup:
    per-family hits/misses/compile seconds summed across instances,
    recent causes and undeclared families unioned (with the reporting
    instances attached — a family drifting on ONE replica must stay
    visible in the fleet view)."""
    families: dict = {}
    undeclared: dict = {}
    totals = {"hits": 0, "misses": 0, "compile_s": 0.0}
    for instance in sorted(by_instance):
        snap = by_instance[instance] or {}
        for fam in snap.get("undeclared", ()):
            undeclared.setdefault(str(fam), []).append(str(instance))
        for name, f in (snap.get("families") or {}).items():
            m = families.setdefault(name, {
                "hits": 0, "misses": 0, "compile_s": 0.0,
                "signatures": 0, "instances": [], "last_causes": [],
            })
            m["hits"] += int(f.get("hits", 0))
            m["misses"] += int(f.get("misses", 0))
            m["compile_s"] += float(f.get("compile_s", 0.0))
            m["signatures"] += int(f.get("signatures", 0))
            m["instances"].append(str(instance))
            for c in (f.get("last_causes") or [])[-4:]:
                m["last_causes"].append(
                    {"instance": str(instance), **c}
                    if isinstance(c, dict)
                    else {"instance": str(instance), "cause": c})
        t = snap.get("totals") or {}
        totals["hits"] += int(t.get("hits", 0))
        totals["misses"] += int(t.get("misses", 0))
        totals["compile_s"] += float(t.get("compile_s", 0.0))
    return {"instances": sorted(by_instance), "families": families,
            "undeclared": undeclared, "totals": totals}


class _MergedView:
    """Registry shim the fold-in :class:`MetricsHistory` samples: its
    ``collect()`` is the scraper's merged fleet view."""

    def __init__(self, scraper):
        self._scraper = scraper

    def collect(self):
        return self._scraper.merged()

    def __getattr__(self, name):
        # counter/gauge/histogram creation (the history's own
        # bookkeeping metrics) falls through to the process registry
        from .telemetry import get_registry
        return getattr(get_registry(), name)


class FleetScraper:
    """Discover + scrape + merge + fold. ``store=`` drives KV discovery;
    ``endpoints={instance: "host:port"}`` is the static tier (both can
    coexist — static entries win on collision)."""

    def __init__(self, store=None, key_prefix=None, endpoints=None,
                 interval_s=None, stale_s=None, timeout_s=1.0,
                 history=None, history_capacity=1024):
        self.store = store
        if key_prefix is None:
            key_prefix = "fleet/telemetry/"
        self.key_prefix = str(key_prefix)
        self.static_endpoints = dict(endpoints or {})
        if interval_s is None:
            interval_s = _env_float("PADDLE_TELEMETRY_SCRAPE_INTERVAL_S",
                                    DEFAULT_SCRAPE_INTERVAL_S)
        self.interval_s = float(interval_s)
        if stale_s is None:
            stale_s = _env_float("PADDLE_TELEMETRY_STALE_S",
                                 DEFAULT_STALE_S)
        self.stale_s = float(stale_s)
        self.timeout_s = float(timeout_s)
        self._lock = threading.RLock()
        self._snaps: dict = {}        # instance -> parsed families
        self._last_ok: dict = {}      # instance -> monotonic t of last ok
        self._errors: dict = {}       # instance -> last error repr
        self.scrapes = 0
        self._stop_evt = threading.Event()
        self._thread = None
        self._tele = None
        if history is None:
            from .timeseries import MetricsHistory
            history = MetricsHistory(capacity=history_capacity,
                                     interval_s=0,
                                     registry=_MergedView(self))
        self.history = history

    def _telemetry(self):
        if self._tele is None:
            from .telemetry import get_registry
            r = get_registry()
            self._tele = {
                "stale": r.gauge(
                    "paddle_telemetry_stale_instances",
                    "discovered telemetry endpoints with no successful "
                    "scrape inside PADDLE_TELEMETRY_STALE_S"),
                "scrapes": r.counter(
                    "paddle_telemetry_scrapes_total",
                    "endpoint scrape attempts, by outcome",
                    labels=("outcome",)),
            }
        return self._tele

    # -- discovery -----------------------------------------------------------
    def discover(self) -> dict:
        """{instance: "host:port"} from the KV store plus the static
        map (static wins)."""
        found: dict = {}
        if self.store is not None:
            try:
                keys = self.store.keys(self.key_prefix)
            except Exception:
                keys = []
            for key in keys:
                try:
                    v = self.store.get(key)
                except Exception:
                    continue
                state = (v or {}).get("state") if isinstance(v, dict) \
                    else None
                if not isinstance(state, dict):
                    continue
                host, port = state.get("host"), state.get("port")
                if host is None or port is None:
                    continue
                instance = state.get("instance") \
                    or key[len(self.key_prefix):]
                found[str(instance)] = f"{host}:{port}"
        found.update(self.static_endpoints)
        return found

    # -- scraping ------------------------------------------------------------
    def scrape_once(self, now=None) -> dict:
        """One scrape round over every discovered endpoint. Per-endpoint
        failures never raise (and never block past ``timeout_s``); the
        round always finishes for the survivors. Returns
        ``{instance: "ok" | "error"}``."""
        now = time.monotonic() if now is None else float(now)
        tele = self._telemetry()
        targets = self.discover()
        outcome = {}
        for instance, endpoint in sorted(targets.items()):
            try:
                families = fetch_metrics(endpoint,
                                         timeout_s=self.timeout_s)
            except Exception as e:
                outcome[instance] = "error"
                tele["scrapes"].inc(outcome="error")
                with self._lock:
                    self._errors[instance] = repr(e)
                continue
            outcome[instance] = "ok"
            tele["scrapes"].inc(outcome="ok")
            with self._lock:
                self._snaps[instance] = families
                self._last_ok[instance] = now
                self._errors.pop(instance, None)
        with self._lock:
            self.scrapes += 1
            known = set(targets) | set(self._last_ok)
            stale = [i for i in known
                     if now - self._last_ok.get(i, -1e18) > self.stale_s]
        tele["stale"].set(len(stale))
        # fold the fleet view into the history on the scrape timeline —
        # burn-rate rules attached to self.history now see fleet series
        try:
            self.history.tick(now=now)
        except Exception:
            pass
        return outcome

    def instances(self, now=None) -> dict:
        """{instance: {endpoint, stale, age_s, error}} — the liveness
        table the fleet console renders."""
        now = time.monotonic() if now is None else float(now)
        targets = self.discover()
        out = {}
        with self._lock:
            for instance in sorted(set(targets) | set(self._last_ok)):
                last = self._last_ok.get(instance)
                age = None if last is None else now - last
                out[instance] = {
                    "endpoint": targets.get(instance),
                    "age_s": None if age is None else round(age, 3),
                    "stale": age is None or age > self.stale_s,
                    "error": self._errors.get(instance),
                }
        return out

    def last_scrape_age(self, now=None) -> "float | None":
        """Seconds since the freshest successful scrape (the bench's
        ``telemetry_scrape_age_s`` aux metric); None before any."""
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            if not self._last_ok:
                return None
            return max(now - max(self._last_ok.values()), 0.0)

    # -- merged views --------------------------------------------------------
    def merged(self) -> dict:
        """Instance-labeled union of every FRESH instance's last scrape
        (stale instances drop out — their numbers are history, not
        state)."""
        now = time.monotonic()
        with self._lock:
            fresh = {i: snap for i, snap in self._snaps.items()
                     if now - self._last_ok.get(i, -1e18) <= self.stale_s}
        return merge_instances(fresh)

    def metrics_text(self) -> str:
        return render_metrics_text(self.merged())

    def compile_snapshots(self, now=None) -> dict:
        """Scrape every discovered endpoint's ``/compile`` route NOW
        (on demand — compile state changes on trace events, not on the
        metrics cadence). Returns ``{instance: snapshot}``; endpoints
        that fail to answer are skipped, never raise."""
        out = {}
        for instance, endpoint in sorted(self.discover().items()):
            try:
                out[instance] = fetch_compile(endpoint,
                                              timeout_s=self.timeout_s)
            except Exception as e:
                with self._lock:
                    self._errors[instance] = repr(e)
        return out

    def compile_merged(self) -> dict:
        """Fleet-wide compile rollup: :meth:`compile_snapshots` folded
        through :func:`merge_compile_snapshots`."""
        return merge_compile_snapshots(self.compile_snapshots())

    # -- background loop -----------------------------------------------------
    def start(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="paddle-fleet-scraper")
            self._thread.start()
        return self

    def stop(self):
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self):
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.scrape_once()
            except Exception:    # a scrape round must never kill the loop
                pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return float(default)


# ---------------------------------------------------------------------------
# module facade — paddle.profiler.fleet_metrics() / fleet_metrics_text()
# ---------------------------------------------------------------------------

_SCRAPER: "FleetScraper | None" = None
_SCRAPER_LOCK = threading.Lock()


def get_fleet_scraper() -> "FleetScraper | None":
    return _SCRAPER


def start_fleet_scraper(store=None, **kwargs) -> FleetScraper:
    """Build + start the process-global scraper (the one
    :func:`fleet_metrics` reads)."""
    global _SCRAPER
    with _SCRAPER_LOCK:
        if _SCRAPER is not None:
            _SCRAPER.stop()
        _SCRAPER = FleetScraper(store=store, **kwargs)
        _SCRAPER.start()
    return _SCRAPER


def stop_fleet_scraper():
    global _SCRAPER
    with _SCRAPER_LOCK:
        if _SCRAPER is not None:
            _SCRAPER.stop()
            _SCRAPER = None


def fleet_metrics() -> dict:
    """``paddle.profiler.fleet_metrics()`` — the merged instance-labeled
    fleet view from the global scraper (empty before one runs)."""
    s = _SCRAPER
    return {} if s is None else s.merged()


def fleet_metrics_text() -> str:
    """The merged fleet view in Prometheus text exposition format."""
    s = _SCRAPER
    return "" if s is None else s.metrics_text()
