"""Metric time-series: bounded history of the process-global registry
(ISSUE 11 — the sensing half of ROADMAP 4's autoscaling control plane).

The :class:`~.telemetry.MetricRegistry` is a point-in-time snapshot: it
can say *what the gauges read now*, never *how they moved through the
burst*. :class:`MetricsHistory` closes that gap — it samples the
registry on a background interval (``PADDLE_HISTORY_INTERVAL_S``) or on
an explicit, deterministic :meth:`~MetricsHistory.tick` (``tick(now=)``
in tests and replay harnesses), keeping a bounded ring of
``(timestamp, value)`` points per labeled series:

* counters / gauges sample their value; histograms expand to three
  derived series (``:count``, ``:sum``, ``:p95``) so both rate-style and
  latency-style questions have a timeline;
* :meth:`~MetricsHistory.rate` computes counter increase-per-second over
  a window with Prometheus-style **reset detection** (a process restart
  mid-history yields the post-restart increase, never a huge negative
  rate);
* :meth:`~MetricsHistory.window` gives min / mean / max / exact-p95 over
  the points inside a time window — the primitive the alert rules
  (:mod:`.alerts`) and the replay report
  (``inference/fleet/replay.py``) are built on;
* :meth:`~MetricsHistory.export_jsonl` writes a self-describing JSONL
  file ``tools/fleet_console.py`` renders without importing jax, and
  :meth:`~MetricsHistory.to_chrome` emits chrome **counter tracks**
  (``ph:"C"``) that ``flight_recorder.merge_chrome_traces`` folds into
  the per-rank trace view as one more lane.

Same zero-overhead discipline as the flight recorder: the module gate
(:func:`is_enabled`) is a plain bool, and the wired call site
(:func:`history_tick`) returns immediately when it is off.
``PADDLE_HISTORY=1`` enables at import (and starts the background
sampler unless ``PADDLE_HISTORY_INTERVAL_S=0``);
``PADDLE_HISTORY_CAPACITY`` bounds the ring (points per series,
default 512). Everything here is stdlib-only.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

__all__ = [
    "MetricsHistory", "get_history", "history", "history_tick",
    "enable", "disable", "is_enabled", "reset",
    "HISTORY_SCHEMA", "DEFAULT_HISTORY_CAPACITY",
    "DEFAULT_HISTORY_INTERVAL_S",
]

HISTORY_SCHEMA = "paddle_history/1"
DEFAULT_HISTORY_CAPACITY = 512
DEFAULT_HISTORY_INTERVAL_S = 1.0

_ENABLED = False
_HISTORY: "MetricsHistory | None" = None
_MODULE_LOCK = threading.Lock()


def _env_truthy(v) -> bool:
    return v not in (None, "", "0", "false", "False", "no")


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return float(default)


class _Series:
    """One labeled series: a bounded ring of (t, value) points."""

    __slots__ = ("name", "key", "kind", "label_names", "points", "dropped")

    def __init__(self, name, key, kind, label_names, capacity):
        self.name = name
        self.key = key                    # the collect() label-value key
        self.kind = kind                  # counter | gauge | derived
        self.label_names = list(label_names)
        self.points: deque = deque(maxlen=capacity)
        self.dropped = 0                  # ring evictions (capacity hits)

    def append(self, t, v):
        if len(self.points) == self.points.maxlen:
            self.dropped += 1
        self.points.append((t, float(v)))

    @property
    def display(self):
        return f"{self.name}{{{self.key}}}" if self.key else self.name


class MetricsHistory:
    """Sampler + query surface over the process-global metric registry.

    h = MetricsHistory()
    h.tick()                       # one deterministic snapshot
    h.start()                      # or: background sampling
    h.rate("paddle_slo_violations_total", labels="request", window_s=30)
    h.window("paddle_fleet_replica_queue_depth", labels="r0", window_s=10)
    """

    def __init__(self, capacity=None, interval_s=None, registry=None):
        if capacity is None:
            try:
                capacity = int(os.environ.get("PADDLE_HISTORY_CAPACITY",
                                              str(DEFAULT_HISTORY_CAPACITY)))
            except ValueError:
                capacity = DEFAULT_HISTORY_CAPACITY
        self.capacity = max(int(capacity), 8)
        self.interval_s = (interval_s if interval_s is not None
                           else _env_float("PADDLE_HISTORY_INTERVAL_S",
                                           DEFAULT_HISTORY_INTERVAL_S))
        self._registry = registry
        self._lock = threading.RLock()
        self._series: dict = {}           # (name, key) -> _Series
        self._ticks = 0
        self._last_tick_t = None
        self._wall_offset = time.time() - time.monotonic()
        self._observers: list = []        # fn(history, now) after each tick
        self._stop = threading.Event()
        self._thread = None
        self._tele = None

    # -- internals -----------------------------------------------------------
    def _reg(self):
        if self._registry is None:
            from .telemetry import get_registry
            self._registry = get_registry()
        return self._registry

    def _telemetry(self):
        if self._tele is None:
            r = self._reg()
            self._tele = {
                "samples": r.counter(
                    "paddle_history_samples_total",
                    "history sampler ticks taken"),
                "series": r.gauge(
                    "paddle_history_series",
                    "distinct labeled series tracked in the history"),
                "evicted": r.counter(
                    "paddle_history_points_evicted_total",
                    "points aged out of full series rings"),
            }
        return self._tele

    @staticmethod
    def now() -> float:
        """The history clock (monotonic). Replay harnesses and alert
        rules share it so window math lines up exactly."""
        return time.monotonic()

    # -- sampling ------------------------------------------------------------
    def tick(self, now=None) -> int:
        """Take one snapshot of the registry; every series gains one
        point stamped ``now`` (the history clock unless given — tests
        and replay harnesses pass explicit times for determinism).
        Returns the number of series updated."""
        now = self.now() if now is None else float(now)
        snap = self._reg().collect()
        updated = 0
        evicted_before = 0
        with self._lock:
            for s in self._series.values():
                evicted_before += s.dropped
            for name, fam in snap.items():
                kind = fam.get("type", "untyped")
                label_names = fam.get("label_names", [])
                for key, val in fam.get("series", {}).items():
                    if kind == "histogram":
                        for suffix, v in (
                                (":count", val.get("count", 0)),
                                (":sum", val.get("sum", 0.0)),
                                (":p95", val.get("p95", 0.0))):
                            self._append_locked(
                                name + suffix, key,
                                "counter" if suffix != ":p95" else "derived",
                                label_names, now, v)
                            updated += 1
                    else:
                        self._append_locked(name, key, kind, label_names,
                                            now, val)
                        updated += 1
            self._ticks += 1
            self._last_tick_t = now
            n_series = len(self._series)
            evicted_after = sum(s.dropped for s in self._series.values())
        tele = self._telemetry()
        tele["samples"].inc()
        tele["series"].set(n_series)
        if evicted_after > evicted_before:
            tele["evicted"].inc(evicted_after - evicted_before)
        for fn in list(self._observers):
            try:
                fn(self, now)
            except Exception:      # an observer must never kill the sampler
                pass
        return updated

    def _append_locked(self, name, key, kind, label_names, now, v):
        sk = (name, key)
        s = self._series.get(sk)
        if s is None:
            s = self._series[sk] = _Series(name, key, kind, label_names,
                                           self.capacity)
        s.append(now, v)

    def add_tick_observer(self, fn):
        """``fn(history, now)`` runs after every tick — the alert engine
        hooks here so rules evaluate on the exact tick timeline."""
        if fn not in self._observers:
            self._observers.append(fn)

    def remove_tick_observer(self, fn):
        if fn in self._observers:
            self._observers.remove(fn)

    # -- background sampler --------------------------------------------------
    def start(self, interval_s=None):
        """Start the background sampling thread (no-op if running)."""
        if interval_s is not None:
            self.interval_s = float(interval_s)
        if self.interval_s <= 0:
            return self
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="paddle-history-sampler")
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:      # sampling must never crash the process
                pass

    # -- read side -----------------------------------------------------------
    def series_names(self) -> list:
        with self._lock:
            return sorted(s.display for s in self._series.values())

    def _find(self, name, labels=""):
        key = (",".join(str(labels[n]) for n in labels)
               if isinstance(labels, dict) else str(labels))
        with self._lock:
            s = self._series.get((name, key))
            if s is None and isinstance(labels, dict):
                # dict labels: match by value set against the label order
                for (n, k), cand in self._series.items():
                    if n == name and set(k.split(",")) == set(
                            str(v) for v in labels.values()):
                        s = cand
                        break
        return s

    def points(self, name, labels="") -> list:
        """The raw ``[(t, value), ...]`` ring for one series (oldest
        first; empty when the series was never sampled)."""
        s = self._find(name, labels)
        if s is None:
            return []
        with self._lock:
            return list(s.points)

    def _window_points(self, name, labels, window_s, now):
        pts = self.points(name, labels)
        if not pts:
            return []
        if window_s is None:
            return pts
        now = pts[-1][0] if now is None else float(now)
        lo = now - float(window_s)
        return [(t, v) for t, v in pts if lo <= t <= now]

    def window(self, name, labels="", window_s=None, now=None) -> dict:
        """min / mean / max / exact-p95 over the points inside the
        window (``window_s=None`` = the whole ring; ``now`` defaults to
        the newest point)."""
        pts = self._window_points(name, labels, window_s, now)
        if not pts:
            return {"count": 0, "min": 0.0, "mean": 0.0, "max": 0.0,
                    "p95": 0.0, "t_first": None, "t_last": None}
        vals = sorted(v for _, v in pts)
        k95 = max(0, min(len(vals) - 1,
                         int(round(0.95 * (len(vals) - 1)))))
        return {
            "count": len(pts),
            "min": vals[0],
            "mean": sum(vals) / len(vals),
            "max": vals[-1],
            "p95": vals[k95],
            "t_first": pts[0][0],
            "t_last": pts[-1][0],
        }

    def rate(self, name, labels="", window_s=None, now=None) -> float:
        """Counter increase per second over the window, reset-aware: a
        decrease between consecutive points means the counter restarted
        (process restart mid-history), so the post-reset value counts
        as increase-from-zero instead of poisoning the rate with a huge
        negative delta (the Prometheus ``rate()`` convention)."""
        pts = self._window_points(name, labels, window_s, now)
        if len(pts) < 2:
            return 0.0
        increase = 0.0
        for (_, a), (_, b) in zip(pts, pts[1:]):
            increase += (b - a) if b >= a else b
        dt = pts[-1][0] - pts[0][0]
        return increase / dt if dt > 0 else 0.0

    def increase(self, name, labels="", window_s=None, now=None) -> float:
        """Reset-aware counter increase over the window (the rate's
        numerator — burn-rate rules use this directly)."""
        pts = self._window_points(name, labels, window_s, now)
        if len(pts) < 2:
            return 0.0
        inc = 0.0
        for (_, a), (_, b) in zip(pts, pts[1:]):
            inc += (b - a) if b >= a else b
        return inc

    def latest(self, name, labels="") -> "tuple | None":
        pts = self.points(name, labels)
        return pts[-1] if pts else None

    @property
    def ticks(self):
        return self._ticks

    def clear(self):
        with self._lock:
            self._series.clear()
            self._ticks = 0
            self._last_tick_t = None

    def snapshot(self, match=None, window_s=None, now=None,
                 max_series=None) -> list:
        """JSON-ready ``[{name, labels, kind, points}, ...]`` view of the
        rings — the ``/history`` telemetry-plane endpoint's body.
        ``match=`` filters by display-name substring, ``window_s=``
        keeps only the trailing window (newest point anchored unless
        ``now`` is given), ``max_series=`` bounds the series count (the
        endpoint must never return unbounded work)."""
        with self._lock:
            series = sorted(self._series.values(),
                            key=lambda s: (s.name, s.key))
            out = []
            for s in series:
                disp = s.display
                if match and match not in disp:
                    continue
                pts = list(s.points)
                if window_s is not None and pts:
                    hi = pts[-1][0] if now is None else float(now)
                    lo = hi - float(window_s)
                    pts = [(t, v) for t, v in pts if lo <= t <= hi]
                out.append({"name": s.name, "labels": s.key,
                            "kind": s.kind,
                            "points": [[round(t, 6), v] for t, v in pts]})
                if max_series is not None and len(out) >= int(max_series):
                    break
        return out

    # -- exports -------------------------------------------------------------
    def export_jsonl(self, path) -> int:
        """Write the whole history as self-describing JSONL: one header
        record (schema, tick count, wall-clock offset so consumers can
        map monotonic t to wall time) then one record per series.
        Write-temp-then-replace: a concurrent reader (the fleet console
        tailing mid-replay) never sees a torn file. Returns the series
        count."""
        with self._lock:
            series = [
                {"name": s.name, "labels": s.key,
                 "label_names": s.label_names, "kind": s.kind,
                 "dropped": s.dropped,
                 "points": [[round(t, 6), v] for t, v in s.points]}
                for s in self._series.values()
            ]
            header = {"schema": HISTORY_SCHEMA, "ticks": self._ticks,
                      "capacity": self.capacity,
                      "wall_offset": self._wall_offset,
                      "unix_time": time.time()}
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(json.dumps(header) + "\n")
            for rec in sorted(series, key=lambda r: (r["name"],
                                                     r["labels"])):
                f.write(json.dumps(rec) + "\n")
        os.replace(tmp, path)
        return len(series)

    def to_chrome(self, pid=None, match=None) -> dict:
        """Chrome **counter-track** events (``ph:"C"``): each series
        renders as a value-over-time track Perfetto draws next to the
        span lanes. Feed the result to
        ``flight_recorder.merge_chrome_traces`` as one more lane to see
        metric movement against the per-rank / per-request timeline.
        ``match=`` filters series by substring of the display name."""
        pid = os.getpid() if pid is None else pid
        events = []
        with self._lock:
            series = list(self._series.values())
        for s in sorted(series, key=lambda x: (x.name, x.key)):
            disp = s.display
            if match and match not in disp:
                continue
            for t, v in s.points:
                events.append({"name": disp, "ph": "C", "pid": pid,
                               "tid": 0, "ts": round(t * 1e6, 3),
                               "args": {"value": v}})
        return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# module facade (zero overhead disabled — same pattern as flight_recorder)
# ---------------------------------------------------------------------------


def get_history() -> MetricsHistory:
    global _HISTORY
    if _HISTORY is None:
        with _MODULE_LOCK:
            if _HISTORY is None:
                _HISTORY = MetricsHistory()
    return _HISTORY


def history() -> MetricsHistory:
    """``paddle.profiler.history()`` — the process-global history."""
    return get_history()


def is_enabled() -> bool:
    return _ENABLED


def enable(interval_s=None, sampler=True) -> MetricsHistory:
    """Turn the history on (and start the background sampler unless
    ``sampler=False`` — replay harnesses and tests drive ``tick()``
    themselves for determinism)."""
    global _ENABLED
    h = get_history()
    _ENABLED = True
    if sampler:
        h.start(interval_s=interval_s)
    elif interval_s is not None:
        h.interval_s = float(interval_s)
    return h


def disable():
    global _ENABLED
    _ENABLED = False
    with _MODULE_LOCK:
        if _HISTORY is not None:
            _HISTORY.stop()


def reset():
    """Drop the global history (tests / between jobs). Keeps the
    enabled flag."""
    global _HISTORY
    with _MODULE_LOCK:
        if _HISTORY is not None:
            _HISTORY.stop()
        _HISTORY = None


def history_tick(now=None):
    """The wired call site: one sample IF the layer is enabled (plain
    bool check when off — the disabled path costs nothing)."""
    if not _ENABLED:
        return None
    return get_history().tick(now=now)


if _env_truthy(os.environ.get("PADDLE_HISTORY")):   # pragma: no cover
    enable(sampler=_env_float("PADDLE_HISTORY_INTERVAL_S",
                              DEFAULT_HISTORY_INTERVAL_S) > 0)
