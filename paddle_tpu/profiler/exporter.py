"""Per-process telemetry exporter: every replica a scrapeable HTTP
endpoint (ISSUE 15; the remote face of the in-process observability
stack — ROADMAP item 3's one-process-per-replica fleet is diagnosable
only if each process exports what PRs 3/9/11-13 already collect).

:class:`TelemetryServer` is a daemon ``ThreadingHTTPServer`` serving:

| route                  | method | body                                    |
|------------------------|--------|-----------------------------------------|
| ``/metrics``           | GET    | Prometheus text exposition (``metrics_text()``) |
| ``/healthz``           | GET    | watchdog heartbeat ages + component summary; 200 healthy / 503 stale |
| ``/state``             | GET    | flight-recorder component states (JSON) |
| ``/history``           | GET    | metric time-series window (``?window_s=&match=``, capped) |
| ``/timeline/<trace>``  | GET    | one request's PR-9 timeline (404 unknown) |
| ``/compile``           | GET    | compile-observatory snapshot: per-family hit/miss/seconds + retrace causes (JSON) |
| ``/debug/dump``        | POST   | trigger an on-demand flight-recorder dump; returns the dump paths |

Every endpoint is bounded: the history window is capped at
``MAX_HISTORY_WINDOW_S`` / ``MAX_HISTORY_SERIES``, request bodies over
``MAX_POST_BYTES`` are rejected with 400, and only ``/debug/dump``
accepts POST (anything else is 405).

Gating: the env knob ``PADDLE_TELEMETRY_PORT`` turns the plane on —
unset / empty / ``0`` means **off** (zero overhead: the wired call site
:func:`maybe_start_exporter` is one env read returning None), ``auto``
binds an ephemeral port (the multi-replica-per-process tier always uses
ephemeral ports to avoid collisions), an integer binds that port.
``PADDLE_TELEMETRY_HOST`` picks the bind address (default 127.0.0.1);
``PADDLE_TELEMETRY_INSTANCE`` names the endpoint when the owning
component doesn't.

Discovery: a started server publishes
``<prefix><instance>`` -> ``{host, port, pid}`` through the existing
:func:`~.flight_recorder.publish_component_state` KV path
(``KV_TELEMETRY_PREFIX`` = ``fleet/telemetry/`` by default), so the
:class:`~.scrape.FleetScraper` finds endpoints with the same
``keys(prefix)`` scan on ``MemKVStore`` and ``TcpKVStore`` that replica
heartbeats already ride.
"""
from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse

__all__ = [
    "TelemetryServer", "maybe_start_exporter", "exporter_enabled",
    "ROUTES", "KV_TELEMETRY_PREFIX", "MAX_HISTORY_WINDOW_S",
    "MAX_HISTORY_SERIES", "MAX_POST_BYTES",
]

#: every HTTP route the exporter serves; tools/check_inventory.py
#: requires each documented in docs/OBSERVABILITY.md AND exercised by a
#: test
ROUTES = ("/metrics", "/healthz", "/state", "/history", "/timeline",
          "/compile", "/debug/dump")

#: discovery key prefix: ``<prefix><instance>`` -> {host, port, pid}
KV_TELEMETRY_PREFIX = "fleet/telemetry/"

#: endpoint bounds — a scrape must never be unbounded work
MAX_HISTORY_WINDOW_S = 3600.0
MAX_HISTORY_SERIES = 256
MAX_POST_BYTES = 65536

_TELE = None


def _telemetry():
    global _TELE
    if _TELE is None:
        from .telemetry import get_registry
        _TELE = get_registry().counter(
            "paddle_telemetry_http_requests_total",
            "exporter HTTP requests served, by route",
            labels=("route",))
    return _TELE


def _env_port():
    """The gate: None = plane off; 0 = ephemeral; else the fixed port."""
    v = os.environ.get("PADDLE_TELEMETRY_PORT")
    if v is None:
        return None
    v = v.strip().lower()
    if v in ("", "0", "off", "false", "no"):
        return None
    if v in ("auto", "ephemeral"):
        return 0
    try:
        p = int(v)
    except ValueError:
        return None
    return p if p > 0 else None


def exporter_enabled() -> bool:
    return _env_port() is not None


def _default_health() -> "tuple[bool, dict]":
    """(ok, payload): watchdog heartbeat ages vs deadline plus a small
    per-component state summary (bounded — full state lives at
    ``/state``)."""
    from . import flight_recorder as fr
    rec = fr.get_flight_recorder()
    now = time.monotonic()
    ages = {str(r): round(now - t, 3)
            for r, t in dict(rec._heartbeats).items()}
    wd = fr.get_watchdog()
    if wd is not None:
        deadline = wd.deadline_s
    else:
        try:
            deadline = float(os.environ.get("PADDLE_FLIGHT_DEADLINE_S",
                                            300.0))
        except ValueError:
            deadline = 300.0
    stale = sorted(r for r, a in ages.items() if a > deadline)
    comps = {}
    for name, fn in list(fr._STATE_PROVIDERS.items()):
        try:
            st = fn()
        except Exception as e:     # a probe must never 500 the healthz
            comps[name] = {"error": repr(e)}
            continue
        if not isinstance(st, dict):
            continue
        summary = {k: st[k] for k in ("engine", "running", "queue_depth",
                                      "replica", "role", "draining",
                                      "steps", "oldest_request_age_s")
                   if k in st}
        reps = st.get("replicas")
        if isinstance(reps, dict):
            summary["replicas_alive"] = sum(
                1 for v in reps.values()
                if isinstance(v, dict) and v.get("alive"))
            summary["replicas"] = len(reps)
        comps[name] = summary
    ok = not stale
    return ok, {"ok": ok, "deadline_s": deadline,
                "heartbeat_ages_s": ages, "stale_ranks": stale,
                "components": comps}


class TelemetryServer:
    """One process's scrapeable telemetry endpoint.

    srv = TelemetryServer(instance="r0", port=0).start()   # ephemeral
    ...  curl http://{srv.host}:{srv.port}/metrics
    srv.stop()

    With ``store=``, the started server announces itself under
    ``<key_prefix><instance>`` so a :class:`~.scrape.FleetScraper`
    discovers it; ``stop(unpublish=False)`` models process death (the
    key stays, the endpoint goes dark, the scraper marks it stale).
    """

    def __init__(self, instance=None, host=None, port=None, store=None,
                 key_prefix=None, health_fn=None):
        self.instance = str(instance
                            or os.environ.get("PADDLE_TELEMETRY_INSTANCE")
                            or f"proc-{os.getpid()}")
        self.host = host or os.environ.get("PADDLE_TELEMETRY_HOST",
                                           "127.0.0.1")
        if port is None:
            port = _env_port() or 0
        self.port = int(port)
        self._store = store
        self._prefix = (KV_TELEMETRY_PREFIX if key_prefix is None
                        else str(key_prefix))
        self._health_fn = health_fn or _default_health
        self._httpd = None
        self._thread = None

    # -- lifecycle -----------------------------------------------------------
    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def kv_key(self) -> str:
        return f"{self._prefix}{self.instance}"

    def start(self):
        if self._httpd is not None:
            return self
        handler = _make_handler(self)
        try:
            self._httpd = ThreadingHTTPServer((self.host, self.port),
                                              handler)
        except OSError:
            if self.port == 0:
                raise
            # fixed port taken (another exporter in this process, or a
            # peer on the host): fall back to an ephemeral pick rather
            # than refusing to export at all
            self._httpd = ThreadingHTTPServer((self.host, 0), handler)
        self._httpd.daemon_threads = True
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.05}, daemon=True,
            name=f"paddle-telemetry-{self.instance}")
        self._thread.start()
        self.publish()
        return self

    def publish(self):
        """(Re-)announce this endpoint through the KV discovery path."""
        if self._store is None:
            return None
        from .flight_recorder import publish_component_state
        return publish_component_state(self._store, self.kv_key, {
            "instance": self.instance, "host": self.host,
            "port": self.port, "pid": os.getpid(),
        })

    def stop(self, unpublish=True):
        """Shut the endpoint down. ``unpublish=False`` leaves the
        discovery key in place — the hard-kill path: the scraper must
        see the endpoint go stale, not vanish cleanly."""
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if unpublish and self._store is not None:
            try:
                self._store.delete(self.kv_key)
            except Exception:
                pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- endpoint bodies (called by the handler) -----------------------------
    def _body_metrics(self):
        from .telemetry import metrics_text
        return 200, metrics_text().encode(), \
            "text/plain; version=0.0.4; charset=utf-8"

    def _body_healthz(self):
        ok, payload = self._health_fn()
        payload["instance"] = self.instance
        return (200 if ok else 503), _json(payload), "application/json"

    def _body_state(self):
        from . import flight_recorder as fr
        state = fr.get_flight_recorder()._provider_state()
        return 200, _json({"instance": self.instance, "state": state}), \
            "application/json"

    def _body_history(self, query):
        from .timeseries import get_history
        window = None
        if query.get("window_s"):
            try:
                window = float(query["window_s"][0])
            except ValueError:
                return 400, _json({"error": "bad window_s"}), \
                    "application/json"
        window = (MAX_HISTORY_WINDOW_S if window is None
                  else min(max(window, 0.0), MAX_HISTORY_WINDOW_S))
        match = query.get("match", [None])[0]
        series = get_history().snapshot(match=match, window_s=window,
                                        max_series=MAX_HISTORY_SERIES)
        return 200, _json({"instance": self.instance,
                           "window_s": window, "series": series}), \
            "application/json"

    def _body_timeline(self, trace_id):
        from .request_trace import request_timeline
        try:
            tl = request_timeline(unquote(trace_id))
        except KeyError:
            return 404, _json({"error": f"no trace {trace_id!r}"}), \
                "application/json"
        return 200, _json(tl), "application/json"

    def _body_compile(self):
        from . import compile_observatory as co
        return 200, _json({"instance": self.instance,
                           **co.snapshot()}), "application/json"

    def _body_dump(self):
        from . import flight_recorder as fr
        res = fr.get_flight_recorder().dump(
            reason=f"http_debug_dump:{self.instance}")
        return 200, _json({"instance": self.instance, **res}), \
            "application/json"


def _json(obj) -> bytes:
    return json.dumps(obj, default=str).encode()


def _route_label(path: str) -> str:
    if path.startswith("/timeline/"):
        return "/timeline"
    return path if path in ROUTES else "other"


def _make_handler(server: TelemetryServer):
    class _Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):      # quiet: telemetry, not access logs
            pass

        def _send(self, code, body, ctype="application/json"):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _count(self, path):
            try:
                _telemetry().inc(route=_route_label(path))
            except Exception:
                pass

        def do_GET(self):
            url = urlparse(self.path)
            path = url.path.rstrip("/") or "/"
            self._count(path)
            try:
                if path == "/metrics":
                    code, body, ctype = server._body_metrics()
                elif path == "/healthz":
                    code, body, ctype = server._body_healthz()
                elif path == "/state":
                    code, body, ctype = server._body_state()
                elif path == "/history":
                    code, body, ctype = server._body_history(
                        parse_qs(url.query))
                elif path.startswith("/timeline/"):
                    code, body, ctype = server._body_timeline(
                        path[len("/timeline/"):])
                elif path == "/compile":
                    code, body, ctype = server._body_compile()
                elif path == "/debug/dump":
                    code, body, ctype = 405, _json(
                        {"error": "POST /debug/dump"}), "application/json"
                else:
                    code, body, ctype = 404, _json(
                        {"error": f"no route {path!r}"}), "application/json"
            except Exception as e:   # an endpoint bug must not kill serving
                code, body, ctype = 500, _json({"error": repr(e)}), \
                    "application/json"
            self._send(code, body, ctype)

        def do_POST(self):
            url = urlparse(self.path)
            path = url.path.rstrip("/") or "/"
            self._count(path)
            try:
                length = int(self.headers.get("Content-Length") or 0)
            except ValueError:
                length = 0
            if length > MAX_POST_BYTES:
                # bounded bodies: refuse before reading, drop the
                # connection after answering (no unbounded drain)
                self.close_connection = True
                self._send(400, _json({"error": "body too large"}))
                return
            if length:
                self.rfile.read(length)          # drain (bounded)
            if path != "/debug/dump":
                self._send(405, _json(
                    {"error": "only POST /debug/dump"}))
                return
            try:
                code, body, ctype = server._body_dump()
            except Exception as e:
                code, body, ctype = 500, _json({"error": repr(e)}), \
                    "application/json"
            self._send(code, body, ctype)

    return _Handler


def maybe_start_exporter(instance=None, store=None, key_prefix=None,
                         ephemeral=False,
                         health_fn=None) -> "TelemetryServer | None":
    """The wired lifecycle call site: start (and return) an exporter IF
    the ``PADDLE_TELEMETRY_PORT`` gate is on, else None at the cost of
    one env read. ``ephemeral=True`` forces an ephemeral port even under
    a fixed-port env value — the router's per-replica exporters always
    use it (N replicas cannot share one port)."""
    port = _env_port()
    if port is None:
        return None
    if ephemeral:
        port = 0
    try:
        return TelemetryServer(instance=instance, port=port, store=store,
                               key_prefix=key_prefix,
                               health_fn=health_fn).start()
    except Exception:      # an unexportable process still serves traffic
        return None
