"""Determinism observatory: cross-rank/cross-run digest ledger (ISSUE 13).

Nearly every headline guarantee in this repo is a *bit-parity* property
— elastic shrink resumes bit-identical (PR 6), spec decode equals plain
greedy (PR 10), disagg handoff and requeue never change tokens (PRs
8/9) — but each is asserted only inside tests. In a running fleet
nothing would *notice* silent numerical divergence: a flipped bit in
one rank's optimizer state, a non-deterministic kernel, a stale KV page
after a handoff. Production TPU serving (arxiv 2605.25645) treats
cross-replica output equivalence as an operational invariant; this
module is the sensor that makes it one here.

The :class:`StepLedger` computes cheap, *stable* content digests (sha1
over the raw float bit patterns, dtype/shape-tagged — a 1-ulp
perturbation changes the digest) of designated tensors at well-defined
barriers:

* **training** — per-step parameter and (post-sync) gradient digests,
  hooked through ``Optimizer.step``; optional per-leaf *local* (pre
  all-reduce) gradient digests through the PR-5 tape grad-ready
  callbacks (:func:`attach`, thread-local per simulated rank). Entry
  names are ``grad:<param>`` / ``param:<param>`` / ``grad.local:<param>``
  — the ``grad.local:`` tier legitimately differs across dp ranks (each
  rank owns a data shard) so only the first two enter the cross-rank
  comparison; all three enter the cross-run golden ledger.
* **serving** — per-request delivered-token-stream *chain* digests
  (``d_i = sha1(d_{i-1} || token_i)``) recorded at the engines' single
  token-append point and threaded through ``RequestTraceStore`` spans
  (the ``delivered``/``done`` span carries ``token_digest``); the
  router attests at delivery that a requeued or disaggregated request's
  stream is digest-consistent across attempts/replicas
  (:func:`attest_delivery`) — the at-most-once resume contract becomes
  a runtime-checked invariant.
* **handoff** — KV-page-blob digests sealed at
  ``SlotPagedKVCache.export_pages`` and verified at ``import_pages``
  (:func:`seal_handoff` / :func:`check_handoff`).

Three consumers wire it end to end:

1. **cross-rank** — each rank's committed step row is compared against
   its peers' (directly under the thread-rank simulator; via
   :func:`publish_ledger`/:func:`gather_ledgers` over the flight-
   recorder KV component-state path for real multi-process jobs). The
   comparator raises a structured :class:`DivergenceError` naming the
   FIRST divergent step/rank/tensor (majority vote across ranks;
   ``PADDLE_LEDGER_MODE=warn`` records-and-continues — the warn path is
   read-only, bit-identical to ledger-off). Detections tick
   ``paddle_ledger_divergence_total{kind}``, set the
   ``paddle_ledger_divergent_steps`` gauge the built-in
   ``numerics_divergence`` alert rule pages on, and ride into watchdog
   dumps through the ``ledger`` state provider.
2. **cross-run** — :func:`export_golden` writes a deterministic
   (timestamp-free, sorted, write-tmp-then-replace) JSONL golden
   ledger; stdlib-only ``tools/ledger_diff.py`` diffs two ledgers and
   reports the first divergent step/tensor/request — CI's
   seeded-run-vs-committed-golden guard.
3. **attestation** — see above; failures are ``kind="attestation"``
   divergences.

Zero overhead disabled (flight-recorder-style module bool): every call
site checks :func:`is_enabled` first, nothing registers on the tape
until :func:`enable`/:func:`attach`, and a disabled ledger never
touches tensor memory. ``PADDLE_LEDGER=1`` enables at import.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
from collections import Counter, OrderedDict

__all__ = [
    "DivergenceError", "StepLedger", "get_ledger", "enable", "disable",
    "attach", "detach", "is_enabled", "reset", "tensor_digest",
    "chain_update", "blob_digest", "first_divergence",
    "record_optimizer_step", "note_stream_token", "stream_digest",
    "attest_delivery", "seal_handoff", "check_handoff", "export_golden",
    "publish_ledger", "gather_ledgers", "compare_store",
    "LEDGER_SCHEMA", "KV_LEDGER_PREFIX",
    "DEFAULT_LEDGER_CAPACITY", "DEFAULT_STREAM_CAPACITY",
]

LEDGER_SCHEMA = "paddle_ledger/1"
KV_LEDGER_PREFIX = "ledger/rank/"

DEFAULT_LEDGER_CAPACITY = 512      # committed step rows kept (all ranks)
DEFAULT_STREAM_CAPACITY = 512      # per-(trace, attempt) token chains kept
#: chain digests kept per stream; past the cap the rolling digest and
#: count still advance (attestation then compares final prefixes only)
MAX_CHAIN_PER_STREAM = 4096
#: entry-name prefix excluded from the cross-rank comparison (pre-sync
#: local gradients differ across dp ranks by construction)
LOCAL_PREFIX = "grad.local:"

_MODES = ("raise", "warn")

_ENABLED = False
_LEDGER: "StepLedger | None" = None
_MODULE_LOCK = threading.Lock()

#: seed of every token-stream chain (so an empty stream has a defined,
#: non-colliding digest)
STREAM_SEED = hashlib.sha1(b"paddle-ledger-stream").hexdigest()


class DivergenceError(RuntimeError):
    """Two replicas (ranks, attempts or handoff sides) that must be
    bit-identical are not. Carries the comparison ``kind``
    (``cross_rank`` / ``attestation`` / ``handoff``), the first
    divergent ``step`` (token position for attestation), the divergent
    ``rank`` (attempt number for attestation), the exact ``tensor``
    name (``grad:<param>`` / ``param:<param>`` / ``tokens:<trace_id>``
    / ``handoff:<digest-prefix>``) and the per-replica ``digests``."""

    def __init__(self, kind, step, rank, tensor, digests=None):
        self.kind = str(kind)
        self.step = step
        self.rank = rank
        self.tensor = str(tensor)
        self.digests = dict(digests or {})
        super().__init__(
            f"{self.kind} divergence at step {step}: rank {rank} "
            f"diverges on '{self.tensor}' "
            f"(digests {self.digests}) — run tools/ledger_diff.py "
            f"against the golden ledger and see docs/RUNBOOK.md "
            f"'silent divergence'")


def _rank():
    try:
        from ..distributed import simulator
        r = simulator.current_rank()
        if r is not None:
            return r
    except Exception:
        pass
    return 0


# ---------------------------------------------------------------------------
# digest primitives (pure; shared with tools/ledger_diff.py by schema,
# not by import — the tool must stay stdlib-only)
# ---------------------------------------------------------------------------


def tensor_digest(arr) -> str:
    """sha1 over dtype tag + shape tag + the raw (bit-pattern) buffer.
    Stable across runs/processes for bit-identical content; any single
    flipped bit — including ``-0.0`` vs ``0.0`` or a NaN payload —
    changes it. Works for every numpy-convertible dtype incl. bf16."""
    import numpy as np
    a = np.ascontiguousarray(np.asarray(arr))
    h = hashlib.sha1()
    h.update(str(a.dtype).encode())
    h.update(b"|")
    h.update(repr(tuple(a.shape)).encode())
    h.update(b"|")
    h.update(a.tobytes())
    return h.hexdigest()


def chain_update(prev_hex: str, token: int) -> str:
    """One link of a token-stream chain digest: the digest at position
    ``i`` covers every token up to and including ``i``, so two streams
    agree on a prefix iff their chain digests agree at its last
    position."""
    h = hashlib.sha1()
    h.update(bytes.fromhex(prev_hex))
    h.update(int(token).to_bytes(8, "little", signed=True))
    return h.hexdigest()


def blob_digest(blob: dict) -> str:
    """Content digest of a KV-page handoff blob (``export_pages``
    payload): geometry tags + page digests + every layer's raw K/V
    bytes (+ scales for int8 pools). Ignores any already-attached
    ``ledger_digest`` so sealing is idempotent."""
    import numpy as np
    h = hashlib.sha1()
    h.update(str(blob.get("page_size")).encode())
    h.update(str(blob.get("kv_dtype")).encode())
    h.update(str(blob.get("native_dtype")).encode())
    for d in blob.get("digests", ()):
        h.update(bytes(d))
    for k, v in blob.get("layers", ()):
        for part in (k, v):
            a = np.ascontiguousarray(np.asarray(part))
            h.update(str(a.dtype).encode())
            h.update(repr(tuple(a.shape)).encode())
            h.update(a.tobytes())
    for pair in (blob.get("scales") or ()):
        for part in pair:
            h.update(np.ascontiguousarray(np.asarray(part)).tobytes())
    return h.hexdigest()


def first_divergence(entries_by_rank: dict):
    """Pure comparator over one step's ``{rank: {name: digest}}``.

    Entries are walked in canonical sorted order (``grad:`` sorts
    before ``param:``, so the causal gradient divergence is named
    before the parameter that followed it); ``grad.local:`` entries are
    skipped — local shards differ across dp ranks by design. The
    divergent rank is the one outvoted by the majority digest (ties
    side with the lowest rank). Returns ``None`` or
    ``{"rank", "tensor", "digests"}``."""
    names = sorted(set().union(*[set(e) for e in entries_by_rank.values()])
                   if entries_by_rank else ())
    for name in names:
        if name.startswith(LOCAL_PREFIX):
            continue
        per = {r: e.get(name) for r, e in entries_by_rank.items()}
        present = {r: v for r, v in per.items() if v is not None}
        missing = sorted(r for r, v in per.items() if v is None)
        if missing and present:
            return {"rank": missing[0], "tensor": name, "digests": per}
        if len(set(present.values())) <= 1:
            continue
        top, n = Counter(present.values()).most_common(1)[0]
        majority = (top if n > len(present) // 2
                    else present[min(present)])
        bad = sorted(r for r, v in present.items() if v != majority)
        return {"rank": bad[0] if bad else min(per),
                "tensor": name, "digests": per}
    return None


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------


class StepLedger:
    """Process-global digest ledger. One instance; per-rank rows (the
    thread-rank simulator's ranks share it, which is exactly what lets
    the cross-rank comparator run in-process — multi-process jobs go
    through :func:`publish_ledger`/:func:`gather_ledgers` instead)."""

    def __init__(self, mode=None, interval=None, capacity=None,
                 stream_capacity=None):
        if mode is None:
            mode = os.environ.get("PADDLE_LEDGER_MODE", "raise")
        if mode not in _MODES:
            raise ValueError(f"unknown PADDLE_LEDGER_MODE {mode!r} "
                             f"(one of {'/'.join(_MODES)})")
        self.mode = mode
        if interval is None:
            try:
                interval = int(os.environ.get("PADDLE_LEDGER_INTERVAL", "1"))
            except ValueError:
                interval = 1
        self.interval = max(int(interval), 1)
        if capacity is None:
            try:
                capacity = int(os.environ.get(
                    "PADDLE_LEDGER_CAPACITY", str(DEFAULT_LEDGER_CAPACITY)))
            except ValueError:
                capacity = DEFAULT_LEDGER_CAPACITY
        self.capacity = max(int(capacity), 8)
        if stream_capacity is None:
            try:
                stream_capacity = int(os.environ.get(
                    "PADDLE_LEDGER_STREAMS", str(DEFAULT_STREAM_CAPACITY)))
            except ValueError:
                stream_capacity = DEFAULT_STREAM_CAPACITY
        self.stream_capacity = max(int(stream_capacity), 8)
        self._lock = threading.RLock()
        self._rows: OrderedDict = OrderedDict()    # (rank, step) -> row
        self._pending: dict = {}                   # rank -> OrderedDict
        self._counts: dict = {}                    # rank -> committed steps
        self._verified: dict = {}                  # rank -> verified step hw
        self._streams: OrderedDict = OrderedDict()  # (trace, attempt) -> st
        self._handoffs: list = []                  # recent handoff records
        self._divergences: list = []               # latched detections
        self._store = None                         # optional KV publish
        self._tele = None

    # -- telemetry -----------------------------------------------------------
    def _telemetry(self):
        if self._tele is None:
            from .telemetry import get_registry
            r = get_registry()
            self._tele = {
                "digests": r.counter(
                    "paddle_ledger_digests_total",
                    "content digests computed, by tensor kind",
                    labels=("kind",)),
                "divergence": r.counter(
                    "paddle_ledger_divergence_total",
                    "bit-divergence detections, by comparison kind",
                    labels=("kind",)),
                "divergent_steps": r.gauge(
                    "paddle_ledger_divergent_steps",
                    "distinct steps with a latched cross-rank divergence "
                    "(the built-in numerics_divergence alert's signal)"),
                "attest": r.counter(
                    "paddle_ledger_attestations_total",
                    "delivered-token-stream attestations, by result",
                    labels=("result",)),
            }
        return self._tele

    # -- training: tape + optimizer hooks ------------------------------------
    def _sampling(self, rank) -> bool:
        return self._counts.get(rank, 0) % self.interval == 0

    def _on_grad_ready(self, t):
        """Tape grad-ready callback (:func:`attach`): digest the LOCAL
        (pre all-reduce) gradient the moment it is final. Read-only —
        never perturbs the overlapped-backward dispatch order."""
        g = getattr(t, "grad", None)
        if g is None:
            return
        rank = _rank()
        with self._lock:
            if not self._sampling(rank):
                return
        name = getattr(t, "name", None) or f"param{id(t)}"
        d = tensor_digest(g._data)
        with self._lock:
            self._pending.setdefault(rank, OrderedDict())[
                f"{LOCAL_PREFIX}{name}"] = d
        self._telemetry()["digests"].inc(kind="grad_local")

    def record_optimizer_step(self, optimizer):
        """``Optimizer.step`` hook: digest every stepped parameter's
        (post-sync) gradient and updated value, commit the step row and
        run the cross-rank comparator. Raises :class:`DivergenceError`
        in ``raise`` mode when this commit completes a divergent step.

        Entries are keyed by parameter POSITION (``grad:p0003``) — the
        auto-assigned parameter names come from a process-global
        counter, so the thread-simulated ranks' copies of one model
        carry different names; position in the optimizer's parameter
        list is the cross-rank identity (same construction order on
        every rank). The human name rides in the row's ``names`` map
        and is substituted back into :class:`DivergenceError`."""
        rank = _rank()
        with self._lock:
            step = self._counts.get(rank, 0)
            sampled = step % self.interval == 0
            entries = self._pending.pop(rank, OrderedDict())
        names = {}
        if sampled:
            tele = self._telemetry()
            params = [p for p in optimizer._parameter_list
                      if p.grad is not None
                      and getattr(p, "trainable", not p.stop_gradient)]
            for i, p in enumerate(params):
                names[f"p{i:04d}"] = (getattr(p, "name", None)
                                      or f"param{id(p)}")
            for i, p in enumerate(params):
                entries[f"grad:p{i:04d}"] = tensor_digest(p.grad._data)
                tele["digests"].inc(kind="grad")
            for i, p in enumerate(params):
                entries[f"param:p{i:04d}"] = tensor_digest(p._data)
                tele["digests"].inc(kind="param")
        self._commit(rank, step, entries, names)

    def _commit(self, rank, step, entries, names=None):
        row = {"rank": int(rank), "step": int(step),
               "entries": dict(entries), "names": dict(names or {})}
        with self._lock:
            self._rows[(rank, step)] = row
            self._counts[rank] = step + 1
            while len(self._rows) > self.capacity:
                self._rows.popitem(last=False)
        if self._store is not None:
            try:
                from . import flight_recorder
                flight_recorder.publish_component_state(
                    self._store, f"{KV_LEDGER_PREFIX}{rank}/{step}", row)
            except Exception:
                pass            # sensing must never kill the training loop
        self._verify_committed(rank)

    def _verify_committed(self, rank):
        """Advance this rank's verified high-water across every step all
        live peers have committed; first divergence is handled per
        ``mode`` (raise on the committing rank's own thread)."""
        try:
            from ..distributed import simulator
            w = simulator.active_world()
        except Exception:
            w = None
        if w is None:
            return
        live = [r for r in range(w.nprocs) if r not in w.dead_ranks]
        if len(live) < 2 or rank not in live:
            return
        found = None
        with self._lock:
            s = self._verified.get(rank, -1) + 1
            while s < self._counts.get(rank, 0):
                rows = {r: self._rows.get((r, s)) for r in live}
                if any(v is None for v in rows.values()):
                    break                    # peers not there yet
                self._verified[rank] = s
                div = first_divergence(
                    {r: row["entries"] for r, row in rows.items()})
                if div is not None:
                    found = dict(div, step=s)
                    # substitute the divergent rank's human parameter
                    # name back into the positional entry key
                    kind, _, key = found["tensor"].partition(":")
                    name = (rows[found["rank"]] or {}).get(
                        "names", {}).get(key)
                    if name:
                        found["tensor"] = f"{kind}:{name}"
                    break
                s += 1
        if found is not None:
            self._on_divergence("cross_rank", found["step"], found["rank"],
                                found["tensor"], found["digests"])

    # -- serving: token streams + attestation --------------------------------
    def note_stream_token(self, trace_id, attempt, token):
        """Advance the (trace, attempt) chain digest by one delivered
        token — called from the engines' single token-append point."""
        key = (str(trace_id), int(attempt or 0))
        with self._lock:
            st = self._streams.get(key)
            if st is None:
                st = self._streams[key] = {
                    "trace": key[0], "attempt": key[1],
                    "count": 0, "digest": STREAM_SEED, "chain": []}
                self._streams.move_to_end(key)
                while len(self._streams) > self.stream_capacity:
                    self._streams.popitem(last=False)
            st["digest"] = chain_update(st["digest"], token)
            st["count"] += 1
            if len(st["chain"]) < MAX_CHAIN_PER_STREAM:
                st["chain"].append(st["digest"])
        self._telemetry()["digests"].inc(kind="stream")

    def streams(self, trace_id) -> dict:
        """{attempt: {"count", "digest"}} for one trace."""
        tid = str(trace_id)
        with self._lock:
            return {a: {"count": st["count"], "digest": st["digest"]}
                    for (t, a), st in self._streams.items() if t == tid}

    def stream_digest(self, trace_id, attempt=None):
        """Final chain digest of one attempt's stream (highest attempt
        when unspecified), or ``None`` when nothing was recorded."""
        tid = str(trace_id)
        with self._lock:
            cands = [(a, st) for (t, a), st in self._streams.items()
                     if t == tid
                     and (attempt is None or a == int(attempt))]
        if not cands:
            return None
        return max(cands)[1]["digest"]

    def attest_delivery(self, trace_id, attempt=None):
        """Verify every attempt recorded for ``trace_id`` is chain-
        consistent with the delivering attempt over their common prefix
        (a requeued attempt restarted decode; a disagg prefill attempt
        produced the first token on another replica — both must have
        produced the SAME tokens). Returns the delivered stream's final
        digest; mismatch is an ``attestation`` divergence."""
        tid = str(trace_id)
        with self._lock:
            atts = sorted(((a, dict(st, chain=list(st["chain"])))
                           for (t, a), st in self._streams.items()
                           if t == tid))
        if not atts:
            return None
        base = dict(atts[-1][1])
        if attempt is not None:
            for a, st in atts:
                if a == int(attempt):
                    base = st
                    break
        tele = self._telemetry()
        for a, st in atts:
            if st is base or a == base["attempt"]:
                continue
            n = min(st["count"], base["count"])
            if n == 0 or n > len(st["chain"]) or n > len(base["chain"]):
                continue
            if st["chain"][n - 1] != base["chain"][n - 1]:
                tele["attest"].inc(result="fail")
                self._on_divergence(
                    "attestation", n - 1, a, f"tokens:{tid}",
                    {a: st["chain"][n - 1],
                     base["attempt"]: base["chain"][n - 1]})
                return base["digest"]      # warn mode records + continues
        tele["attest"].inc(result="pass")
        return base["digest"]

    # -- KV-page handoff -----------------------------------------------------
    def seal_handoff(self, blob) -> str:
        """Exporter side: compute + record the blob digest (the caller
        attaches it to the blob as ``ledger_digest``)."""
        d = blob_digest(blob)
        with self._lock:
            self._handoffs.append({"direction": "export", "digest": d,
                                   "pages": len(blob.get("digests", ()))})
            del self._handoffs[:-64]
        self._telemetry()["digests"].inc(kind="handoff")
        return d

    def check_handoff(self, blob):
        """Importer side: recompute and verify a sealed blob. An
        unsealed blob (exporter ran ledger-off) records but never
        fails — enabling the ledger must stay a rolling operation."""
        d = blob_digest(blob)
        want = blob.get("ledger_digest")
        with self._lock:
            self._handoffs.append({"direction": "import", "digest": d,
                                   "pages": len(blob.get("digests", ()))})
            del self._handoffs[:-64]
        self._telemetry()["digests"].inc(kind="handoff")
        if want is not None and want != d:
            self._on_divergence("handoff", None, _rank(),
                                f"handoff:{want[:12]}",
                                {"exported": want, "imported": d})
        return d

    # -- divergence handling -------------------------------------------------
    def _on_divergence(self, kind, step, rank, tensor, digests):
        tele = self._telemetry()
        tele["divergence"].inc(kind=kind)
        with self._lock:
            self._divergences.append({
                "kind": kind, "step": step, "rank": rank,
                "tensor": str(tensor), "digests": dict(digests or {})})
            del self._divergences[:-64]
            steps = {d["step"] for d in self._divergences
                     if d["kind"] == "cross_rank"}
        tele["divergent_steps"].set(len(steps))
        from . import flight_recorder
        flight_recorder.record_event("ledger", divergence=kind, step=step,
                                     divergent_rank=rank,
                                     tensor=str(tensor))
        if self.mode == "raise":
            raise DivergenceError(kind, step, rank, tensor, digests)

    def divergences(self) -> list:
        with self._lock:
            return [dict(d) for d in self._divergences]

    # -- read side -----------------------------------------------------------
    def rows(self, rank=None) -> list:
        with self._lock:
            return [dict(r, entries=dict(r["entries"]))
                    for r in self._rows.values()
                    if rank is None or r["rank"] == rank]

    def state(self) -> dict:
        """The ``ledger`` state-provider payload (watchdog dumps)."""
        with self._lock:
            recent = list(self._rows.values())[-8:]
            return {
                "mode": self.mode,
                "interval": self.interval,
                "steps": dict(self._counts),
                "verified": dict(self._verified),
                "recent_rows": [
                    {"rank": r["rank"], "step": r["step"],
                     "entries": dict(sorted(r["entries"].items())[:32])}
                    for r in recent],
                "streams": len(self._streams),
                "handoffs": [dict(h) for h in self._handoffs[-8:]],
                "divergences": [dict(d) for d in self._divergences],
            }

    def attach_store(self, store):
        """Publish every committed row to an elastic KV store under
        ``ledger/rank/<r>/<s>`` (the flight-recorder component-state
        path) so an out-of-process comparator (:func:`compare_store`)
        sees them."""
        self._store = store
        return self

    # -- golden export -------------------------------------------------------
    def export_golden(self, path=None) -> str:
        """Write the deterministic JSONL golden ledger: one ``meta``
        line, then step rows sorted by (rank, step) with sorted
        entries, stream rows sorted by (trace, attempt), handoffs in
        record order. No timestamps — two bit-identical runs produce
        byte-identical files. Write-tmp-then-replace."""
        path = path or os.environ.get("PADDLE_LEDGER_GOLDEN") \
            or "./ledger_golden.jsonl"
        with self._lock:
            rows = sorted(self._rows.values(),
                          key=lambda r: (r["rank"], r["step"]))
            lines = [json.dumps({"kind": "meta", "schema": LEDGER_SCHEMA},
                                sort_keys=True)]
            for r in rows:
                lines.append(json.dumps(
                    {"kind": "step", "rank": r["rank"], "step": r["step"],
                     "entries": dict(sorted(r["entries"].items())),
                     "names": dict(sorted(r.get("names", {}).items()))},
                    sort_keys=True))
            for (t, a) in sorted(self._streams):
                st = self._streams[(t, a)]
                lines.append(json.dumps(
                    {"kind": "stream", "trace": t, "attempt": a,
                     "count": st["count"], "digest": st["digest"]},
                    sort_keys=True))
            for h in self._handoffs:
                lines.append(json.dumps(dict(h, kind="handoff"),
                                        sort_keys=True))
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write("\n".join(lines) + "\n")
        os.replace(tmp, path)
        return path

    def clear(self):
        with self._lock:
            self._rows.clear()
            self._pending.clear()
            self._counts.clear()
            self._verified.clear()
            self._streams.clear()
            del self._handoffs[:]
            del self._divergences[:]


# ---------------------------------------------------------------------------
# module facade (every call is a bool check away from free when disabled)
# ---------------------------------------------------------------------------

_ATTACHED = threading.local()


def get_ledger() -> StepLedger:
    global _LEDGER
    if _LEDGER is None:
        with _MODULE_LOCK:
            if _LEDGER is None:
                _LEDGER = StepLedger()
    return _LEDGER


def is_enabled() -> bool:
    return _ENABLED


def attach() -> StepLedger:
    """Register the ledger's tape grad-ready callback on THIS thread
    (each simulated rank attaches itself — tape hooks are thread-local).
    Optional: the optimizer-step digests need no attachment. Idempotent
    per thread."""
    led = get_ledger()
    if getattr(_ATTACHED, "cb", None) is not None:
        return led
    from ..autograd import tape
    _ATTACHED.cb = tape.register_grad_ready_callback(led._on_grad_ready)
    return led


def detach():
    cb = getattr(_ATTACHED, "cb", None)
    if cb is None:
        return
    from ..autograd import tape
    tape.unregister_grad_ready_callback(cb)
    _ATTACHED.cb = None


def enable(mode=None, interval=None, capacity=None, store=None,
           grad_ready=False) -> StepLedger:
    """Build/replace the global ledger, register the ``ledger`` watchdog
    state provider and the built-in ``numerics_divergence`` alert rule.
    ``grad_ready=True`` also attaches the calling thread's tape hook
    (per-leaf local-grad digests); ``store=`` publishes committed rows
    to an elastic KV store."""
    global _ENABLED, _LEDGER
    with _MODULE_LOCK:
        if (_LEDGER is None or mode is not None or interval is not None
                or capacity is not None):
            _LEDGER = StepLedger(mode=mode, interval=interval,
                                 capacity=capacity)
    _ENABLED = True
    led = get_ledger()
    if store is not None:
        led.attach_store(store)
    if grad_ready:
        attach()
    from . import flight_recorder
    flight_recorder.register_state_provider("ledger", led.state)
    try:
        from .alerts import ThresholdRule, get_alert_engine
        eng = get_alert_engine()
        if "numerics_divergence" not in eng.rules:
            eng.add_rule(ThresholdRule(
                name="numerics_divergence",
                metric="paddle_ledger_divergent_steps",
                above=0, severity="page"))
    except Exception:
        pass           # alerting is optional; detection must still work
    return led


def disable():
    """Detach this thread and drop the module gate + state provider."""
    global _ENABLED
    _ENABLED = False
    detach()
    from . import flight_recorder
    flight_recorder.unregister_state_provider("ledger")


def reset():
    """Drop the ledger and its rows/streams (tests / between jobs)."""
    global _LEDGER
    detach()
    with _MODULE_LOCK:
        _LEDGER = None
    try:
        from .alerts import _ENGINE
        if _ENGINE is not None:
            _ENGINE.remove_rule("numerics_divergence")
    except Exception:
        pass


# -- wired call-site facades (each checks the module gate first) ------------


def record_optimizer_step(optimizer):
    if not _ENABLED:
        return
    get_ledger().record_optimizer_step(optimizer)


def note_stream_token(trace_id, attempt, token):
    if not _ENABLED or trace_id is None:
        return
    get_ledger().note_stream_token(trace_id, attempt, token)


def stream_digest(trace_id, attempt=None):
    if not _ENABLED or trace_id is None:
        return None
    return get_ledger().stream_digest(trace_id, attempt=attempt)


def attest_delivery(trace_id, attempt=None):
    if not _ENABLED or trace_id is None:
        return None
    return get_ledger().attest_delivery(trace_id, attempt=attempt)


def seal_handoff(blob):
    if not _ENABLED:
        return None
    return get_ledger().seal_handoff(blob)


def check_handoff(blob):
    if not _ENABLED:
        return None
    return get_ledger().check_handoff(blob)


def export_golden(path=None) -> str:
    return get_ledger().export_golden(path)


# ---------------------------------------------------------------------------
# cross-process tier: publish/gather over the flight-recorder KV path
# ---------------------------------------------------------------------------


def publish_ledger(store, rank=None) -> int:
    """Deposit every committed row for ``rank`` (caller's rank by
    default) under ``ledger/rank/<r>/<s>`` — same elastic-KV transport
    as ``flight_recorder.publish_snapshot``. Returns the row count."""
    from . import flight_recorder
    r = _rank() if rank is None else int(rank)
    rows = get_ledger().rows(rank=r)
    for row in rows:
        flight_recorder.publish_component_state(
            store, f"{KV_LEDGER_PREFIX}{r}/{row['step']}", row)
    return len(rows)


def gather_ledgers(store) -> dict:
    """{rank: {step: entries}} for every published ledger row."""
    from . import flight_recorder
    out: dict = {}
    for key, row in flight_recorder.gather_component_states(
            store, KV_LEDGER_PREFIX).items():
        if not isinstance(row, dict) or "entries" not in row:
            continue
        out.setdefault(int(row["rank"]), {})[int(row["step"])] = \
            row["entries"]
    return out


def compare_store(store):
    """Out-of-process comparator: gather every rank's published rows
    and return the first divergence (``{"step", "rank", "tensor",
    "digests"}``) across the steps every rank has published, else
    ``None``. Pure read — raising/alerting policy belongs to the
    caller (this is the multi-process analogue of the in-process
    comparator the thread simulator gets for free)."""
    by_rank = gather_ledgers(store)
    if len(by_rank) < 2:
        return None
    common = sorted(set.intersection(
        *[set(steps) for steps in by_rank.values()]))
    for s in common:
        div = first_divergence({r: by_rank[r][s] for r in by_rank})
        if div is not None:
            return dict(div, step=s)
    return None


def _env_truthy(v) -> bool:
    return v not in (None, "", "0", "false", "False", "no")


if _env_truthy(os.environ.get("PADDLE_LEDGER")):   # pragma: no cover
    enable()
