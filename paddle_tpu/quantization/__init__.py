"""paddle.quantization (reference: ``python/paddle/quantization/`` — QAT
fake-quant layer wrappers, PTQ observers, export to int8 inference;
SURVEY.md §2.2).

TPU-native: fake-quant is a quantize-dequantize pair with a straight-through
gradient (custom VJP: identity inside the clip range) — XLA folds the
round/clamp into the surrounding ops, so QAT costs almost nothing on the MXU.
Conversion produces int8 weight arrays + scales (simulated-int8 execution;
native int8 MXU matmul via a Pallas kernel is the serving-path upgrade).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..autograd.tape import apply
from ..nn.layer import Layer

__all__ = ["QuantConfig", "QAT", "PTQ", "FakeQuanterWithAbsMaxObserver",
           "AbsmaxObserver", "quanted_layers", "QuantedLinear", "calibrate",
           "quantize_linears", "int8_linear"]


# ---------------------------------------------------------------------------
# fake-quant primitive (straight-through estimator)
# ---------------------------------------------------------------------------

@jax.custom_vjp
def _fake_quant(x, scale, qmax):
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    return q * s / qmax


def _fq_fwd(x, scale, qmax):
    out = _fake_quant(x, scale, qmax)
    return out, (x, scale, qmax)


def _fq_bwd(res, g):
    x, scale, qmax = res
    inside = jnp.abs(x) <= jnp.maximum(scale, 1e-8)
    return (jnp.where(inside, g, 0.0), jnp.zeros_like(scale), None)


_fake_quant.defvjp(_fq_fwd, _fq_bwd)


def fake_quant(x, scale, bit_length=8):
    qmax = float(2 ** (bit_length - 1) - 1)
    return apply(lambda a, s: _fake_quant(a, s, qmax), x, scale,
                 op_name="fake_quant")


# ---------------------------------------------------------------------------
# observers / quanters
# ---------------------------------------------------------------------------

class AbsmaxObserver:
    """PTQ observer: running abs-max → scale."""

    def __init__(self, quant_bits=8, moving_rate=0.9):
        self.quant_bits = quant_bits
        self.moving_rate = moving_rate
        self.scale = 0.0

    def observe(self, x):
        m = float(jnp.max(jnp.abs(x._data if isinstance(x, Tensor) else x)))
        if self.scale == 0.0:
            self.scale = m
        else:
            self.scale = (self.moving_rate * self.scale
                          + (1 - self.moving_rate) * m)
        return x

    def _instance(self, layer=None):
        import copy
        return copy.copy(self)


class FakeQuanterWithAbsMaxObserver(AbsmaxObserver):
    """QAT quanter: observe abs-max then fake-quantize (reference
    ``FakeQuanterWithAbsMaxObserverLayer``)."""

    def quantize(self, x):
        self.observe(x)
        return fake_quant(x, Tensor(np.float32(self.scale)),
                          self.quant_bits)


class QuantConfig:
    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._layer_configs = {}

    def add_layer_config(self, layer=None, activation=None, weight=None,
                         **kw):
        for l in (layer if isinstance(layer, (list, tuple)) else [layer]):
            self._layer_configs[id(l)] = (activation, weight)

    def _for(self, layer):
        return self._layer_configs.get(id(layer),
                                       (self.activation, self.weight))


# ---------------------------------------------------------------------------
# quantized layer wrappers
# ---------------------------------------------------------------------------

def _apply_quanter(q, t):
    """QAT quanters fake-quantize; plain PTQ observers only observe."""
    if hasattr(q, "quantize"):
        return q.quantize(t)
    q.observe(t)
    return t

class QuantedLinear(Layer):
    def __init__(self, inner, a_quanter, w_quanter):
        super().__init__()
        self.inner = inner
        self.a_q = a_quanter._instance(inner) if a_quanter else None
        self.w_q = w_quanter._instance(inner) if w_quanter else None
        self._converted = False          # set by convert(): int8 weight path

    def forward(self, x):
        from ..nn import functional as F
        if self._converted and not self.training:
            # weight-only int8 inference: Pallas kernel streams int8 weight
            # tiles + dequantizes in VMEM (ops/pallas/quant_matmul.py).
            # Inference-only — no VJP on the int8 kernel, so keep the op
            # off the tape even when a caller forgot no_grad().
            from ..ops.pallas.quant_matmul import int8_matmul
            from ..autograd.tape import no_grad

            def fn(a, w_q, s, *bias):
                shape = a.shape
                out = int8_matmul(a.reshape(-1, shape[-1]), w_q, s)
                out = out.reshape(*shape[:-1], out.shape[-1])
                return out + bias[0] if bias else out

            args = (x, Tensor(self._w_int8), Tensor(self._w_scale))
            if self.inner.bias is not None:
                args = args + (self.inner.bias,)
            with no_grad():
                return apply(fn, *args, op_name="int8_linear")
        if self.a_q is not None:
            x = _apply_quanter(self.a_q, x)
        w = self.inner.weight
        if self.w_q is not None:
            w = _apply_quanter(self.w_q, w)
        return F.linear(x, w, self.inner.bias)


class QuantedConv2D(Layer):
    def __init__(self, inner, a_quanter, w_quanter):
        super().__init__()
        self.inner = inner
        self.a_q = a_quanter._instance(inner) if a_quanter else None
        self.w_q = w_quanter._instance(inner) if w_quanter else None
        self._converted = False          # set by convert(): int8 weight path

    def forward(self, x):
        if self._converted and not self.training:
            # weight-only int8 conv: the artifact stores the filter as an
            # int8 constant + per-out-channel scales; dequant is one fused
            # convert+mul XLA folds into the conv's weight operand (half
            # the weight bytes of bf16 at rest and on the wire)
            from ..autograd.tape import no_grad
            w = self.inner.weight
            saved, saved_node = w._data, w._grad_node
            deq = (jnp.asarray(self._w_int8, jnp.float32)
                   * jnp.asarray(self._w_scale)[:, None, None, None])
            w._data = deq.astype(saved.dtype)
            try:
                with no_grad():
                    return self.inner(x)
            finally:
                w._data, w._grad_node = saved, saved_node
        if self.a_q is not None:
            x = _apply_quanter(self.a_q, x)
        if self.w_q is None or not hasattr(self.w_q, "quantize"):
            if self.w_q is not None:
                self.w_q.observe(self.inner.weight)   # PTQ calibration
            return self.inner(x)
        # run the conv with the fake-quantized weight temporarily swapped in
        w = self.inner.weight
        saved, saved_node = w._data, w._grad_node
        qw = self.w_q.quantize(w)
        w._data, w._grad_node, w._out_idx = qw._data, qw._grad_node, qw._out_idx
        try:
            return self.inner(x)
        finally:
            w._data, w._grad_node, w._out_idx = saved, saved_node, 0


def quanted_layers():
    from ..nn.layers.common import Linear
    from ..nn.layers.conv import Conv2D
    return {Linear: QuantedLinear, Conv2D: QuantedConv2D}


def _swap_layers(model, make_wrapper):
    table = quanted_layers()
    for name, sub in list(model._sub_layers.items()):
        if sub is None:
            continue
        wrapper_cls = table.get(type(sub))
        if wrapper_cls is not None:
            model._sub_layers[name] = make_wrapper(wrapper_cls, sub)
        else:
            _swap_layers(sub, make_wrapper)
    return model


class QAT:
    """Quantization-aware training driver: ``qat.quantize(model)`` swaps
    Linear/Conv2D for fake-quant wrappers (in place, training continues)."""

    def __init__(self, q_config: QuantConfig):
        self.config = q_config

    def quantize(self, model, inplace=True):
        def make(cls, sub):
            a, w = self.config._for(sub)
            return cls(sub, a, w)

        return _swap_layers(model, make)

    def convert(self, model, inplace=True):
        return convert(model)


class PTQ(QAT):
    """Post-training quantization: observers only (no fake quant in fwd),
    then ``convert`` freezes int8 weights + scales."""


def convert(model):
    """Freeze calibrated quantization: int8 weights + scales. Linear
    layers get per-output-channel scales and route inference through the
    Pallas int8 matmul kernel (``ops/pallas/quant_matmul.py`` — true int8
    weight stream in HBM); Conv2D freezes a per-out-channel int8 filter
    constant (int8 at rest in the exported artifact; XLA chooses the
    runtime dequant placement). Calibrated activation scales (PTQ
    observers) are recorded as ``act_scale`` on each wrapper and exported
    with the model — activations themselves stay float (weight-only
    W8A16/W8A32: on TPU the weight stream, not the activation math, is
    the HBM-bound resource for inference)."""
    from ..ops.pallas.quant_matmul import quantize_weight
    for name, sub in list(model._sub_layers.items()):
        if sub is None:
            continue
        if isinstance(sub, QuantedLinear):
            w = sub.inner.weight
            q, scale = quantize_weight(w._data)
            sub._w_int8 = np.asarray(q)
            sub._w_scale = np.asarray(scale)
            sub._converted = True
            sub.act_scale = float(sub.a_q.scale) if sub.a_q is not None \
                else None
            # back-compat per-tensor attrs (test/inspection surface)
            sub.int8_weight = sub._w_int8
            sub.weight_scale = float(scale.max() * 127.0)
            w._data = jnp.asarray(q, jnp.float32) * scale[None, :]
        elif isinstance(sub, QuantedConv2D):
            w = sub.inner.weight                      # [out_c, in_c, kh, kw]
            amax = jnp.max(jnp.abs(w._data), axis=(1, 2, 3))
            scale = jnp.maximum(amax, 1e-8) / 127.0   # per out-channel
            int_w = np.asarray(
                jnp.clip(jnp.round(w._data / scale[:, None, None, None]),
                         -127, 127), np.int8)
            sub._w_int8 = int_w
            sub._w_scale = np.asarray(scale, np.float32)
            sub._converted = True
            sub.act_scale = float(sub.a_q.scale) if sub.a_q is not None \
                else None
            sub.int8_weight = int_w
            sub.weight_scale = float(scale.max() * 127.0)
            w._data = (jnp.asarray(int_w, jnp.float32)
                       * scale[:, None, None, None]).astype(w._data.dtype)
        else:
            convert(sub)
    return model


def int8_linear(x, w_int8, w_scale, bias=None):
    """Weight-only int8 linear for layers carrying quantized weights:
    flatten leading dims, run the Pallas int8 GEMM (int8 weight stream
    in HBM, per-output-channel dequant in VMEM), restore the shape, add
    bias. Inference-only — no VJP on the int8 kernel, so the op stays
    off the tape even when a caller forgot ``no_grad()``."""
    from ..ops.pallas.quant_matmul import int8_matmul
    from ..autograd.tape import no_grad

    def fn(a, w_q, s, *b):
        shape = a.shape
        out = int8_matmul(a.reshape(-1, shape[-1]), w_q, s)
        out = out.reshape(*shape[:-1], out.shape[-1])
        return out + b[0] if b else out

    args = (x,
            w_int8 if isinstance(w_int8, Tensor) else Tensor(w_int8),
            w_scale if isinstance(w_scale, Tensor) else Tensor(w_scale))
    if bias is not None:
        args = args + (bias,)
    with no_grad():
        return apply(fn, *args, op_name="int8_linear")


def quantize_linears(model):
    """End-to-end int8 weight entry point (``PADDLE_WEIGHT_DTYPE=int8``
    routes the serving engine here): swap every ``nn.Linear``'s weight
    for ``(int8, per-output-channel scale)`` via ``quantize_weight`` so
    its forward runs through the Pallas int8 GEMM. The float master
    weight is replaced by the dequantized int8 values (``convert()``'s
    idiom), so any path still reading ``layer.weight`` — the XLA
    fallback, ``paddle.flops`` — sees numerics consistent with the
    kernel. Composes with int8 KV pages (``kv_dtype="int8"``) for a
    fully-quantized serving config. Returns the number of Linear layers
    quantized."""
    from ..nn.layers.common import Linear
    from ..ops.pallas.quant_matmul import quantize_weight

    count = 0

    def visit(layer):
        nonlocal count
        if isinstance(layer, Linear) and getattr(layer, "_w_int8",
                                                 None) is None:
            w = layer.weight
            q, scale = quantize_weight(w._data)
            layer._w_int8 = np.asarray(q)
            layer._w_scale = np.asarray(scale, np.float32)
            w._data = (jnp.asarray(q, jnp.float32)
                       * scale[None, :]).astype(w._data.dtype)
            count += 1
        for sub in layer._sub_layers.values():
            if sub is not None:
                visit(sub)

    visit(model)
    return count


def calibrate(model, data, steps=None):
    """PTQ calibration driver (reference: the sample-data loop of
    ``PTQ``/static post-training quantization): run ``data`` (a DataLoader
    or any iterable of batches / (batch, label) pairs) through the
    observer-wrapped ``model`` in eval mode so every activation observer
    sees real ranges. Returns the number of batches observed."""
    from ..autograd.tape import no_grad
    was_training = model.training
    model.eval()
    n = 0
    try:
        with no_grad():
            for item in data:
                x = item[0] if isinstance(item, (tuple, list)) else item
                model(x if isinstance(x, Tensor) else Tensor(jnp.asarray(x)))
                n += 1
                if steps is not None and n >= steps:
                    break
    finally:
        if was_training:
            model.train()
    return n
