"""paddle.inference (reference: ``paddle/fluid/inference/`` —
``AnalysisPredictor``: load pdmodel → IR fusion passes → run; Python surface
``Config``/``create_predictor``/zero-copy handles; SURVEY.md §2.1 "Inference
engine", §3.6).

TPU-native: the saved artifact is serialized StableHLO (paddle.jit.save) —
already fused/optimized by XLA at export; the predictor deserializes and
executes the AOT program. The reference's IR-fusion pass pipeline and
TensorRT engine have no role: XLA is both. Zero-copy IO maps to device
arrays held on the handle until copy_to_cpu().
"""
from __future__ import annotations

import os
import time

import numpy as np
import jax

from ..framework.core import Tensor


class Config:
    """paddle_infer.Config(prog_file, params_file) or Config(model_dir)."""

    def __init__(self, prog_file=None, params_file=None):
        if prog_file is not None and params_file is None \
                and os.path.isdir(prog_file):
            # model_dir flavor: find the single prefix inside
            cands = [f[: -len(".pdmodel.stablehlo")]
                     for f in os.listdir(prog_file)
                     if f.endswith(".pdmodel.stablehlo")]
            if not cands:
                raise FileNotFoundError(
                    f"no .pdmodel.stablehlo in {prog_file}")
            self.prefix = os.path.join(prog_file, cands[0])
        else:
            # accept either the exported prefix or the model file path
            p = prog_file or ""
            for suf in (".pdmodel.stablehlo", ".pdmodel"):
                if p.endswith(suf):
                    p = p[: -len(suf)]
            self.prefix = p
        self._use_tpu = True
        self.mem_opt = True
        self.ir_debug = False
        self.ir_optim = False
        self.profile = False

    # knobs kept for API compat (XLA supersedes them)
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_tpu = True

    def disable_gpu(self):
        self._use_tpu = False

    def enable_memory_optim(self):
        self.mem_opt = True

    def switch_ir_optim(self, flag=True):
        """Run the program-level pass pipeline (canonicalize+cse via
        ``static.pir``) on the loaded StableHLO before execution. XLA
        optimizes again at compile time regardless; this knob exercises
        the PIR-analogue pass infra and slims the program pre-compile."""
        self.ir_optim = bool(flag)

    def switch_ir_debug(self, flag=True):
        """Dump the loaded program's StableHLO text next to the model
        (``<prefix>.hlo.txt``) — the IR-inspection knob made real."""
        self.ir_debug = bool(flag)

    def enable_profile(self):
        """Collect per-run wall times; read via Predictor.get_profile()."""
        self.profile = True

    def set_optim_cache_dir(self, path):
        """Persistent compilation cache (reference: the optimization
        cache dir) — compiled executables survive process restarts."""
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # default min-compile-time threshold (1s) silently skips small
        # models — the knob must persist everything it is asked to
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    def enable_tensorrt_engine(self, *a, **kw):
        raise NotImplementedError(
            "TensorRT is CUDA-only; the TPU build runs XLA-compiled "
            "StableHLO (already fused)")

    def set_cpu_math_library_num_threads(self, n):
        pass                        # XLA's host runtime sizes its own pool


class _IOHandle:
    """Zero-copy style IO handle (reference ZeroCopyTensor)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def reshape(self, shape):
        pass

    def copy_from_cpu(self, arr):
        self._value = np.ascontiguousarray(arr)

    def copy_to_cpu(self):
        v = self._value
        if isinstance(v, jax.Array):
            return np.asarray(jax.device_get(v))
        return np.asarray(v)

    def share_external_data(self, arr):
        self.copy_from_cpu(np.asarray(arr))


class Predictor:
    def __init__(self, config: Config):
        from ..jit import load as jit_load
        self._layer = jit_load(config.prefix)
        if getattr(config, "ir_optim", False):
            # best-effort: the knob's old contract was a no-op ("XLA always
            # optimizes") — a pass-infra failure must degrade, not brick
            # model load
            try:
                from ..static.pir import optimize_exported
                self._layer._exported = optimize_exported(
                    self._layer._exported)
            except Exception as e:
                import warnings
                warnings.warn(f"ir_optim: pass pipeline unavailable "
                              f"({e!r}); serving the unoptimized program",
                              RuntimeWarning)
        specs = self._layer._meta.get("input_specs", [])
        names = []
        for i, s in enumerate(specs):
            n = s[2] if len(s) > 2 and s[2] else f"input_{i}"
            while n in names:            # spec names may collide with
                n += "_"                 # positional fallbacks — dedupe
            names.append(n)
        self._inputs = [_IOHandle(n) for n in (names or ["input_0"])]
        self._outputs = []
        self._profile = [] if getattr(config, "profile", False) else None
        if getattr(config, "ir_debug", False):
            # IR debug dump is best-effort diagnostics: an unwritable
            # model dir must not take down predictor construction
            try:
                try:
                    text = self._layer._exported.mlir_module()
                except Exception:
                    text = str(self._layer._exported)
                with open(config.prefix + ".hlo.txt", "w") as f:
                    f.write(text)
            except OSError as e:
                import warnings
                warnings.warn(f"ir_debug: cannot write HLO dump next to "
                              f"the model ({e})", RuntimeWarning)

    def get_profile(self):
        """Per-run wall times (s) collected under Config.enable_profile."""
        if self._profile is None:
            raise RuntimeError("call Config.enable_profile() before "
                               "create_predictor")
        t = np.asarray(self._profile)
        return {"runs": len(t),
                "total_s": float(t.sum()) if len(t) else 0.0,
                "mean_s": float(t.mean()) if len(t) else 0.0,
                "p50_s": float(np.percentile(t, 50)) if len(t) else 0.0,
                "p99_s": float(np.percentile(t, 99)) if len(t) else 0.0}

    def get_input_names(self):
        return [h.name for h in self._inputs]

    def get_input_handle(self, name):
        for h in self._inputs:
            if h.name == name:
                return h
        raise KeyError(name)

    def run(self, inputs=None):
        t0 = time.perf_counter() if self._profile is not None else None
        if inputs is not None:          # list-of-arrays convenience form
            for h, a in zip(self._inputs, inputs):
                h.copy_from_cpu(np.asarray(a))
        args = [Tensor(h._value) for h in self._inputs]
        out = self._layer(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._outputs = []
        for i, o in enumerate(outs):
            h = _IOHandle(f"output_{i}")
            h._value = o._data if isinstance(o, Tensor) else o
            self._outputs.append(h)
        if self._profile is not None:
            jax.block_until_ready([h._value for h in self._outputs])
            self._profile.append(time.perf_counter() - t0)
        if inputs is not None:
            return [h.copy_to_cpu() for h in self._outputs]
        return True

    def get_output_names(self):
        return [h.name for h in self._outputs] or ["output_0"]

    def get_output_handle(self, name):
        for h in self._outputs:
            if h.name == name:
                return h
        raise KeyError(name)


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


def get_version():
    import paddle_tpu
    return paddle_tpu.__version__


class PrecisionType:
    Float32 = 0
    Half = 1
    Int8 = 2

from .serving import ServingEngine, ContinuousServingEngine  # noqa: E402,F401
from .speculative import (NGramDrafter, DraftModelDrafter,   # noqa: E402,F401
                          make_drafter)
from .fleet import (ServingRouter, Rejected,                 # noqa: E402,F401
                    TenantQuotaManager, ROUTER_POLICIES,
                    FleetController, ControllerAction,
                    ReplayHarness, ReplayTrace, make_trace)
