"""Serving fleet router: N continuous-batching engine replicas behind one
front end (ROADMAP item 2; the Gemma-on-TPU serving comparison, arxiv
2605.25645, argues TPU serving economics are won at exactly this
orchestration layer — replica routing, cache locality, KV transfer).

Four pillars:

* **Prefix-cache-affinity routing** — every request's prompt is hashed
  into its ``block_hash_chain`` (PR 4); the router keeps a per-replica
  hash-frontier map and scores replicas by ``affinity * matched_tokens -
  (1 - affinity) * load_tokens`` (``PADDLE_FLEET_AFFINITY``), so requests
  sharing a system prompt land on the replica already holding those KV
  pages and everything else falls back to least-loaded (live token
  occupancy accounted router-side from in-flight footprints, cross-checked
  against the engine's flight-recorder state provider).
* **Prefill/decode disaggregation** (``PADDLE_FLEET_DISAGG=1``) —
  dedicated prefill replicas run the chunked/ragged prefill, then the
  finished KV pages travel to a decode replica via
  ``SlotPagedKVCache.export_pages``/``import_pages`` (re-registered under
  the receiver's prefix index, so greedy decode is bit-identical to
  colocated serving).
* **Per-tenant admission quotas** — fleet-wide token buckets over the
  elastic KV store's atomic ``incr`` (:mod:`.quota`); over-budget and
  queue-full requests fail fast with a structured ``Rejected(reason)``.
* **Replica health & drain** — replicas heartbeat engine state through
  the flight-recorder KV publish path; a missed-TTL replica is marked
  dead and hard-aborted, its queued and in-flight requests requeue to
  survivors (decode restarts from the cached prefix; tokens are delivered
  to the caller exactly once, on the attempt that completes), and a
  drained replica can rejoin.

Thread-per-replica on the simulator tier; on device tiers each replica is
its own process and the same router logic coordinates over ``TcpKVStore``.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import OrderedDict

import numpy as np

from ...distributed import fault as _fault
from ...framework.core import Tensor
from ...models.generation import block_hash_chain
from ...profiler import request_trace as _rt
from ...profiler import ledger as _ledger
from ..serving import ContinuousServingEngine, _Control, _engine_state
from .quota import Rejected, TenantQuotaManager

#: per-request requeue budget (PADDLE_FLEET_MAX_ATTEMPTS): a request
#: whose replica dies under it requeues at most this many times before
#: failing with a structured Rejected(reason="attempts_exhausted")
DEFAULT_FLEET_MAX_ATTEMPTS = 3

#: every routing-decision label the router can emit (the
#: ``paddle_fleet_routed_total{policy=}`` values); tools/check_inventory.py
#: requires each to be exercised by a test
ROUTER_POLICIES = ("affinity", "balance", "round_robin", "disagg")

#: default affinity-vs-balance weight (PADDLE_FLEET_AFFINITY): 1.0 always
#: follows the longest matching hash chain, 0.0 is pure least-loaded
DEFAULT_FLEET_AFFINITY = 0.9

#: per-replica frontier map cap (digests); oldest entries age out
_FRONTIER_CAP = 8192

_TELEMETRY = None


def _telemetry():
    global _TELEMETRY
    if _TELEMETRY is None:
        from ...profiler.telemetry import get_registry
        r = get_registry()
        _TELEMETRY = {
            "routed": r.counter(
                "paddle_fleet_routed_total",
                "requests routed, by deciding policy",
                labels=("policy",)),
            "requeues": r.counter(
                "paddle_fleet_requeues_total",
                "requests requeued to a surviving replica",
                labels=("reason",)),
            "rejected": r.counter(
                "paddle_fleet_rejected_total",
                "requests refused at admission (structured Rejected)",
                labels=("tenant", "reason")),
            "hit_rate": r.gauge(
                "paddle_fleet_affinity_hit_rate",
                "fraction of prefix-matchable requests routed to the "
                "replica holding the longest chain"),
            "qdepth": r.gauge(
                "paddle_fleet_replica_queue_depth",
                "requests waiting inside each replica's engine queue",
                labels=("replica",)),
            "alive": r.gauge(
                "paddle_fleet_replicas_alive",
                "replicas currently routable"),
            "handoff": r.counter(
                "paddle_fleet_handoff_pages_total",
                "KV pages moved prefill->decode (disaggregation)"),
        }
    return _TELEMETRY


class _ReplicaDied(Exception):
    """Internal: the attempt's replica died under it — requeue."""

    def __init__(self, replica, cause):
        self.replica = replica
        self.cause = cause
        super().__init__(f"replica {replica.id} died: {cause}")


class _Ticket:
    """One client request inside the router. Tokens are delivered to the
    caller exactly once — only the attempt that matches ``attempt`` at
    completion may set the result, so a requeued request's superseded
    attempt (which restarts decode from the cached prefix on a survivor)
    can never double-deliver."""

    _ids = itertools.count()

    def __init__(self, ids, max_new_tokens, tenant, chain, timeout, kwargs,
                 trace=None):
        self.id = next(self._ids)
        self.ids = ids                      # np [1, s]
        self.max_new_tokens = int(max_new_tokens)
        self.tenant = tenant
        self.chain = chain
        self.trace = trace                  # request-trace ctx (or None)
        self.kwargs = kwargs
        self.deadline = (None if timeout is None
                         else time.monotonic() + float(timeout))
        self.done = threading.Event()
        self.result = None
        self.error = None
        self.attempt = 0
        self.replica = None
        self.cancelled = False

    def remaining(self):
        if self.deadline is None:
            return None
        return self.deadline - time.monotonic()


class Replica:
    """Router-side handle for one engine replica: its role, liveness,
    hash-frontier map, and in-flight token footprints."""

    def __init__(self, rid, engine, role="mixed"):
        self.id = str(rid)
        self.engine = engine
        self.role = role                # mixed | prefill | decode
        self.alive = False
        self.draining = False
        self.heartbeating = True
        self.exporter = None            # per-replica TelemetryServer
        self.frontier: OrderedDict = OrderedDict()   # digest -> None (LRU)
        self.inflight: dict = {}        # ticket id -> token footprint

    @property
    def load_tokens(self):
        """Live token-budget occupancy: uncached-prompt + decode-budget
        tokens of everything routed here and not yet finished."""
        return sum(self.inflight.values())

    @property
    def queue_depth(self):
        return self.engine._q.qsize()

    def matched_tokens(self, chain):
        """Tokens covered by the LEADING run of ``chain`` digests this
        replica is believed to hold (the affinity score's cache term)."""
        n = 0
        for d in chain:
            if d not in self.frontier:
                break
            n += 1
        return n * self.engine.page_size

    def note_chain(self, chain):
        for d in chain:
            self.frontier[d] = None
            self.frontier.move_to_end(d)
        while len(self.frontier) > _FRONTIER_CAP:
            self.frontier.popitem(last=False)

    def kill(self):
        """Simulate replica process death: stop heartbeating (the router
        health loop will miss the TTL, mark it dead, and requeue its
        work). The engine object itself is aborted by the router."""
        self.heartbeating = False


class ServingRouter:
    """Fleet front end over N :class:`ContinuousServingEngine` replicas.

    router = ServingRouter(model, num_replicas=3, store=MemKVStore())
    router.start()
    out = router.generate(prompt_ids, max_new_tokens=64, tenant="acme")
    router.stop()

    ``generate`` blocks like the engine API and returns the same greedy
    output a single engine would (bit-identical — routing, handoff and
    requeue never change tokens). Admission failures raise the structured
    :class:`Rejected` immediately instead of timing out.
    """

    def __init__(self, model=None, num_replicas=2, engines=None,
                 engine_kwargs=None, store=None, policy="affinity",
                 affinity=None, disagg=None, prefill_replicas=1,
                 quota=None, tenant_quotas=None, max_queue_tokens=None,
                 heartbeat_interval=0.5, heartbeat_ttl=None,
                 health_interval=None, namespace="fleet",
                 max_attempts=None):
        if engines is None:
            if model is None:
                raise ValueError("ServingRouter needs a model or engines=")
            kw = dict(engine_kwargs or {})
            engines = [ContinuousServingEngine(model, **kw)
                       for _ in range(int(num_replicas))]
        if policy not in ("affinity", "balance", "round_robin"):
            raise ValueError(f"unknown router policy {policy!r} "
                             f"(one of affinity|balance|round_robin; "
                             f"disagg is the PADDLE_FLEET_DISAGG mode)")
        self.policy = policy
        if affinity is None:
            affinity = float(os.environ.get("PADDLE_FLEET_AFFINITY",
                                            str(DEFAULT_FLEET_AFFINITY)))
        self.affinity = min(max(float(affinity), 0.0), 1.0)
        if disagg is None:
            disagg = os.environ.get("PADDLE_FLEET_DISAGG", "0") == "1"
        self.disagg = bool(disagg)
        if max_queue_tokens is None:
            max_queue_tokens = int(os.environ.get(
                "PADDLE_FLEET_MAX_QUEUE_TOKENS", "0"))
        self.max_queue_tokens = int(max_queue_tokens)
        if max_attempts is None:
            max_attempts = int(os.environ.get(
                "PADDLE_FLEET_MAX_ATTEMPTS",
                str(DEFAULT_FLEET_MAX_ATTEMPTS)))
        self.max_attempts = max(int(max_attempts), 1)
        # per-request decode cap the FleetController lowers under
        # sustained SLO burn (graceful degradation) and restores on
        # recovery; None = serve what the client asked for
        self.max_new_cap = None
        if store is None:
            from ...distributed.fleet.elastic.tcp_kv import MemKVStore
            store = MemKVStore()
        self.store = store
        self.ns = namespace
        roles = ["mixed"] * len(engines)
        if self.disagg:
            if len(engines) < 2:
                raise ValueError("disaggregation needs >= 2 replicas")
            n_pre = min(max(int(prefill_replicas), 1), len(engines) - 1)
            roles = (["prefill"] * n_pre
                     + ["decode"] * (len(engines) - n_pre))
        self.replicas = [Replica(f"r{i}", eng, role)
                         for i, (eng, role) in enumerate(zip(engines,
                                                             roles))]
        for eng in engines:
            # the router owns each replica's telemetry exporter (named
            # by replica id, discovered through self.store) — the
            # engine's own standalone exporter must not double-bind
            eng._exporter_managed = True
        self._rid_counter = len(self.replicas)   # add_replica ids
        self.page_size = int(self.replicas[0].engine.page_size)
        if quota is None:
            default_cap = int(os.environ.get("PADDLE_FLEET_TENANT_TOKENS",
                                             "0"))
            if tenant_quotas or default_cap > 0:
                quota = TenantQuotaManager(
                    store, capacity=default_cap, namespace=namespace,
                    overrides=tenant_quotas)
        self.quota = quota
        self.heartbeat_interval = float(heartbeat_interval)
        # generous default: on the interpret-mode simulator tier the GIL
        # can starve heartbeat threads for whole forwards, and a spurious
        # fleet-wide death is far worse than slow detection
        self.heartbeat_ttl = float(
            heartbeat_ttl if heartbeat_ttl is not None
            else os.environ.get("PADDLE_FLEET_HEARTBEAT_TTL_S",
                                str(10.0 * self.heartbeat_interval)))
        self.health_interval = float(
            health_interval if health_interval is not None
            else max(self.heartbeat_interval / 2.0, 0.02))
        self._lock = threading.RLock()
        self._stop_evt = threading.Event()
        self._threads: list = []
        self._started = False
        self._rr_next = 0
        self._flight_key = None
        self._models_training: list = []
        # counters mirrored by the state provider (tests read these too)
        self.routed_total = 0
        self.requeues_total = 0
        self.rejected_total = 0
        self.affinity_matchable = 0
        self.affinity_hits = 0
        self.handoff_pages = 0
        self.handoff_host_pages = 0    # served from the exporter's host tier

    # -- lifecycle ----------------------------------------------------------
    def _hb_key(self, replica):
        return f"{self.ns}/replica/{replica.id}"

    def _start_exporter(self, replica):
        """Per-replica telemetry endpoint (ISSUE 15): ephemeral port,
        announced under ``<ns>/telemetry/<rid>`` through the router's KV
        store. A no-op (None) when PADDLE_TELEMETRY_PORT is unset."""
        if replica.exporter is not None:
            return replica.exporter
        from ...profiler import exporter as _exp
        replica.exporter = _exp.maybe_start_exporter(
            instance=replica.id, store=self.store,
            key_prefix=f"{self.ns}/telemetry/", ephemeral=True)
        return replica.exporter

    def _stop_exporter(self, replica, unpublish=True):
        exp, replica.exporter = replica.exporter, None
        if exp is None:
            return
        if unpublish:
            exp.stop(unpublish=True)
        else:
            # hard kill: the endpoint goes dark but its discovery key
            # stays — the FleetScraper must observe it going STALE, the
            # way a dead process's endpoint would; run off-thread so the
            # health loop never stalls on the server join
            threading.Thread(target=lambda: exp.stop(unpublish=False),
                             daemon=True).start()

    def start(self):
        if self._started:
            return self
        # the router owns eval-mode for the shared model(s): a dying
        # replica's teardown must never flip training mode back on while
        # survivors are still serving
        seen = {}
        for r in self.replicas:
            m = r.engine.model
            if id(m) not in seen:
                seen[id(m)] = (m, m.training)
                m.eval()
        self._models_training = list(seen.values())
        self._stop_evt.clear()
        for r in self.replicas:
            r.engine.start()
            r.alive = True
            r.heartbeating = True
            self._start_exporter(r)
            self._publish_heartbeat(r)     # liveness visible before the
            #                                health loop takes its first look
        from ...profiler import flight_recorder as _flight
        self._flight_key = f"serving_fleet_{id(self):x}"
        _flight.register_state_provider(self._flight_key, self._state)
        self._started = True
        for r in self.replicas:
            self._spawn_heartbeat(r)
        t = threading.Thread(target=self._health_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def stop(self):
        if not self._started:
            return
        self._started = False
        self._stop_evt.set()
        for t in self._threads:
            t.join(timeout=10)
        self._threads = []
        for r in self.replicas:
            if r.alive:
                r.engine.stop()
            r.alive = False
            self._stop_exporter(r, unpublish=True)
        if self._flight_key is not None:
            from ...profiler import flight_recorder as _flight
            _flight.unregister_state_provider(self._flight_key)
            self._flight_key = None
        for m, was_training in self._models_training:
            if was_training:
                m.train()
        self._models_training = []

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- heartbeat / health -------------------------------------------------
    def _publish_heartbeat(self, replica):
        from ...profiler import flight_recorder as _flight
        state = _engine_state(replica.engine)
        state.update(replica=replica.id, role=replica.role,
                     draining=replica.draining,
                     load_tokens=replica.load_tokens,
                     inflight=len(replica.inflight))
        _flight.publish_component_state(self.store, self._hb_key(replica),
                                        state)

    def _spawn_heartbeat(self, replica):
        t = threading.Thread(target=self._heartbeat_loop, args=(replica,),
                             daemon=True)
        t.start()
        self._threads.append(t)

    def _heartbeat_loop(self, replica):
        tele = _telemetry()
        while not self._stop_evt.wait(self.heartbeat_interval):
            if replica not in self.replicas:
                return               # removed (scaled down to warm pool)
            if replica.heartbeating and replica.alive:
                try:
                    self._publish_heartbeat(replica)
                except Exception:      # a flaky store must not kill the hb
                    pass
            tele["qdepth"].set(replica.queue_depth, replica=replica.id)

    def _health_loop(self):
        tele = _telemetry()
        while not self._stop_evt.wait(self.health_interval):
            for r in self.replicas:
                if not r.alive or r.draining:
                    continue
                age = self.store.age(self._hb_key(r))
                if age is None or age > self.heartbeat_ttl:
                    self._on_replica_dead(r, reason="heartbeat_ttl")
            tele["alive"].set(sum(r.alive for r in self.replicas))

    def _on_replica_dead(self, replica, reason):
        with self._lock:
            if not replica.alive:
                return
            replica.alive = False
            replica.heartbeating = False
            # engine restart rebuilds the KV cache from scratch: the
            # router's belief about what it holds dies with it
            replica.frontier.clear()
        from ...profiler import flight_recorder as _flight
        _flight.record_event("fleet_replica_dead", replica=replica.id,
                             reason=reason)
        # the dead replica's telemetry endpoint dies WITH it (its
        # discovery key stays — the scraper sees staleness, not absence)
        self._stop_exporter(replica, unpublish=False)
        # hard abort (no drain): blocked dispatch threads get their
        # requests failed NOW and requeue to survivors; run off-thread so
        # the health loop never stalls on the engine join
        threading.Thread(target=replica.engine.abort, daemon=True).start()

    # -- ops hooks ----------------------------------------------------------
    def kill_replica(self, rid, hard=True):
        """Chaos hook. ``hard`` models a dead process: the engine aborts
        now and blocked dispatches requeue immediately via the fast
        attempt-failure path. ``hard=False`` only silences the heartbeat,
        leaving detection entirely to the health loop's missed-TTL sweep
        (the zombie-replica scenario)."""
        r = self._replica(rid)
        r.kill()
        if hard:
            self._on_replica_dead(r, reason="killed")

    def drain(self, rid, timeout=60.0):
        """Graceful removal: stop routing to the replica, wait for its
        in-flight work, stop the engine. The replica can ``rejoin``."""
        r = self._replica(rid)
        with self._lock:
            r.draining = True
        deadline = time.monotonic() + timeout
        while r.inflight and time.monotonic() < deadline:
            time.sleep(0.01)
        if r.inflight:
            raise TimeoutError(f"replica {rid} still has "
                               f"{len(r.inflight)} in-flight requests")
        r.engine.stop()
        with self._lock:
            r.alive = False
            r.frontier.clear()
        self._stop_exporter(r, unpublish=True)
        return r

    def rejoin(self, rid, role=None):
        """Bring a drained (or dead-and-recovered) replica back into the
        routable set with a fresh engine lifecycle. ``role=`` rejoins it
        under a new role — the drain -> rejoin-with-new-role path is the
        FleetController's role-flip actuator."""
        r = self._replica(rid)
        if r.alive:
            return r
        if role is not None:
            if role not in ("mixed", "prefill", "decode"):
                raise ValueError(f"unknown replica role {role!r}")
            r.role = role
        r.engine.start()
        with self._lock:
            r.alive = True
            r.draining = False
            r.heartbeating = True
        self._start_exporter(r)        # fresh endpoint (fresh ephemeral
        #                                port), re-announced for recovery
        self._publish_heartbeat(r)
        return r

    def add_replica(self, engine, role="mixed", rid=None):
        """Join a spare engine to the fleet (the controller's scale-up
        actuator: warm-pool engines enter here). Started routers start
        the engine and begin heartbeating immediately; ids are never
        reused, so a scaled-down-then-up replica is a fresh identity."""
        if role not in ("mixed", "prefill", "decode"):
            raise ValueError(f"unknown replica role {role!r}")
        with self._lock:
            if rid is None:
                rid = f"r{self._rid_counter}"
                self._rid_counter += 1
            elif any(r.id == str(rid) for r in self.replicas):
                raise ValueError(f"replica id {rid!r} already in fleet")
            r = Replica(rid, engine, role)
            self.replicas.append(r)
        engine._exporter_managed = True
        if self._started:
            r.engine.start()
            with self._lock:
                r.alive = True
                r.heartbeating = True
            self._start_exporter(r)
            self._publish_heartbeat(r)
            self._spawn_heartbeat(r)
        return r

    def remove_replica(self, rid):
        """Detach a drained/dead replica from the fleet and return its
        engine (the controller's scale-down actuator parks it back in
        the warm pool). Refuses to remove a live replica — drain
        first."""
        r = self._replica(rid)
        if r.alive:
            raise RuntimeError(f"replica {rid} is alive: drain() before "
                               "remove_replica()")
        with self._lock:
            self.replicas.remove(r)
        return r.engine

    def _replica(self, rid):
        for r in self.replicas:
            if r.id == str(rid):
                return r
        raise KeyError(f"no replica {rid!r}")

    # -- client API ---------------------------------------------------------
    def generate(self, input_ids, max_new_tokens=32, tenant="default",
                 timeout=None, chain=None, **kwargs):
        """Route one request through the fleet and block for its output
        (a ``Tensor``, prompt included — the engine contract). Raises
        :class:`Rejected` on admission failure, ``TimeoutError`` when
        ``timeout`` elapses."""
        if not self._started:
            raise RuntimeError("ServingRouter not started (call start())")
        ids = (input_ids.numpy() if isinstance(input_ids, Tensor)
               else np.asarray(input_ids))
        if ids.ndim == 1:
            ids = ids[None]
        if ids.shape[0] != 1:
            raise ValueError("the fleet router takes one sequence per "
                             "request (batch client-side fan-out belongs "
                             "above the router)")
        if chain is None:
            chain = block_hash_chain(ids[0], self.page_size)
        cap = self.max_new_cap
        if cap is not None and int(cap) > 0:
            # graceful degradation: under sustained burn the controller
            # lowers the per-request decode budget before compliant
            # tenants miss SLO (restored when the burn clears)
            max_new_tokens = min(int(max_new_tokens), int(cap))
        cost = ids.shape[1] + int(max_new_tokens)
        tele = _telemetry()
        # the trace is minted BEFORE admission: rejections must trace too
        ctx = _rt.start_request(tenant=str(tenant), source="router",
                                prompt_tokens=int(ids.shape[1]),
                                max_new_tokens=int(max_new_tokens))
        try:
            with _rt.span(ctx, "admission", tenant=str(tenant),
                          cost=cost) as adm:
                with self._lock:
                    fleet_empty = not any(r.alive and not r.draining
                                          for r in self.replicas)
                if fleet_empty:
                    # fast-fail: an empty fleet must reject NOW, not
                    # after the client burns its whole timeout (and
                    # before the quota charges a request that cannot
                    # possibly run)
                    raise Rejected("no_replicas", tenant=tenant,
                                   detail="every replica dead or "
                                          "draining")
                if self.quota is not None:
                    used = self.quota.admit(tenant, cost)
                    if used is not None and adm is not None:
                        adm.tags["quota_used"] = used
                self._check_backpressure(tenant)
        except Rejected as e:
            with self._lock:
                self.rejected_total += 1
            tele["rejected"].inc(tenant=str(tenant), reason=e.reason)
            _rt.add_event(ctx, "rejected", reason=e.reason)
            _rt.finish_request(ctx, status="rejected", reason=e.reason)
            raise
        ticket = _Ticket(ids, max_new_tokens, tenant, chain, timeout,
                         kwargs, trace=ctx)
        worker = threading.Thread(target=self._dispatch, args=(ticket,),
                                  daemon=True)
        worker.start()
        if not ticket.done.wait(timeout):
            with self._lock:
                ticket.cancelled = True
            # a timed-out request must not vanish from observability:
            # it traces as terminal AND counts next to the admission
            # rejections (reason label keeps the paths apart)
            tele["rejected"].inc(tenant=str(tenant), reason="timeout")
            _rt.add_event(ctx, "timeout")
            _rt.finish_request(ctx, status="timeout")
            raise TimeoutError("fleet generate timed out")
        if ticket.error is not None:
            if isinstance(ticket.error, Rejected):
                # dispatch-side rejection (no healthy replica): same
                # accounting as the admission-time path
                with self._lock:
                    self.rejected_total += 1
                tele["rejected"].inc(tenant=str(tenant),
                                     reason=ticket.error.reason)
                _rt.add_event(ctx, "rejected", reason=ticket.error.reason)
                _rt.finish_request(ctx, status="rejected",
                                   reason=ticket.error.reason)
            elif isinstance(ticket.error, TimeoutError):
                # the ENGINE-side deadline can fire a breath before the
                # router's own wait expires — same terminal outcome,
                # same accounting as the wait-expired path above (the
                # trace must say "timeout" regardless of which side of
                # the race noticed first)
                tele["rejected"].inc(tenant=str(tenant), reason="timeout")
                _rt.add_event(ctx, "timeout")
                _rt.finish_request(ctx, status="timeout")
            else:
                _rt.finish_request(ctx, status="error",
                                   error=type(ticket.error).__name__)
            raise ticket.error
        if _ledger.is_enabled():
            # token-stream attestation: a requeued or disagg request's
            # delivered stream must be digest-consistent across every
            # attempt/replica that produced tokens for it — the
            # at-most-once resume contract, checked at runtime
            try:
                dg = _ledger.attest_delivery(ctx.trace_id if ctx else None,
                                             ticket.attempt)
            except _ledger.DivergenceError as e:
                _rt.add_event(ctx, "attestation_failed", tensor=e.tensor)
                _rt.finish_request(ctx, status="error",
                                   error="DivergenceError")
                raise
            _rt.add_event(ctx, "delivered", attempt=ticket.attempt,
                          **({"token_digest": dg} if dg else {}))
        else:
            _rt.add_event(ctx, "delivered", attempt=ticket.attempt)
        _rt.finish_request(ctx, status="ok")
        return Tensor(ticket.result)

    def _check_backpressure(self, tenant):
        if self.max_queue_tokens <= 0:
            return
        with self._lock:
            elig = [r for r in self.replicas
                    if r.alive and not r.draining and r.role != "prefill"]
            if elig and min(r.load_tokens for r in elig) \
                    >= self.max_queue_tokens:
                raise Rejected(
                    "queue_full", tenant=tenant,
                    detail=f"every replica over "
                           f"{self.max_queue_tokens} queued tokens")

    # -- dispatch -----------------------------------------------------------
    def _dispatch(self, ticket):
        tele = _telemetry()
        while not ticket.done.is_set():
            if ticket.cancelled:
                return
            rem = ticket.remaining()
            if rem is not None and rem <= 0:
                ticket.error = TimeoutError("fleet generate timed out")
                ticket.done.set()
                return
            try:
                out = (self._run_disagg(ticket) if self.disagg
                       else self._run_colocated(ticket))
            except _ReplicaDied as e:
                # fast-path detection: the attempt's replica is gone even
                # if the TTL sweep hasn't fired yet
                self._on_replica_dead(e.replica, reason="attempt_failed")
                if ticket.attempt >= self.max_attempts:
                    # requeue budget spent: a request ping-ponging across
                    # dying replicas fails with a structured terminal
                    # rejection instead of retrying until the client
                    # timeout (generate() finishes the trace)
                    _rt.add_event(ticket.trace, "requeue_budget_exhausted",
                                  attempts=ticket.attempt,
                                  replica=e.replica.id)
                    ticket.error = Rejected(
                        "attempts_exhausted", tenant=ticket.tenant,
                        detail=f"{ticket.attempt} attempts, every "
                               f"replica died underneath")
                    ticket.done.set()
                    return
                with self._lock:
                    self.requeues_total += 1
                tele["requeues"].inc(reason="replica_dead")
                _rt.add_event(ticket.trace, "requeue",
                              reason="replica_dead", replica=e.replica.id,
                              attempt=ticket.attempt)
                continue                      # re-route to a survivor
            except Exception as e:            # noqa: BLE001 — to caller
                ticket.error = e
                ticket.done.set()
                return
            with self._lock:
                if ticket.cancelled:
                    return                    # at-most-once: discard
                ticket.result = out
            ticket.done.set()
            return

    def _run_attempt(self, ticket, replica, max_new_tokens):
        """One engine call, with the replica's in-flight footprint held
        for its duration and death translated to ``_ReplicaDied``."""
        try:
            out = replica.engine.generate(
                ticket.ids, max_new_tokens=max_new_tokens,
                timeout=ticket.remaining(), trace=ticket.trace,
                **ticket.kwargs)
            return np.asarray(out.numpy())
        except TimeoutError:
            raise
        except Exception as e:
            if not replica.alive or replica.engine._aborted:
                raise _ReplicaDied(replica, e) from e
            raise
        finally:
            with self._lock:
                replica.inflight.pop(ticket.id, None)

    def _run_colocated(self, ticket):
        with self._lock:
            replica = self._route_locked(ticket, roles=("mixed",))
        return self._run_attempt(ticket, replica, ticket.max_new_tokens)

    def _run_disagg(self, ticket):
        tele = _telemetry()
        # phase 1 — prefill replica fills + commits the prompt's blocks
        # (max_new_tokens=1 is pure prefill in the ragged scheduler: the
        # single token samples from the final prefill chunk's logits, so
        # the replica never runs a decode step)
        with self._lock:
            pre = self._route_locked(ticket, roles=("prefill",),
                                     label="disagg")
        blob = None
        try:
            self._run_attempt(ticket, pre, max_new_tokens=1)
            chain = ticket.chain
            with _rt.span(ticket.trace, "handoff_export",
                          replica=pre.id):
                blob = pre.engine.run_on_loop(
                    lambda eng: eng._cache.export_pages(chain))
        except _ReplicaDied:
            # degraded mode: the decode replica simply prefills the whole
            # prompt itself — correctness never depends on the handoff
            self._on_replica_dead(pre, reason="attempt_failed")
            with self._lock:
                self.requeues_total += 1
            tele["requeues"].inc(reason="replica_dead")
            _rt.add_event(ticket.trace, "requeue", reason="replica_dead",
                          replica=pre.id, attempt=ticket.attempt)
        except Exception:
            blob = None                      # handoff is best-effort
        # phase 2 — decode replica imports the pages under its prefix
        # index and serves the full request (admission maps the leading
        # blocks onto the imported pages: no re-prefill of the prefix)
        with self._lock:
            dec = self._route_locked(ticket, roles=("decode",),
                                     label="disagg")
        if blob:
            try:
                with _rt.span(ticket.trace, "handoff_import",
                              replica=dec.id, source_replica=pre.id):
                    n = dec.engine.run_on_loop(
                        lambda eng: eng._cache.import_pages(blob))
                hp = int(blob.get("host_pages", 0))
                if n:
                    with self._lock:
                        self.handoff_pages += n
                        self.handoff_host_pages += hp
                    tele["handoff"].inc(n)
                _rt.add_event(ticket.trace, "handoff", pages=int(n or 0),
                              host_pages=hp, replica=dec.id,
                              source_replica=pre.id)
            except Exception:
                pass                         # full prefill fallback
        else:
            _rt.add_event(ticket.trace, "handoff_skipped",
                          replica=dec.id)
        return self._run_attempt(ticket, dec, ticket.max_new_tokens)

    # -- routing ------------------------------------------------------------
    def _route_locked(self, ticket, roles, label=None):
        """Pick a replica for the ticket's next attempt (caller holds the
        lock): longest-matching hash chain weighted against live token
        occupancy, or round-robin / pure balance per policy."""
        tele = _telemetry()
        elig = [r for r in self.replicas
                if r.alive and not r.draining and r.role in roles]
        if not elig and roles == ("prefill",):
            # all dedicated prefill replicas gone: decode replicas absorb
            # the prefill role rather than refusing traffic
            elig = [r for r in self.replicas
                    if r.alive and not r.draining and r.role == "decode"]
        if not elig:
            raise Rejected("no_replicas", tenant=ticket.tenant,
                           detail="no healthy replica for role "
                                  f"{'/'.join(roles)}")
        matched = {r.id: r.matched_tokens(ticket.chain) for r in elig}
        if self.policy == "round_robin":
            best = elig[self._rr_next % len(elig)]
            self._rr_next += 1
            decided = "round_robin"
        else:
            aff = 0.0 if self.policy == "balance" else self.affinity
            best = max(
                elig,
                key=lambda r: (aff * matched[r.id]
                               - (1.0 - aff) * r.load_tokens,
                               -r.load_tokens, r.id))
            decided = ("affinity" if aff > 0 and matched[best.id] > 0
                       else "balance")
            top = max(matched.values())
            if top > 0:
                self.affinity_matchable += 1
                if matched[best.id] == top:
                    self.affinity_hits += 1
                tele["hit_rate"].set(
                    self.affinity_hits / self.affinity_matchable)
        if label is not None:
            decided = label
        # optimistic frontier: the request will fill+commit these blocks
        # on that replica; footprint counts only the tokens it will
        # actually compute there
        best.note_chain(ticket.chain)
        footprint = (max(ticket.ids.shape[1] - matched[best.id], 1)
                     + ticket.max_new_tokens)
        best.inflight[ticket.id] = footprint
        ticket.replica = best
        ticket.attempt += 1
        self.routed_total += 1
        tele["routed"].inc(policy=decided)
        tele["qdepth"].set(best.queue_depth, replica=best.id)
        if ticket.trace is not None:
            # stamp every later engine-side span with where (and which
            # try) this attempt runs, then record the decision itself
            ticket.trace.set_tags(replica=best.id, attempt=ticket.attempt)
            _rt.add_event(ticket.trace, "route", policy=decided,
                          role=best.role,
                          matched_tokens=int(matched[best.id]),
                          load_tokens=int(best.load_tokens),
                          affinity=self.affinity)
        # fleet fault grammar (kill:replica=R,request=N / stall:...):
        # the route itself is the trigger point — a killed replica takes
        # this very attempt down with it (the requeue path must earn its
        # keep), a stalled one serves it slowly
        flt = _fault.check_fleet_route(best.id)
        if flt is not None:
            self._apply_fleet_fault(best, flt)
        return best

    def _apply_fleet_fault(self, replica, flt):
        """Apply a due fleet fault directive (caller holds the lock)."""
        if flt.kind == "kill":
            replica.heartbeating = False
            self._on_replica_dead(replica, reason="fault_kill")
        elif flt.kind == "stall":
            # a straggler, not a corpse: the serve loop sleeps at its
            # next tick boundary while heartbeats keep flowing — SLO
            # burn with no death signal (posted fire-and-forget; the
            # router must not wait out the stall itself)
            try:
                replica.engine._q.put(
                    _Control(lambda eng, s=flt.seconds: time.sleep(s)))
            except Exception:
                pass

    # -- observability ------------------------------------------------------
    def _state(self):
        """Fleet state provider payload (flight-recorder dumps)."""
        with self._lock:
            return {
                "policy": self.policy,
                "affinity": self.affinity,
                "disagg": self.disagg,
                "max_attempts": self.max_attempts,
                "max_new_cap": self.max_new_cap,
                "routed_total": self.routed_total,
                "requeues_total": self.requeues_total,
                "rejected_total": self.rejected_total,
                "affinity_hits": self.affinity_hits,
                "affinity_matchable": self.affinity_matchable,
                "handoff_pages": self.handoff_pages,
                "handoff_host_pages": self.handoff_host_pages,
                "replicas": {
                    r.id: {"alive": r.alive, "draining": r.draining,
                           "role": r.role, "inflight": len(r.inflight),
                           "load_tokens": r.load_tokens,
                           "queue_depth": r.queue_depth,
                           "frontier_blocks": len(r.frontier)}
                    for r in self.replicas},
            }

    def stats(self):
        """Router decision counters (tests / dashboards)."""
        return self._state()
