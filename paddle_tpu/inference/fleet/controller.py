"""Self-healing fleet control plane: the reconcile loop that watches the
PR-11 sensing rig and ACTS (ISSUE 14; ROADMAP item 4's controller half —
the goodput-per-chip framing of arxiv 2605.25645 says a fleet that
cannot resize, re-role or shed load under a burst violates SLOs for
everyone, and the mixed prefill/decode load model of arxiv 2604.15464
is exactly the regime where a static prefill:decode split falls over).

:class:`FleetController` reconciles on an interval (or an explicit,
deterministic :meth:`~FleetController.step` in tests). Signals in:
``paddle.profiler.history()`` series (p95 TTFT via
``paddle_slo_latency_seconds``, ``paddle_serving_active_requests``),
the :class:`~...profiler.alerts.AlertEngine`'s active burn-rate rules
(or an internal :class:`~...profiler.alerts.BurnRateRule` when no
engine is shared), and the router's live replica snapshot (alive,
role, load tokens, queue depth). Actions out — always through the
router's EXISTING actuators, never around them:

* **autoscale** — ``router.add_replica`` joins a spare engine from the
  warm pool under overload; sustained idleness drains the least-loaded
  replica back into the pool (``drain`` -> ``remove_replica``).
  Hysteresis is structural: distinct up (load/burn) and down
  (``down_idle_s`` of observed zero load) conditions plus a per-action
  cooldown (``PADDLE_CONTROLLER_COOLDOWN_S``) mean a steady workload
  can never make the controller flap.
* **role flip** — when the per-replica prefill:decode pressure ratio
  crosses ``flip_ratio`` (disaggregated fleets), one replica from the
  overstaffed side takes the drain -> ``rejoin(role=...)`` path; each
  side always keeps at least one replica.
* **graceful degradation** — under sustained SLO burn the heaviest
  tenant's quota bucket is tightened (``TenantQuotaManager.shed``) and
  the per-request decode budget capped (``router.max_new_cap``)
  *before* compliant tenants miss SLO; both restore once the burn has
  stayed clear for a cooldown. Still burning? The next-heaviest tenant
  sheds on the following cooldown (escalation).
* **supervision** — dead replicas restart (``rejoin``) behind an
  exponential backoff; ``breaker_n`` deaths inside
  ``breaker_window_s`` trips the circuit breaker: the replica is
  quarantined (never auto-restarted again) and the
  ``controller_quarantine`` page-severity alert fires instead of a
  restart loop. ``release(rid)`` is the operator's reset.

Every decision is a structured :class:`ControllerAction`: appended to
the bounded action ledger, counted in
``paddle_controller_actions_total{action,reason}``, recorded as a
flight-recorder ``controller`` event, and carried by the
``fleet_controller`` watchdog state provider — the ledger of *why* the
fleet changed shape is inspectable after the fact
(``tools/fleet_console.py`` renders it from dumps).

Chaos proof: the ``PADDLE_FAULT_PLAN`` grammar's fleet directives
(``kill:replica=R,request=N``, ``stall:replica=R,seconds=T``) inject
the failures, and the PR-11 replay rig measures the outcome
(``fleet_time_to_recover_s`` controller-on vs controller-off,
``BENCH_MODEL=fleet``).
"""
from __future__ import annotations

import os
import threading

__all__ = ["FleetController", "ControllerAction", "CONTROLLER_ACTIONS"]

#: every action kind the controller can emit (the
#: ``paddle_controller_actions_total{action=}`` values);
#: tools/check_inventory.py requires each documented AND tested
CONTROLLER_ACTIONS = ("scale_up", "scale_down", "role_flip", "restart",
                      "quarantine", "shed", "restore")

_TELEMETRY = None


def _telemetry():
    global _TELEMETRY
    if _TELEMETRY is None:
        from ...profiler.telemetry import get_registry
        r = get_registry()
        _TELEMETRY = {
            "actions": r.counter(
                "paddle_controller_actions_total",
                "fleet-controller reconcile decisions, by action kind "
                "and trigger reason",
                labels=("action", "reason")),
            "quarantined": r.gauge(
                "paddle_controller_quarantined_replicas",
                "replicas the circuit breaker has quarantined (page on "
                "> 0: a replica is dying faster than restarts help)"),
            "degraded": r.gauge(
                "paddle_controller_degraded",
                "1 while graceful degradation (tenant shed / decode "
                "cap) is in force, else 0"),
        }
    return _TELEMETRY


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return float(default)


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return int(default)


class ControllerAction:
    """One reconcile decision: what the controller did, why, to whom,
    and the trigger metric value that justified it."""

    __slots__ = ("t", "action", "reason", "target", "value", "detail",
                 "cooldown_s")

    def __init__(self, t, action, reason, target=None, value=None,
                 detail="", cooldown_s=0.0):
        self.t = float(t)
        self.action = str(action)
        self.reason = str(reason)
        self.target = None if target is None else str(target)
        self.value = None if value is None else float(value)
        self.detail = str(detail)
        self.cooldown_s = float(cooldown_s)

    def as_dict(self) -> dict:
        return {"t": round(self.t, 6), "action": self.action,
                "reason": self.reason, "target": self.target,
                "value": self.value, "detail": self.detail,
                "cooldown_s": self.cooldown_s}

    def __repr__(self):
        tgt = f" target={self.target}" if self.target else ""
        return (f"<ControllerAction {self.action}({self.reason}){tgt} "
                f"t={self.t:.3f}>")


class FleetController:
    """SLO-driven reconcile loop over a :class:`~.router.ServingRouter`.

    ctl = FleetController(router, warm_pool=[spare_engine],
                          alert_engine=engine, history=hist)
    ctl.start()              # background reconcile thread
    ...
    ctl.stop()

    or deterministically (tests / replay): ``ctl.step(now=t)``.

    Knobs (constructor kwargs win over env):

    * ``interval_s`` / ``PADDLE_CONTROLLER_INTERVAL_S`` (0.5) — wall
      seconds between background reconciles;
    * ``cooldown_s`` / ``PADDLE_CONTROLLER_COOLDOWN_S`` (5.0) — minimum
      spacing between two actions of the SAME kind (flap prevention);
    * ``up_load_tokens`` / ``PADDLE_CONTROLLER_UP_LOAD_TOKENS`` (256) —
      mean live token load per alive replica that triggers scale-up;
    * ``down_idle_s`` / ``PADDLE_CONTROLLER_DOWN_IDLE_S`` (10.0) —
      sustained zero-load seconds before a replica drains to the pool;
    * ``flip_ratio`` / ``PADDLE_CONTROLLER_FLIP_RATIO`` (4.0) —
      per-replica pressure ratio between decode and prefill sides that
      triggers a role flip;
    * ``breaker_n`` / ``PADDLE_CONTROLLER_BREAKER_N`` (3) and
      ``breaker_window_s`` / ``PADDLE_CONTROLLER_BREAKER_WINDOW_S``
      (60.0) — deaths inside the window that trip quarantine;
    * ``restart_backoff_s`` / ``PADDLE_CONTROLLER_RESTART_BACKOFF_S``
      (0.5) — base of the exponential restart backoff;
    * ``degraded_max_new`` / ``PADDLE_CONTROLLER_DEGRADED_MAX_NEW``
      (0 = off) — per-request decode cap applied while degraded;
    * ``shed_scale`` / ``PADDLE_CONTROLLER_SHED_SCALE`` (0.5) — quota
      scale applied to the heaviest tenant while degraded (0 rejects
      it outright).
    """

    def __init__(self, router, history=None, alert_engine=None,
                 warm_pool=(), min_replicas=1, max_replicas=None,
                 interval_s=None, cooldown_s=None, up_load_tokens=None,
                 down_idle_s=None, flip_ratio=None, breaker_n=None,
                 breaker_window_s=None, restart_backoff_s=None,
                 degraded_max_new=None, shed_scale=None, burn_rule=None,
                 drain_timeout_s=10.0):
        self.router = router
        if history is None:
            from ...profiler.timeseries import get_history
            history = get_history()
        self.history = history
        self.alert_engine = alert_engine
        self.warm_pool = list(warm_pool)
        self.min_replicas = max(int(min_replicas), 1)
        self.max_replicas = (int(max_replicas) if max_replicas is not None
                             else len(router.replicas) + len(self.warm_pool))
        self.interval_s = (float(interval_s) if interval_s is not None
                           else _env_float("PADDLE_CONTROLLER_INTERVAL_S",
                                           0.5))
        self.cooldown_s = (float(cooldown_s) if cooldown_s is not None
                           else _env_float("PADDLE_CONTROLLER_COOLDOWN_S",
                                           5.0))
        self.up_load_tokens = (
            float(up_load_tokens) if up_load_tokens is not None
            else _env_float("PADDLE_CONTROLLER_UP_LOAD_TOKENS", 256.0))
        self.down_idle_s = (
            float(down_idle_s) if down_idle_s is not None
            else _env_float("PADDLE_CONTROLLER_DOWN_IDLE_S", 10.0))
        self.flip_ratio = (
            float(flip_ratio) if flip_ratio is not None
            else _env_float("PADDLE_CONTROLLER_FLIP_RATIO", 4.0))
        self.breaker_n = (
            int(breaker_n) if breaker_n is not None
            else _env_int("PADDLE_CONTROLLER_BREAKER_N", 3))
        self.breaker_window_s = (
            float(breaker_window_s) if breaker_window_s is not None
            else _env_float("PADDLE_CONTROLLER_BREAKER_WINDOW_S", 60.0))
        self.restart_backoff_s = (
            float(restart_backoff_s) if restart_backoff_s is not None
            else _env_float("PADDLE_CONTROLLER_RESTART_BACKOFF_S", 0.5))
        self.degraded_max_new = (
            int(degraded_max_new) if degraded_max_new is not None
            else _env_int("PADDLE_CONTROLLER_DEGRADED_MAX_NEW", 0))
        self.shed_scale = (
            float(shed_scale) if shed_scale is not None
            else _env_float("PADDLE_CONTROLLER_SHED_SCALE", 0.5))
        self.drain_timeout_s = float(drain_timeout_s)
        if burn_rule is None:
            from ...profiler.alerts import BurnRateRule
            burn_rule = BurnRateRule(name="controller_burn",
                                     fast_window_s=2.0, slow_window_s=6.0)
        self._own_burn = burn_rule
        self._lock = threading.RLock()
        self.actions: list = []          # bounded ControllerAction ledger
        self._last: dict = {}            # action kind -> last fire t
        self._was_alive: dict = {}       # rid -> last observed liveness
        self._fails: dict = {}           # rid -> recent death times
        self._next_restart: dict = {}    # rid -> earliest restart t
        self._quarantined: set = set()
        self._idle_since = None
        self._burn_clear_since = None
        self._degraded = False
        self._shed_tenants: list = []
        self._saved_cap = None
        self._stop_evt = threading.Event()
        self._thread = None
        self._running = False
        self._flight_key = None
        self.exporter = None
        self.steps = 0
        if self.alert_engine is not None:
            # the breaker's page: quarantining a replica must raise a
            # page-severity alert instead of silently shrinking the
            # fleet (evaluated on the shared history's tick timeline)
            from ...profiler.alerts import ThresholdRule
            self.alert_engine.add_rule(ThresholdRule(
                name="controller_quarantine",
                metric="paddle_controller_quarantined_replicas",
                above=0, severity="page"))
        _telemetry()["quarantined"].set(0)
        _telemetry()["degraded"].set(0)

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self._running:
            return self
        self._running = True
        self._stop_evt.clear()
        from ...profiler import flight_recorder as _flight
        self._flight_key = "fleet_controller"
        _flight.register_state_provider(self._flight_key, self.state)
        from ...profiler import exporter as _exp
        # the control plane is remotely diagnosable too: its endpoint
        # rides the same discovery prefix as the replicas (ISSUE 15)
        self.exporter = _exp.maybe_start_exporter(
            instance="controller", store=self.router.store,
            key_prefix=f"{self.router.ns}/telemetry/", ephemeral=True)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="paddle-fleet-controller")
        self._thread.start()
        return self

    def stop(self):
        if not self._running:
            return
        self._running = False
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._flight_key is not None:
            from ...profiler import flight_recorder as _flight
            _flight.unregister_state_provider(self._flight_key)
            self._flight_key = None
        exp = getattr(self, "exporter", None)
        if exp is not None:
            exp.stop()
            self.exporter = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def _loop(self):
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.step()
            except Exception:    # a bad reconcile must not kill the loop
                pass

    # -- signals -------------------------------------------------------------
    def _burning(self, now):
        """(is the SLO burning, trigger value): active burn-rate rule on
        the shared alert engine, else the internal rule over the
        history."""
        if self.alert_engine is not None:
            with self.alert_engine._lock:
                for name, ent in self.alert_engine.active.items():
                    rule = self.alert_engine.rules.get(name)
                    if rule is not None and rule.kind == "burn_rate":
                        return True, ent.get("value")
            return False, None
        try:
            return (self._own_burn.breached(self.history, now),
                    self._own_burn.value(self.history, now))
        except Exception:
            return False, None

    def _ttft_over_target(self):
        """p95 TTFT (from the history's SLO gauge series) over the
        ``PADDLE_SLO_TTFT_MS`` target — the latency face of overload."""
        target_ms = _env_float("PADDLE_SLO_TTFT_MS", 0.0)
        if target_ms <= 0:
            return False, None
        p = self.history.latest("paddle_slo_latency_seconds", "ttft,p95")
        if p is None:
            return False, None
        return p[1] * 1e3 > target_ms, p[1]

    def _snapshot(self):
        with self.router._lock:
            return [{"rid": r.id, "alive": r.alive,
                     "draining": r.draining, "role": r.role,
                     "load": r.load_tokens, "queue": r.queue_depth,
                     "inflight": len(r.inflight)}
                    for r in self.router.replicas]

    # -- the reconcile -------------------------------------------------------
    def step(self, now=None) -> list:
        """One reconcile pass; returns the actions taken (possibly
        empty). Deterministic under an explicit ``now`` (the history
        clock) — the unit tests drive it sample-aligned."""
        if not self.router._started:
            return []
        now = self.history.now() if now is None else float(now)
        out = []
        with self._lock:
            self.steps += 1
        burning, burn_value = self._burning(now)
        snap = self._snapshot()
        out += self._supervise(now, snap)
        out += self._degrade(now, burning, burn_value)
        snap = self._snapshot()              # supervision may have acted
        alive = [s for s in snap if s["alive"] and not s["draining"]]
        total_load = sum(s["load"] for s in alive)
        total_queue = sum(s["queue"] for s in alive)
        out += self._scale_up(now, alive, total_load, burning, burn_value)
        out += self._scale_down(now, alive, total_load, total_queue)
        out += self._role_flip(now, alive)
        return out

    def _cool(self, action, now) -> bool:
        last = self._last.get(action)
        return last is None or (now - last) >= self.cooldown_s

    def _act(self, now, action, reason, target=None, value=None,
             detail=""):
        rec = ControllerAction(now, action, reason, target=target,
                               value=value, detail=detail,
                               cooldown_s=self.cooldown_s)
        with self._lock:
            self.actions.append(rec)
            del self.actions[:-128]
            self._last[action] = now
        _telemetry()["actions"].inc(action=action, reason=reason)
        from ...profiler import flight_recorder as _flight
        _flight.record_event("controller", action=action, reason=reason,
                             target=target,
                             value=None if value is None else float(value))
        return rec

    # -- supervision: restart / circuit breaker ------------------------------
    def _supervise(self, now, snap) -> list:
        out = []
        for s in snap:
            rid = s["rid"]
            if s["draining"]:
                continue
            if s["alive"]:
                self._was_alive[rid] = True
                continue
            if self._was_alive.get(rid, True):
                # fresh death observed: one breaker strike, backoff grows
                # with the strike count inside the window
                self._was_alive[rid] = False
                fails = self._fails.setdefault(rid, [])
                fails.append(now)
                fails[:] = [t for t in fails
                            if now - t <= self.breaker_window_s]
                self._next_restart[rid] = now + (
                    self.restart_backoff_s * (2 ** max(len(fails) - 1, 0)))
                if (len(fails) >= self.breaker_n
                        and rid not in self._quarantined):
                    self._quarantined.add(rid)
                    _telemetry()["quarantined"].set(len(self._quarantined))
                    out.append(self._act(
                        now, "quarantine", "breaker_tripped", target=rid,
                        value=len(fails),
                        detail=f"{len(fails)} deaths in "
                               f"{self.breaker_window_s:g}s"))
                    continue
            if rid in self._quarantined:
                continue
            if now >= self._next_restart.get(rid, now):
                try:
                    eng = self.router._replica(rid).engine
                    th = getattr(eng, "_thread", None)
                    if th is not None and th.is_alive():
                        # the aborted serve loop is still winding down:
                        # restarting now would race its queue drain —
                        # next pass (the backoff already spaced us out)
                        continue
                    self.router.rejoin(rid)
                except Exception:
                    # engine would not come back: another strike's worth
                    # of backoff before the next try
                    self._next_restart[rid] = now + (
                        self.restart_backoff_s
                        * (2 ** len(self._fails.get(rid, []))))
                    continue
                self._was_alive[rid] = True
                out.append(self._act(
                    now, "restart", "replica_dead", target=rid,
                    value=len(self._fails.get(rid, []))))
        return out

    def release(self, rid):
        """Operator reset: lift a quarantine (and its breaker strikes)
        so supervision may restart the replica again — the RUNBOOK.md
        "fleet won't recover" escape hatch."""
        rid = str(rid)
        with self._lock:
            self._quarantined.discard(rid)
            self._fails.pop(rid, None)
            self._next_restart.pop(rid, None)
        _telemetry()["quarantined"].set(len(self._quarantined))

    # -- graceful degradation ------------------------------------------------
    def _degrade(self, now, burning, burn_value) -> list:
        out = []
        quota = self.router.quota
        if burning:
            self._burn_clear_since = None
            if not self._cool("shed", now):
                return out
            shed = None
            if quota is not None:
                for tenant in quota.tenants_by_usage():
                    if tenant not in self._shed_tenants:
                        quota.shed(tenant, self.shed_scale)
                        self._shed_tenants.append(tenant)
                        shed = tenant
                        break
            capped = False
            if not self._degraded and self.degraded_max_new > 0:
                self._saved_cap = self.router.max_new_cap
                self.router.max_new_cap = self.degraded_max_new
                capped = True
            if shed is not None or capped:
                self._degraded = True
                _telemetry()["degraded"].set(1)
                out.append(self._act(
                    now, "shed", "slo_burn", target=shed,
                    value=burn_value,
                    detail=(f"quota x{self.shed_scale:g}"
                            if shed else "") + (
                        f" max_new<={self.degraded_max_new}"
                        if capped else "")))
        else:
            if self._burn_clear_since is None:
                self._burn_clear_since = now
            if (self._degraded
                    and now - self._burn_clear_since >= self.cooldown_s
                    and self._cool("restore", now)):
                if quota is not None:
                    for tenant in self._shed_tenants:
                        quota.restore(tenant)
                restored = list(self._shed_tenants)
                self._shed_tenants = []
                self.router.max_new_cap = self._saved_cap
                self._saved_cap = None
                self._degraded = False
                _telemetry()["degraded"].set(0)
                out.append(self._act(
                    now, "restore", "recovered",
                    target=",".join(restored) or None,
                    detail="quota + decode cap restored"))
        return out

    # -- autoscale -----------------------------------------------------------
    def _scale_up(self, now, alive, total_load, burning, burn_value):
        if not self.warm_pool or len(alive) >= self.max_replicas:
            return []
        mean_load = total_load / max(len(alive), 1)
        slow, ttft = self._ttft_over_target()
        if burning:
            reason, value = "slo_burn", burn_value
        elif alive and mean_load >= self.up_load_tokens:
            reason, value = "overload", mean_load
        elif slow:
            reason, value = "ttft_over_target", ttft
        else:
            return []
        if not self._cool("scale_up", now):
            return []
        role = "mixed"
        if self.router.disagg:
            pre = [s for s in alive if s["role"] == "prefill"]
            dec = [s for s in alive if s["role"] == "decode"]
            pre_pr = sum(s["load"] + s["queue"] for s in pre) \
                / max(len(pre), 1)
            dec_pr = sum(s["load"] + s["queue"] for s in dec) \
                / max(len(dec), 1)
            role = "decode" if dec_pr >= pre_pr else "prefill"
        engine = self.warm_pool.pop()
        try:
            rep = self.router.add_replica(engine, role=role)
        except Exception:
            self.warm_pool.append(engine)
            return []
        return [self._act(now, "scale_up", reason, target=rep.id,
                          value=value, detail=f"role={role}")]

    def _scale_down(self, now, alive, total_load, total_queue):
        busy = total_load > 0 or total_queue > 0 \
            or any(s["inflight"] for s in alive)
        if busy:
            self._idle_since = None
            return []
        if self._idle_since is None:
            self._idle_since = now
            return []
        if (now - self._idle_since < self.down_idle_s
                or len(alive) <= self.min_replicas
                or not self._cool("scale_down", now)):
            return []
        cands = list(alive)
        if self.router.disagg:
            # each role keeps at least one replica
            by_role = {}
            for s in alive:
                by_role.setdefault(s["role"], []).append(s)
            cands = [s for s in alive if len(by_role[s["role"]]) > 1]
        if not cands:
            return []
        victim = min(cands, key=lambda s: (s["load"], s["rid"]))
        try:
            self.router.drain(victim["rid"],
                              timeout=self.drain_timeout_s)
            engine = self.router.remove_replica(victim["rid"])
        except Exception:
            return []               # raced with fresh work: not idle
        self.warm_pool.append(engine)
        # forget supervision state for the retired identity
        self._was_alive.pop(victim["rid"], None)
        self._fails.pop(victim["rid"], None)
        return [self._act(now, "scale_down", "idle",
                          target=victim["rid"],
                          value=now - self._idle_since)]

    # -- role flipping -------------------------------------------------------
    def _role_flip(self, now, alive):
        if not self.router.disagg or not self._cool("role_flip", now):
            return []
        pre = [s for s in alive if s["role"] == "prefill"]
        dec = [s for s in alive if s["role"] == "decode"]
        if not pre or not dec:
            return []
        pre_pr = sum(s["load"] + s["queue"] for s in pre) / len(pre) + 1.0
        dec_pr = sum(s["load"] + s["queue"] for s in dec) / len(dec) + 1.0
        if dec_pr / pre_pr >= self.flip_ratio and len(pre) > 1:
            donor_side, new_role, ratio = pre, "decode", dec_pr / pre_pr
        elif pre_pr / dec_pr >= self.flip_ratio and len(dec) > 1:
            donor_side, new_role, ratio = dec, "prefill", pre_pr / dec_pr
        else:
            return []
        donor = min(donor_side, key=lambda s: (s["load"], s["rid"]))
        try:
            self.router.drain(donor["rid"], timeout=self.drain_timeout_s)
            self.router.rejoin(donor["rid"], role=new_role)
        except Exception:
            return []               # busy donor: try again next pass
        return [self._act(now, "role_flip", "queue_imbalance",
                          target=donor["rid"], value=ratio,
                          detail=f"-> {new_role}")]

    # -- observability -------------------------------------------------------
    def state(self) -> dict:
        """The ``fleet_controller`` state-provider payload (watchdog
        dumps, ``tools/fleet_console.py``)."""
        now = self.history.now()
        with self._lock:
            return {
                "running": self._running,
                "steps": self.steps,
                "interval_s": self.interval_s,
                "cooldown_s": self.cooldown_s,
                "cooldowns": {
                    a: round(max(self._last[a] + self.cooldown_s - now,
                                 0.0), 3)
                    for a in sorted(self._last)},
                "recent_actions": [a.as_dict()
                                   for a in self.actions[-16:]],
                "quarantined": sorted(self._quarantined),
                "degraded": self._degraded,
                "shed_tenants": list(self._shed_tenants),
                "max_new_cap": self.router.max_new_cap,
                "warm_pool": len(self.warm_pool),
                "failures": {rid: len(ts)
                             for rid, ts in sorted(self._fails.items())
                             if ts},
            }
