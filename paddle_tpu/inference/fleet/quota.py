"""Per-tenant admission control for the serving fleet.

Quotas are token buckets accounted FLEET-WIDE: the consumed-token counter
for each tenant lives in the shared elastic KV store (``MemKVStore`` on
the thread-rank simulator tier, ``TcpKVStore`` across processes/hosts)
and is advanced with the store's atomic ``incr`` — N routers admitting
the same tenant concurrently can never double-spend a budget. A request
that exceeds its tenant's budget is refused up front with a structured
:class:`Rejected` (reason ``tenant_quota``) before any model work — the
caller learns immediately instead of burning its timeout in a queue.
"""
from __future__ import annotations

import time


class Rejected(RuntimeError):
    """Structured fleet admission rejection — NOT a timeout. ``reason``
    is one of ``tenant_quota`` (the tenant's fleet-wide token budget is
    spent), ``queue_full`` (every live replica is over the router's
    queue-token backpressure bound), or ``no_replicas`` (no healthy
    replica can take the request)."""

    def __init__(self, reason, detail="", tenant=None):
        self.reason = str(reason)
        self.tenant = tenant
        self.detail = detail
        msg = f"request rejected ({self.reason})"
        if tenant is not None:
            msg += f" tenant={tenant}"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class TenantQuotaManager:
    """Fleet-wide token-bucket quotas per tenant id.

    A tenant's bucket holds ``capacity`` tokens and refills at
    ``refill_per_s`` tokens/second (``refill_per_s=0`` makes it a hard
    budget — the deterministic configuration tests use). The admitted
    cost of a request is its token footprint (uncached prompt estimate +
    decode budget), charged via ``store.incr`` so the counter is one
    fleet-wide truth; a rejected request's charge is rolled back with a
    negative increment.

    ``capacity <= 0`` means the tenant is unlimited. Per-tenant
    ``overrides`` ({tenant: (capacity, refill_per_s)}) win over the
    defaults.
    """

    def __init__(self, store, capacity=0, refill_per_s=0.0,
                 namespace="fleet", overrides=None):
        self.store = store
        self.capacity = int(capacity)
        self.refill_per_s = float(refill_per_s)
        self.ns = namespace
        self.overrides = dict(overrides or {})

    def _limits(self, tenant):
        cap, rate = self.overrides.get(
            tenant, (self.capacity, self.refill_per_s))
        return int(cap), float(rate)

    def _key(self, tenant, leaf):
        return f"{self.ns}/quota/{tenant}/{leaf}"

    def admit(self, tenant, cost_tokens):
        """Charge ``cost_tokens`` to ``tenant``'s fleet-wide bucket.
        Returns the tenant's post-charge consumed-token counter (None
        for an unlimited tenant — the router's admission trace span
        records it); raises :class:`Rejected` (reason ``tenant_quota``)
        when the bucket cannot cover the cost."""
        cap, rate = self._limits(tenant)
        if cap <= 0:
            return None
        cost = max(int(cost_tokens), 1)
        t0_key = self._key(tenant, "t0")
        t0 = self.store.get(t0_key)
        if t0 is None:
            # first sighting of the tenant anywhere in the fleet starts
            # its refill clock; near-simultaneous writers land within
            # clock jitter of each other, which the bucket tolerates
            self.store.put(t0_key, time.time())
            t0 = self.store.get(t0_key) or time.time()
        allowance = cap + rate * max(time.time() - float(t0), 0.0)
        used = self.store.incr(self._key(tenant, "used"), cost)
        if used > allowance:
            self.store.incr(self._key(tenant, "used"), -cost)  # roll back
            raise Rejected(
                "tenant_quota", tenant=tenant,
                detail=f"cost {cost} tokens over budget "
                       f"(used {used - cost}/{int(allowance)})")
        return int(used)

    def usage(self, tenant):
        """Current consumed-token counter for ``tenant`` (0 if unseen)."""
        return int(self.store.get(self._key(tenant, "used")) or 0)
