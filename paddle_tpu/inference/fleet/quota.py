"""Per-tenant admission control for the serving fleet.

Quotas are token buckets accounted FLEET-WIDE: the consumed-token counter
for each tenant lives in the shared elastic KV store (``MemKVStore`` on
the thread-rank simulator tier, ``TcpKVStore`` across processes/hosts)
and is advanced with the store's atomic ``incr`` — N routers admitting
the same tenant concurrently can never double-spend a budget. A request
that exceeds its tenant's budget is refused up front with a structured
:class:`Rejected` (reason ``tenant_quota``) before any model work — the
caller learns immediately instead of burning its timeout in a queue.
"""
from __future__ import annotations

import threading
import time

#: every structured rejection reason the fleet can emit; each must be
#: documented in docs/SERVING.md AND exercised by a test
#: (tools/check_inventory.py::check_controller_catalog enforces both)
REJECTION_REASONS = ("tenant_quota", "queue_full", "no_replicas",
                     "attempts_exhausted")


class Rejected(RuntimeError):
    """Structured fleet admission rejection — NOT a timeout. ``reason``
    is one of ``tenant_quota`` (the tenant's fleet-wide token budget is
    spent), ``queue_full`` (every live replica is over the router's
    queue-token backpressure bound), ``no_replicas`` (every replica is
    dead or draining — failed immediately, never after a timeout), or
    ``attempts_exhausted`` (the request's requeue budget
    ``PADDLE_FLEET_MAX_ATTEMPTS`` ran out ping-ponging across dying
    replicas)."""

    def __init__(self, reason, detail="", tenant=None):
        self.reason = str(reason)
        self.tenant = tenant
        self.detail = detail
        msg = f"request rejected ({self.reason})"
        if tenant is not None:
            msg += f" tenant={tenant}"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class TenantQuotaManager:
    """Fleet-wide token-bucket quotas per tenant id.

    A tenant's bucket holds ``capacity`` tokens and refills at
    ``refill_per_s`` tokens/second (``refill_per_s=0`` makes it a hard
    budget — the deterministic configuration tests use). The admitted
    cost of a request is its token footprint (uncached prompt estimate +
    decode budget), charged via ``store.incr`` so the counter is one
    fleet-wide truth; a rejected request's charge is rolled back with a
    negative increment.

    ``capacity <= 0`` means the tenant is unlimited. Per-tenant
    ``overrides`` ({tenant: (capacity, refill_per_s)}) win over the
    defaults.
    """

    def __init__(self, store, capacity=0, refill_per_s=0.0,
                 namespace="fleet", overrides=None):
        self.store = store
        self.capacity = int(capacity)
        self.refill_per_s = float(refill_per_s)
        self.ns = namespace
        self.overrides = dict(overrides or {})
        self._lock = threading.Lock()
        self._shed: dict = {}          # tenant -> scale in (0, 1]
        self._seen: set = set()        # tenants this manager admitted

    def _limits(self, tenant):
        cap, rate = self.overrides.get(
            tenant, (self.capacity, self.refill_per_s))
        with self._lock:
            scale = self._shed.get(tenant, 1.0)
        return int(cap * scale), float(rate * scale)

    # -- graceful degradation (the FleetController's shed actuator) ----------
    def shed(self, tenant, scale):
        """Tighten ``tenant``'s bucket to ``scale`` x its configured
        capacity+refill (controller-local, not fleet-wide KV state: one
        controller owns the fleet's degradation posture). ``scale=0``
        rejects the tenant outright until :meth:`restore`."""
        with self._lock:
            self._shed[str(tenant)] = min(max(float(scale), 0.0), 1.0)

    def restore(self, tenant=None):
        """Undo :meth:`shed` for one tenant (or all when None)."""
        with self._lock:
            if tenant is None:
                self._shed.clear()
            else:
                self._shed.pop(str(tenant), None)

    def shed_scales(self) -> dict:
        with self._lock:
            return dict(self._shed)

    def tenants_by_usage(self) -> list:
        """Tenants this manager has admitted, heaviest consumer first —
        the controller's shed-candidate order (an unlimited tenant can
        still be the hog)."""
        with self._lock:
            seen = sorted(self._seen)
        return sorted(seen, key=lambda t: -self.usage(t))

    def _key(self, tenant, leaf):
        return f"{self.ns}/quota/{tenant}/{leaf}"

    def admit(self, tenant, cost_tokens):
        """Charge ``cost_tokens`` to ``tenant``'s fleet-wide bucket.
        Returns the tenant's post-charge consumed-token counter (None
        for an unlimited tenant — the router's admission trace span
        records it); raises :class:`Rejected` (reason ``tenant_quota``)
        when the bucket cannot cover the cost."""
        with self._lock:
            self._seen.add(str(tenant))
            scale = self._shed.get(tenant, 1.0)
        cap, rate = self._limits(tenant)
        if scale <= 0.0:
            # fully shed (controller degradation): reject outright even
            # for an otherwise-unlimited tenant
            raise Rejected("tenant_quota", tenant=tenant,
                           detail="tenant shed by the fleet controller")
        cost = max(int(cost_tokens), 1)
        if cap <= 0:
            base_cap, _ = self.overrides.get(
                tenant, (self.capacity, self.refill_per_s))
            if int(base_cap) > 0:
                # a configured budget scaled below one whole token:
                # nothing can fit — same outcome as fully shed
                raise Rejected("tenant_quota", tenant=tenant,
                               detail="tenant shed by the fleet "
                                      "controller")
            # unlimited tenant: no budget check, but the consumed-token
            # counter still advances — the controller's shed-candidate
            # ranking (tenants_by_usage) needs the hog visible
            self.store.incr(self._key(tenant, "used"), cost)
            return None
        t0_key = self._key(tenant, "t0")
        t0 = self.store.get(t0_key)
        if t0 is None:
            # first sighting of the tenant anywhere in the fleet starts
            # its refill clock; near-simultaneous writers land within
            # clock jitter of each other, which the bucket tolerates
            self.store.put(t0_key, time.time())
            t0 = self.store.get(t0_key) or time.time()
        allowance = cap + rate * max(time.time() - float(t0), 0.0)
        used = self.store.incr(self._key(tenant, "used"), cost)
        if used > allowance:
            self.store.incr(self._key(tenant, "used"), -cost)  # roll back
            raise Rejected(
                "tenant_quota", tenant=tenant,
                detail=f"cost {cost} tokens over budget "
                       f"(used {used - cost}/{int(allowance)})")
        return int(used)

    def usage(self, tenant):
        """Current consumed-token counter for ``tenant`` (0 if unseen)."""
        return int(self.store.get(self._key(tenant, "used")) or 0)
