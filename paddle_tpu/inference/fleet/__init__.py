"""Serving fleet: a router front end over N continuous-batching engine
replicas — prefix-cache-affinity routing, prefill/decode disaggregation,
fleet-wide per-tenant admission quotas, and replica health/drain/rejoin
(ROADMAP item 2; see docs/SERVING.md "Serving fleet")."""
from .quota import Rejected, TenantQuotaManager                  # noqa: F401
from .router import (DEFAULT_FLEET_AFFINITY, ROUTER_POLICIES,    # noqa: F401
                     Replica, ServingRouter)
from .replay import (REPLAY_PRESETS, ReplayHarness, ReplayReport,  # noqa: F401
                     ReplayRequest, ReplayTrace, load_trace,
                     make_trace, time_to_recover)

__all__ = ["ServingRouter", "Replica", "Rejected", "TenantQuotaManager",
           "ROUTER_POLICIES", "DEFAULT_FLEET_AFFINITY",
           "ReplayHarness", "ReplayReport", "ReplayRequest",
           "ReplayTrace", "REPLAY_PRESETS", "load_trace", "make_trace",
           "time_to_recover"]
