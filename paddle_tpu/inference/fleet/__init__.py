"""Serving fleet: a router front end over N continuous-batching engine
replicas — prefix-cache-affinity routing, prefill/decode disaggregation,
fleet-wide per-tenant admission quotas, replica health/drain/rejoin
(ROADMAP item 2; see docs/SERVING.md "Serving fleet"), and the
self-healing control plane that autoscales, re-roles, sheds and
supervises them against SLO signals (ISSUE 14; docs/SERVING.md
"Fleet controller")."""
from .quota import (REJECTION_REASONS, Rejected,                 # noqa: F401
                    TenantQuotaManager)
from .router import (DEFAULT_FLEET_AFFINITY,                     # noqa: F401
                     DEFAULT_FLEET_MAX_ATTEMPTS, ROUTER_POLICIES,
                     Replica, ServingRouter)
from .controller import (CONTROLLER_ACTIONS, ControllerAction,   # noqa: F401
                         FleetController)
from .replay import (REPLAY_PRESETS, ReplayHarness, ReplayReport,  # noqa: F401
                     ReplayRequest, ReplayTrace, load_trace,
                     make_trace, time_to_recover)

__all__ = ["ServingRouter", "Replica", "Rejected", "TenantQuotaManager",
           "ROUTER_POLICIES", "REJECTION_REASONS",
           "DEFAULT_FLEET_AFFINITY", "DEFAULT_FLEET_MAX_ATTEMPTS",
           "FleetController", "ControllerAction", "CONTROLLER_ACTIONS",
           "ReplayHarness", "ReplayReport", "ReplayRequest",
           "ReplayTrace", "REPLAY_PRESETS", "load_trace", "make_trace",
           "time_to_recover"]
