"""Batched serving engine (reference: the serving tier around
``fused_multi_transformer`` / Paddle Inference's request batching —
SURVEY.md §2.1 "Inference engine", §3.6; VERDICT.md L11 "no serving tier").

TPU-native: requests are micro-batched by prompt length (same-shape
grouping keeps every step a fixed-shape jit-friendly batch), each group
decodes through the paged KV cache + Pallas ``paged_attention`` kernel,
and per-request results are fanned back to the callers. Static batching
with a collect window — the continuous-batching scheduler can replace the
grouping policy without touching the decode path."""
from __future__ import annotations

import os
import queue
import threading
import time
from collections import deque

import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor
from ..profiler import request_trace as _rt
from ..profiler import ledger as _ledger
from ..profiler import compile_observatory as _co

#: default token budget of one chunked-prefill step (overridable per
#: engine via ``prefill_chunk_tokens=`` or PADDLE_SERVING_CHUNK_TOKENS)
DEFAULT_PREFILL_CHUNK_TOKENS = 256

#: default per-tick token budget of the ragged continuous-batching
#: scheduler (``token_budget=`` / PADDLE_SERVING_TOKEN_BUDGET): every
#: live decode slot contributes 1 token, prefill spans fill the rest
DEFAULT_SERVING_TOKEN_BUDGET = 256

#: default stripe length of the sep-parallel long-context prefill
#: (``sep_stripe_tokens=`` / PADDLE_SEP_STRIPE_TOKENS): every chunk of a
#: long prompt pads to exactly this many tokens, so the ring-prefill
#: program family has ONE chunk shape
DEFAULT_SEP_STRIPE_TOKENS = 512

_TELEMETRY = None      # lazily bound registry families


def _chunk_bucket(n_valid, cap):
    """Pad a prefill chunk to the next power-of-two bucket (min 8, capped
    at the chunk budget) so the engine runs a BOUNDED set of compiled
    prefill programs — {8, 16, ..., cap} plus the decode step — instead
    of one program per prompt length."""
    b = 8
    while b < n_valid:
        b *= 2
    return min(b, max(int(cap), 1)) if n_valid <= cap else int(cap)


def _token_bucket(n, cap):
    """Pad a ragged tick's packed token batch to the next power of two
    (min 1, capped at the token budget). Unlike the chunk buckets there
    is no floor of 8: a decode-only tick with two live slots runs a
    2-token program, not an 8-token one — padded-token waste on
    decode-heavy ticks is what the ragged scheduler exists to remove."""
    b = 1
    while b < n:
        b *= 2
    return min(b, max(int(cap), 1)) if n <= cap else int(cap)


def _telemetry():
    """Serving latency/occupancy metrics in the unified registry:
    queue-wait (enqueue → admission), TTFT (enqueue → first token),
    per-decode-step and per-token latency histograms, plus active-slot /
    free-slot / free-page gauges for the continuous scheduler."""
    global _TELEMETRY
    if _TELEMETRY is None:
        from ..profiler.telemetry import (get_registry,
                                          DEFAULT_RATIO_BUCKETS)
        r = get_registry()
        _TELEMETRY = {
            "requests": r.counter("paddle_serving_requests_total",
                                  "generate() requests accepted",
                                  labels=("engine",)),
            "queue_wait": r.histogram(
                "paddle_serving_queue_wait_seconds",
                "enqueue -> scheduler admission", labels=("engine",)),
            "ttft": r.histogram("paddle_serving_ttft_seconds",
                                "enqueue -> first generated token",
                                labels=("engine",)),
            "decode_step": r.histogram(
                "paddle_serving_decode_step_seconds",
                "one fixed-shape decode step over all active slots"),
            "token": r.histogram(
                "paddle_serving_token_latency_seconds",
                "per-token decode latency (step time / active slots)"),
            "tokens": r.counter("paddle_serving_tokens_generated_total",
                                "tokens generated", labels=("engine",)),
            "qdepth": r.gauge("paddle_serving_queue_depth",
                              "requests waiting in the engine queue"),
            "active_reqs": r.gauge(
                "paddle_serving_active_requests",
                "generate() calls currently in flight (queued or "
                "decoding) — the live-load series the metric history "
                "samples", labels=("engine",)),
            "active": r.gauge("paddle_serving_active_slots",
                              "continuous-scheduler slots decoding"),
            "free_slots": r.gauge("paddle_serving_free_slots",
                                  "continuous-scheduler slots free"),
            "free_pages": r.gauge("paddle_serving_free_pages",
                                  "KV-cache pages not backing live context"),
            "prefix_hits": r.counter(
                "paddle_serving_prefix_hits",
                "prompt blocks served from the prefix cache (no prefill)"),
            "prefix_misses": r.counter(
                "paddle_serving_prefix_misses",
                "full prompt blocks that had to prefill"),
            "prefix_cached": r.counter(
                "paddle_serving_prefix_cached_tokens",
                "prompt tokens skipped at prefill via prefix-cache hits"),
            "chunk_util": r.histogram(
                "paddle_serving_chunk_utilization",
                "valid-token fraction of each padded prefill chunk",
                buckets=DEFAULT_RATIO_BUCKETS),
            "pool_occupancy": r.gauge(
                "paddle_serving_page_pool_occupancy",
                "fraction of the shared KV page pool backing live or "
                "prefix-cached context"),
            "budget_util": r.histogram(
                "paddle_serving_token_budget_utilization",
                "useful-token fraction of each padded ragged step "
                "(1 - utilization = padding waste)",
                buckets=DEFAULT_RATIO_BUCKETS),
            "ragged_tokens": r.counter(
                "paddle_serving_ragged_tokens_total",
                "tokens executed through the ragged program family",
                labels=("kind",)),
            "pool_bytes": r.gauge(
                "paddle_serving_page_pool_bytes",
                "dtype-aware KV page-pool bytes (kind=used: pages "
                "backing live or prefix-cached context; kind=capacity: "
                "the whole allocatable pool)", labels=("kind",)),
            "spec_tokens": r.counter(
                "paddle_spec_tokens_total",
                "speculative-decode tokens by fate "
                "(kind=drafted: proposed by the drafter; kind=accepted: "
                "verified equal to the target model's token)",
                labels=("kind",)),
            "spec_accept": r.histogram(
                "paddle_spec_acceptance_ratio",
                "accepted/drafted fraction of each verified span",
                buckets=DEFAULT_RATIO_BUCKETS),
            "prefix_evictions": r.counter(
                "paddle_serving_prefix_evictions_total",
                "prefix-cache evictions by tier (tier=device: LRU "
                "reclaim of an index page, demoted to host when the "
                "tier is on; tier=host: second-level LRU drop — the "
                "prefix is gone and will re-prefill)",
                labels=("tier",)),
            "host_pool_bytes": r.gauge(
                "paddle_kv_host_pool_bytes",
                "host-RAM KV tier bytes (kind=used: resident demoted "
                "pages; kind=capacity: PADDLE_KV_HOST_POOL_MB bound)",
                labels=("kind",)),
            "host_demotions": r.counter(
                "paddle_kv_host_demotions_total",
                "device prefix pages demoted into the host tier"),
            "host_promotions": r.counter(
                "paddle_kv_host_promotions_total",
                "host-tier pages promoted back to device on an "
                "admission hit (prefill work avoided)"),
        }
    return _TELEMETRY


def _engine_state(engine) -> dict:
    """Request-queue / scheduler state snapshot for flight-recorder dumps
    (a post-hang dump must show what the serving tier was doing)."""
    state = {"engine": engine._ENGINE, "running": engine._running,
             "queue_depth": engine._q.qsize()}
    for attr in ("batches_run", "decode_steps", "prefills", "max_batch",
                 "prefill_chunks", "cancelled_rows", "ragged_steps",
                 "token_budget", "ragged_prefill_tokens",
                 "ragged_decode_tokens", "padded_tokens_total",
                 "useful_tokens_total", "spec_drafted_tokens",
                 "spec_accepted_tokens", "spec_rounds", "spec_k",
                 "spec_draft_forwards", "spec_draft_ticks",
                 "quantized_linears", "sep_requests"):
        v = getattr(engine, attr, None)
        if v is not None:
            state[attr] = v
    buckets = getattr(engine, "ragged_buckets_used", None)
    if buckets:
        state["ragged_buckets_used"] = sorted(buckets)
    # per-request ages, oldest first: a watchdog dump must NAME the stuck
    # request (trace id + scheduler state), not just the stalled rank
    reqs = list(getattr(engine, "_inflight_reqs", {}).values())
    if reqs:
        now = time.perf_counter()
        ages = []
        for r in reqs:
            rows = getattr(r, "_rows", None)
            ages.append({
                "age_s": round(now - r.t_submit, 3),
                "state": (",".join(sorted({row.state for row in rows}))
                          if rows else "queued"),
                "trace": (r.trace.trace_id if r.trace is not None
                          else None),
                "cancelled": r.cancelled,
            })
        ages.sort(key=lambda a: -a["age_s"])
        state["oldest_request_age_s"] = ages[0]["age_s"]
        state["oldest_request_trace"] = ages[0]["trace"]
        state["request_ages"] = ages[:8]
    else:
        state["oldest_request_age_s"] = 0.0
    if getattr(engine, "enable_ragged", None) is not None:
        state["ragged"] = engine.enable_ragged
    if getattr(engine, "enable_spec", None) is not None:
        state["spec_decode"] = engine.enable_spec
    if getattr(engine, "draft_batch", None) is not None:
        state["draft_batch"] = engine.draft_batch
    if getattr(engine, "weight_dtype", None) is not None:
        state["weight_dtype"] = engine.weight_dtype
    cache = getattr(engine, "_cache", None)
    if cache is not None:
        # bytes, not just page counts: the int8-KV capacity win must be
        # visible in a hang dump without arithmetic
        page_nb = cache.page_nbytes
        state["prefix_cache"] = {
            "enabled": cache.enable_prefix_cache,
            "hits": cache.prefix_hits,
            "misses": cache.prefix_misses,
            "cached_tokens": cache.cached_tokens_total,
            "cow_copies": cache.cow_copies,
            "free_pages": cache.free_page_count,
            "used_pages": cache.used_page_count,
            "kv_dtype": cache.kv_dtype,
            "page_nbytes": page_nb,
            "pool_bytes_used": cache.used_page_count * page_nb,
            "pool_bytes_capacity": (cache.num_pages - 1) * page_nb,
            "rollbacks": cache.rollbacks,
            "tokens_rolled_back": cache.tokens_rolled_back,
        }
        hp = getattr(cache, "host_pool", None)
        if hp is not None:
            state["kv_host_tier"] = {
                "enabled": hp.enabled,
                "used_bytes": hp.used_bytes,
                "capacity_bytes": hp.max_bytes,
                "entries": len(hp),
                "demotions": hp.demotions,
                "promotions": hp.promotions,
                "evictions": hp.evictions,
                "device_evictions": cache.prefix_evictions_device,
                "promote_rejects": cache.host_promote_rejects,
            }
        if getattr(cache, "sep_stripes_stored", 0) or \
                getattr(engine, "sep_requests", 0):
            state["sep_prefill"] = {
                "stripes_stored": cache.sep_stripes_stored,
                "chunks": cache.sep_chunks,
                "decode_steps": cache.sep_decode_steps,
            }
    return state


class _Control:
    """A callable posted into the engine queue and executed by the serve
    loop at a tick boundary — the safe point to touch scheduler-owned
    state (the KV cache, slot tables) from another thread. The fleet
    router's disaggregation handoff (export/import of KV pages) rides on
    this."""

    def __init__(self, fn):
        self.fn = fn
        self.done = threading.Event()
        self.result = None
        self.error = None

    def run(self, engine):
        try:
            self.result = self.fn(engine)
        except Exception as e:        # noqa: BLE001 — fanned to the caller
            self.error = e
        finally:
            self.done.set()

    def fail(self, exc):
        if not self.done.is_set():
            self.error = exc
            self.done.set()


class _Request:
    def __init__(self, ids, max_new_tokens, kwargs, trace=None):
        self.ids = np.asarray(ids)
        if self.ids.ndim == 1:
            self.ids = self.ids[None]
        self.max_new_tokens = max_new_tokens
        self.kwargs = kwargs
        self.trace = trace             # request-trace context (or None)
        self.done = threading.Event()
        self.result = None
        self.error = None
        self.cancelled = False         # client gave up (timeout)
        self.t_submit = time.perf_counter()
        self.t_first = None            # first-token time (TTFT)


class ServingEngine:
    """Thread-safe batched ``generate`` front end.

    engine = ServingEngine(model, max_batch_size=8)
    engine.start()
    out = engine.generate(prompt_ids, max_new_tokens=64)   # blocks
    engine.stop()
    """

    _STOP = object()
    _ENGINE = "static"             # telemetry label

    def __init__(self, model, max_batch_size=8, batch_window_s=0.005,
                 use_paged_cache=True, page_size=16):
        # NB: generate() handles eval()/restore per call — constructing an
        # engine must not flip a training model's mode
        self.model = model
        self.max_batch = int(max_batch_size)
        self.window = float(batch_window_s)
        self.use_paged = use_paged_cache
        self.page_size = page_size
        self._q: queue.Queue = queue.Queue()
        self._thread = None
        self._running = False
        self._aborted = False
        self._inflight_reqs: dict = {}   # id(req) -> req (age tracking)
        self.batches_run = 0          # observability/testing

    # -- client API ----------------------------------------------------------
    def run_on_loop(self, fn, timeout=30.0):
        """Run ``fn(engine)`` on the serve-loop thread at the next tick
        boundary and return its result (raising its exception). The only
        safe way to inspect or mutate scheduler-owned state (e.g. the
        slot-paged KV cache) while the engine is serving."""
        if not self._running:
            raise RuntimeError("ServingEngine not started (call start())")
        ctl = _Control(fn)
        self._q.put(ctl)
        if not ctl.done.wait(timeout):
            raise TimeoutError("run_on_loop control not serviced")
        if ctl.error is not None:
            raise ctl.error
        return ctl.result

    def generate(self, input_ids, max_new_tokens=32, timeout=None,
                 trace=None, **kwargs):
        if not self._running:
            raise RuntimeError("ServingEngine not started (call start())")
        ids = input_ids.numpy() if isinstance(input_ids, Tensor) \
            else np.asarray(input_ids)
        # mint a request trace at direct engine admission (fleet-less
        # use); the router passes its own ctx through ``trace=`` and
        # stays the owner (it finishes the trace at delivery)
        trace_owned = False
        if trace is None and _rt.is_enabled():
            trace = _rt.start_request(
                source=self._ENGINE, prompt_tokens=int(ids.shape[-1]),
                max_new_tokens=int(max_new_tokens))
            trace_owned = True
        req = _Request(ids, max_new_tokens, kwargs, trace=trace)
        tele = _telemetry()
        tele["requests"].inc(engine=self._ENGINE)
        self._inflight_reqs[id(req)] = req
        tele["active_reqs"].set(len(self._inflight_reqs),
                                engine=self._ENGINE)
        self._q.put(req)
        tele["qdepth"].set(self._q.qsize())
        deadline = None if timeout is None else time.monotonic() + timeout
        try:
            while not req.done.is_set():
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    # the scheduler must not keep decoding for a client
                    # that gave up: pending rows are skipped at admission,
                    # active slots/pages freed at the next step boundary
                    req.cancelled = True
                    _rt.add_event(trace, "timeout", engine=self._ENGINE)
                    if trace_owned:
                        _rt.finish_request(trace, status="timeout")
                    raise TimeoutError("generate timed out")
                th = self._thread
                worker_alive = th is not None and th.is_alive()
                if not self._running and not worker_alive:
                    # raced with stop() AND the worker (whose exit path
                    # fails every still-queued request) is gone: our
                    # request provably missed the drain — fail it here
                    # rather than hang
                    if not req.done.is_set():
                        req.error = RuntimeError("ServingEngine stopped")
                        req.done.set()
                    break
                req.done.wait(0.5 if remaining is None
                              else min(0.5, remaining))
            if req.error is not None:
                _rt.add_event(trace, "engine_error",
                              error=type(req.error).__name__)
                if trace_owned:
                    _rt.finish_request(trace, status="error")
                raise req.error
            if trace_owned:
                # thread the delivered-token-stream digest into the
                # trace's terminal span (fleet-less attestation record)
                dg = (_ledger.stream_digest(trace.trace_id)
                      if _ledger.is_enabled() and trace is not None
                      else None)
                _rt.finish_request(trace, status="ok",
                                   **({"token_digest": dg} if dg else {}))
            return Tensor(req.result)
        finally:
            self._inflight_reqs.pop(id(req), None)
            tele["active_reqs"].set(len(self._inflight_reqs),
                                    engine=self._ENGINE)

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self._running:
            return self
        # drain stale stop tokens from a previous stop() so the new
        # worker doesn't die on arrival
        try:
            while True:
                item = self._q.get_nowait()
                if item is not self._STOP and item is not None:
                    self._q.put(item)
                    break
        except queue.Empty:
            pass
        self._running = True
        self._aborted = False
        import weakref
        from ..profiler import flight_recorder as _flight
        self._flight_key = f"serving_{self._ENGINE}_{id(self):x}"
        wr = weakref.ref(self)     # the provider registry must not keep a
        #                            stopped-but-unstopped engine alive
        _flight.register_state_provider(
            self._flight_key,
            lambda: _engine_state(wr()) if wr() is not None else {})
        if not getattr(self, "_exporter_managed", False):
            # standalone engine: its own telemetry endpoint when the
            # plane is on (a router-fronted engine's exporter is owned
            # by the router, named by replica id — see _exporter_managed)
            from ..profiler import exporter as _exp
            self._exporter = _exp.maybe_start_exporter(
                instance=os.environ.get("PADDLE_TELEMETRY_INSTANCE")
                or f"{self._ENGINE}-{os.getpid()}")
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if not self._running and self._thread is None:
            return
        self._running = False
        self._q.put(self._STOP)
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        # unregister AFTER the drain: a watchdog dump taken while the
        # engine winds down must still see its state, and repeated
        # start/stop (the fleet router's drain/rejoin cycle) must never
        # accumulate stale providers in dumps
        key = getattr(self, "_flight_key", None)
        if key is not None:
            from ..profiler import flight_recorder as _flight
            _flight.unregister_state_provider(key)
            self._flight_key = None
        exp = getattr(self, "_exporter", None)
        if exp is not None:
            exp.stop()
            self._exporter = None

    def abort(self):
        """Hard stop: fail every queued AND in-flight request instead of
        draining decodes to completion — the fleet tier's simulated
        replica death (a real process kill has no drain either)."""
        self._aborted = True
        self.stop()

    # -- scheduler -----------------------------------------------------------
    def _collect(self):
        """Block for one request, then drain compatible ones within the
        window. Groups by (prompt_len, max_new_tokens, kwargs) — equal
        shapes keep the decode batch fixed-shape."""
        first = self._q.get()
        while isinstance(first, _Control):
            first.run(self)
            first = self._q.get()
        if first is self._STOP or first is None:
            return None
        group = [first]
        key = (first.ids.shape[1], first.max_new_tokens,
               tuple(sorted(first.kwargs.items())))
        deadline = time.monotonic() + self.window
        leftovers = []
        try:
            while sum(r.ids.shape[0] for r in group) < self.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if isinstance(nxt, _Control):
                    nxt.run(self)
                    continue
                if nxt is self._STOP or nxt is None:
                    self._q.put(self._STOP)  # re-post the stop token
                    break
                k = (nxt.ids.shape[1], nxt.max_new_tokens,
                     tuple(sorted(nxt.kwargs.items())))
                if k == key and (sum(r.ids.shape[0] for r in group)
                                 + nxt.ids.shape[0]) <= self.max_batch:
                    group.append(nxt)
                else:
                    leftovers.append(nxt)
        finally:
            for r in leftovers:             # incompatible: next rounds
                self._q.put(r)
        return group

    def _loop(self):
        try:
            self._serve()
        finally:
            # fail any stranded requests (queued behind the stop token /
            # leftovers re-queued after it) instead of blocking callers
            try:
                while True:
                    item = self._q.get_nowait()
                    if isinstance(item, _Request):
                        item.error = RuntimeError("ServingEngine stopped")
                        item.done.set()
                    elif isinstance(item, _Control):
                        item.fail(RuntimeError("ServingEngine stopped"))
            except queue.Empty:
                pass

    def _serve(self):
        tele = _telemetry()
        while self._running:
            group = self._collect()
            if group is None:
                break
            # a timed-out client already raised; don't burn a batch on it
            group = [r for r in group if not r.cancelled]
            if not group:
                continue
            t_admit = time.perf_counter()
            for r in group:
                tele["queue_wait"].observe(t_admit - r.t_submit,
                                           engine=self._ENGINE)
                _rt.add_span(r.trace, "queue_wait", t0=r.t_submit,
                             dur=t_admit - r.t_submit, engine=self._ENGINE)
            try:
                batch = np.concatenate([r.ids for r in group], axis=0)
                kwargs = dict(group[0].kwargs)
                if self.use_paged:
                    kwargs.setdefault("use_paged_cache", True)
                    kwargs.setdefault("page_size", self.page_size)
                out = self.model.generate(
                    Tensor(batch), max_new_tokens=group[0].max_new_tokens,
                    **kwargs)
                arr = np.asarray(out.numpy())
                self.batches_run += 1
                prompt_len = group[0].ids.shape[1]
                # the static window batcher emits the whole completion at
                # once, so first-token time == completion time
                t_done = time.perf_counter()
                for r in group:
                    r.t_first = t_done
                    tele["ttft"].observe(t_done - r.t_submit,
                                         engine=self._ENGINE)
                    # the window batcher emits the whole completion at
                    # once: one batch span + one token mark per request
                    _rt.add_span(r.trace, "batch_generate", t0=t_admit,
                                 dur=t_done - t_admit,
                                 batch=len(group), engine=self._ENGINE)
                    _rt.note_token(r.trace, t_done)
                tele["tokens"].inc(
                    (arr.shape[1] - prompt_len) * arr.shape[0],
                    engine=self._ENGINE)
                eos = kwargs.get("eos_token_id")
                row = 0
                for r in group:
                    n = r.ids.shape[0]
                    res = arr[row:row + n]
                    if eos is not None and arr.shape[1] > prompt_len:
                        # trim co-batch eos padding: a request's output
                        # must not depend on its batch-mates' lengths
                        gen = res[:, prompt_len:]
                        hits = np.argmax(gen == eos, axis=1)
                        has = (gen == eos).any(axis=1)
                        stop = int(np.max(np.where(has, hits + 1,
                                                   gen.shape[1])))
                        res = res[:, :prompt_len + stop]
                    r.result = res
                    row += n
                    r.done.set()
            except Exception as e:          # fan the failure out, keep serving
                for r in group:
                    r.error = e
                    r.done.set()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class _Row:
    """One sequence of a request inside the continuous scheduler."""

    def __init__(self, req, ids, row_idx=0):
        self.req = req
        self.row_idx = int(row_idx)          # row within the request
        self.prompt = np.asarray(ids)        # [s]
        self.generated: list = []
        self.done = False
        self.state = "queued"                # queued -> prefill -> decode
        self.sep = False                     # long-context sep-ring row
        self._key_base = None                # seeded-sampling PRNG base


class ContinuousServingEngine:
    """Continuous-batching serving engine (reference: the vLLM-style
    scheduler the serving tier around ``fused_multi_transformer`` targets;
    VERDICT.md round-2 item 8 — per-step admit/evict over the paged KV
    cache, replacing :class:`ServingEngine`'s static same-shape windows).

    TPU-native scheduling: admission is NON-BLOCKING — it only maps a
    request onto a free slot of a :class:`SlotPagedKVCache` (prompt
    blocks that hit the prefix index reuse already-filled pages with no
    model work at all); the uncached prompt suffix then prefills in
    fixed-bucket chunks of at most ``prefill_chunk_tokens``, with a
    ``[max_batch, 1]`` decode step interleaved between chunks so a long
    prompt never head-of-line-blocks active decodes. Sequences of
    different prompt lengths and decode budgets share every step, a
    finished sequence's slot is reused immediately, and the engine runs
    a bounded set of compiled programs (the power-of-two chunk buckets
    plus the fixed-shape decode step).

    engine = ContinuousServingEngine(model, max_batch_size=8)
    engine.start()
    out = engine.generate(prompt_ids, max_new_tokens=64)   # blocks
    engine.stop()

    **Ragged continuous batching (default).** Each tick packs up to
    ``token_budget`` tokens into ONE flat batch — every live decode
    slot's single token plus as many prefill tokens as fit (per-span cap
    ``prefill_chunk_tokens``) — and runs them through the single ragged
    paged-attention program family (Ragged Paged Attention, arxiv
    2604.15464). The batch is padded to a bounded bucket set, so the
    whole mixed prefill+decode workload compiles a small fixed family of
    programs and decode liveness no longer trades against the prefill
    chunk budget. ``PADDLE_SERVING_RAGGED=0`` / ``enable_ragged=False``
    restores the legacy two-program scheduler (one prefill chunk + one
    fixed-shape decode step per tick).

    Prefix caching defaults on; disable with ``enable_prefix_cache=False``
    or ``PADDLE_SERVING_PREFIX_CACHE=0`` (legacy per-request prefill
    behavior, still chunked). ``prefill_chunk_tokens`` >= ``max_len``
    restores monolithic prefill.
    """

    _STOP = ServingEngine._STOP
    _ENGINE = "continuous"         # telemetry label

    def __init__(self, model, max_batch_size=8, page_size=16, max_len=2048,
                 pad_token_id=0, prefill_chunk_tokens=None,
                 enable_prefix_cache=None, num_pages=None,
                 token_budget=None, enable_ragged=None, kv_dtype=None,
                 spec_decode=None, spec_k=None, drafter=None,
                 draft_model=None, weight_dtype=None, draft_batch=None,
                 host_pool_mb=None, sep_prefill=None,
                 sep_stripe_tokens=None, sep_threshold_tokens=None):
        self.model = model
        # end-to-end int8 weights (PADDLE_WEIGHT_DTYPE=int8): every
        # nn.Linear swaps its weight for (int8, per-channel scale) and
        # forwards through the Pallas int8 GEMM — composes with
        # kv_dtype="int8" for a fully-quantized serving config
        if weight_dtype is None:
            weight_dtype = os.environ.get("PADDLE_WEIGHT_DTYPE") or None
        self.weight_dtype = str(weight_dtype).lower() if weight_dtype \
            else None
        if self.weight_dtype not in (None, "int8"):
            raise ValueError(f"unsupported weight_dtype "
                             f"{self.weight_dtype!r} (expected 'int8')")
        if self.weight_dtype == "int8":
            from ..quantization import quantize_linears
            self.quantized_linears = quantize_linears(model)
        else:
            self.quantized_linears = 0
        self.max_batch = int(max_batch_size)
        self.page_size = int(page_size)
        self.max_len = int(max_len)
        self.pad_token_id = int(pad_token_id)
        if enable_prefix_cache is None:
            enable_prefix_cache = os.environ.get(
                "PADDLE_SERVING_PREFIX_CACHE", "1") != "0"
        self.enable_prefix_cache = bool(enable_prefix_cache)
        if prefill_chunk_tokens is None:
            prefill_chunk_tokens = int(os.environ.get(
                "PADDLE_SERVING_CHUNK_TOKENS",
                str(DEFAULT_PREFILL_CHUNK_TOKENS)))
        self.chunk_tokens = max(int(prefill_chunk_tokens), 1)
        if enable_ragged is None:
            enable_ragged = os.environ.get(
                "PADDLE_SERVING_RAGGED", "1") != "0"
        self.enable_ragged = bool(enable_ragged)
        if token_budget is None:
            token_budget = int(os.environ.get(
                "PADDLE_SERVING_TOKEN_BUDGET",
                str(DEFAULT_SERVING_TOKEN_BUDGET)))
        # every live decode slot is entitled to its 1 token per tick, so
        # the effective budget never starves decode — clamping here (not
        # per tick) keeps the compiled bucket set fixed for the engine's
        # lifetime
        self.token_budget = max(int(token_budget), self.max_batch, 1)
        self.num_pages = num_pages
        self.kv_dtype = kv_dtype       # None => cache reads PADDLE_KV_DTYPE
        # speculative decoding (PADDLE_SPEC_DECODE=1): a drafter proposes
        # up to spec_k tokens per live decode slot each tick; the ragged
        # forward verifies them as one q_len=k+1 span and the scheduler
        # keeps the longest matching prefix (greedy acceptance => output
        # bit-identical to plain greedy). Requires the ragged scheduler —
        # the legacy fixed-shape decode step has no multi-token span.
        if spec_decode is None:
            spec_decode = os.environ.get("PADDLE_SPEC_DECODE", "0") == "1"
        self.enable_spec = bool(spec_decode)
        if spec_k is None:
            from .speculative import DEFAULT_SPEC_K
            spec_k = int(os.environ.get("PADDLE_SPEC_K",
                                        str(DEFAULT_SPEC_K)))
        self.spec_k = max(int(spec_k), 1)
        if self.enable_spec and not self.enable_ragged:
            raise ValueError(
                "speculative decoding needs the ragged scheduler "
                "(enable_ragged=True / PADDLE_SERVING_RAGGED=1): "
                "verification is a q_len=k+1 ragged span")
        self._drafter = None
        if self.enable_spec:
            if drafter is None:
                from .speculative import make_drafter
                drafter = make_drafter(draft_model=draft_model)
            self._drafter = drafter
        # batched drafting (PADDLE_SPEC_DRAFT_BATCH, default on): one
        # padded draft forward per tick for every live decode slot
        # instead of one forward per slot per drafted token — proposals
        # stay bit-identical (greedy + causal right-padding), only the
        # forward count drops
        if draft_batch is None:
            draft_batch = os.environ.get(
                "PADDLE_SPEC_DRAFT_BATCH", "1") != "0"
        self.draft_batch = bool(draft_batch)
        # tiered KV: the engine owns ONE host pool across cache rebuilds
        # (a serve-loop crash must not flush the warm tier); 0 MB keeps
        # the tier off and eviction behavior exactly legacy
        from ..models.generation import HostKVPool
        if host_pool_mb is None:
            host_pool_mb = float(os.environ.get(
                "PADDLE_KV_HOST_POOL_MB", "0") or 0)
        self.host_pool_mb = float(host_pool_mb)
        if self.host_pool_mb < 0:
            raise ValueError(f"host_pool_mb must be >= 0, got "
                             f"{self.host_pool_mb}")
        self._host_pool = HostKVPool(self.host_pool_mb)
        self._kv_tier_seen: dict = {}   # counter baselines for telemetry
        # sep-parallel long-context prefill (PADDLE_SEP_PREFILL=1):
        # prompts past the threshold are chunked into fixed
        # PADDLE_SEP_STRIPE_TOKENS stripes attended with the
        # ring-attention schedule — the device page pool only ever holds
        # the decode tail, so a prompt far larger than the pool serves
        if sep_prefill is None:
            sep_prefill = os.environ.get("PADDLE_SEP_PREFILL", "0") == "1"
        self.sep_prefill_enabled = bool(sep_prefill)
        if sep_stripe_tokens is None:
            sep_stripe_tokens = int(os.environ.get(
                "PADDLE_SEP_STRIPE_TOKENS", str(DEFAULT_SEP_STRIPE_TOKENS)))
        self.sep_stripe = int(sep_stripe_tokens)
        if sep_threshold_tokens is None:
            sep_threshold_tokens = int(os.environ.get(
                "PADDLE_SEP_THRESHOLD_TOKENS", "0"))
        self.sep_threshold = int(sep_threshold_tokens)
        self.sep_requests = 0
        if self.sep_prefill_enabled:
            if not self.enable_ragged:
                raise ValueError(
                    "sep prefill needs the ragged scheduler "
                    "(enable_ragged=True / PADDLE_SERVING_RAGGED=1)")
            if self.sep_stripe <= 0 or self.sep_stripe % self.page_size:
                raise ValueError(
                    f"sep_stripe_tokens {self.sep_stripe} must be a "
                    f"positive multiple of page_size {self.page_size}")
            kv = self.kv_dtype
            if kv is None:
                kv = os.environ.get("PADDLE_KV_DTYPE", "auto")
            if str(kv).lower() == "int8":
                raise ValueError("sep prefill requires native KV pages "
                                 "(kv_dtype=int8 is unsupported)")
        self.spec_drafted_tokens = 0
        self.spec_accepted_tokens = 0
        self.spec_rounds = 0           # verify spans with >= 1 draft
        self.spec_draft_forwards = 0   # draft-model forwards observed
        self.spec_draft_ticks = 0      # ticks that ran the drafter
        self._q: queue.Queue = queue.Queue()
        self._thread = None
        self._running = False
        self._aborted = False
        self._inflight_reqs = {}       # id(req) -> req (age tracking)
        self._cache = None
        # observability (and the "beats static batching" proof in tests)
        self.decode_steps = 0
        self.prefills = 0              # rows admitted (one per sequence)
        self.prefill_chunks = 0        # chunk forwards run
        self.cancelled_rows = 0
        self.ragged_steps = 0          # ragged packed forwards run
        self.ragged_prefill_tokens = 0
        self.ragged_decode_tokens = 0
        # padded-vs-useful accounting for BOTH schedulers (the bench's
        # waste-ratio metric): padded counts every token position a
        # compiled program processed, useful only the real ones
        self.padded_tokens_total = 0
        self.useful_tokens_total = 0
        #: bucket sizes actually compiled — the inventory guard asserts
        #: this stays inside :meth:`declared_token_buckets`
        self.ragged_buckets_used: set = set()
        # scheduling trace for liveness tests / debugging: ("chunk",
        # slot, n_valid, done) and ("decode", n_active) events in order
        # (the ragged scheduler emits both per packed tick)
        self.events: deque = deque(maxlen=4096)
        self._declare_programs()

    def declared_token_buckets(self):
        """The ragged scheduler's full compiled-shape family: every tick's
        flat token batch is padded to one of these sizes, so the number
        of compiled programs is bounded for the engine's lifetime
        regardless of traffic mix (enforced by tools/check_inventory.py's
        serving-program guard)."""
        out, b = set(), 1
        while b < self.token_budget:
            out.add(b)
            b *= 2
        out.add(self.token_budget)
        return out

    def declared_chunk_buckets(self):
        """The legacy prefill path's compiled-shape family: every chunk
        pads to one of these widths (:func:`_chunk_bucket`, pow2 min 8
        capped at ``chunk_tokens``)."""
        out, b = set(), 8
        while b < self.chunk_tokens:
            out.add(b)
            b *= 2
        out.add(self.chunk_tokens)
        return out

    def declared_draft_buckets(self):
        """The batched drafter's compiled-shape family: (rows, width)
        both pow2-bucketed (:func:`speculative._pow2_bucket`), rows up
        to the engine's slot count, width capped at the draft window.
        Returns ``(rows_buckets, width_buckets)`` or None when batched
        drafting is off / the drafter has no batch path."""
        if not (self.enable_spec and self.draft_batch
                and hasattr(self._drafter, "propose_batch")):
            return None
        from .speculative import _pow2_bucket
        rows, b = set(), 1
        while b < _pow2_bucket(self.max_batch):
            rows.add(b)
            b *= 2
        rows.add(_pow2_bucket(self.max_batch))
        window = int(getattr(self._drafter, "window", 64))
        widths, b = set(), 1
        while b < window:
            widths.add(b)
            b *= 2
        widths.add(window)
        return rows, widths

    def _static_args(self):
        """Static (non-shape) parts of every serving program signature:
        a dtype flip recompiles the whole family, and the observatory's
        cause string must say so (``static arg `weight_dtype`
        int8→native``)."""
        kv = self.kv_dtype
        if kv is None:
            kv = os.environ.get("PADDLE_KV_DTYPE", "auto")
        kv = "native" if str(kv).lower() == "auto" else str(kv).lower()
        return {"weight_dtype": _co.static_arg(self.weight_dtype
                                               or "native"),
                "kv_dtype": _co.static_arg(kv)}

    def _ragged_signature(self, padded):
        sig = {"tokens": _co.tensor_arg((int(padded),), "int64")}
        sig.update(self._static_args())
        return sig

    def _chunk_signature(self, padded):
        sig = {"tokens": _co.tensor_arg((int(padded),), "int64")}
        sig.update(self._static_args())
        return sig

    def _decode_signature(self):
        sig = {"tokens": _co.tensor_arg((self.max_batch, 1), "int64")}
        sig.update(self._static_args())
        return sig

    def _sep_max_stripes(self):
        return self.max_len // max(self.sep_stripe, 1)

    def _sep_tail_buckets(self):
        """pow2 tail-page windows a sep decode step can compile with
        (the cache always gathers the pure power of two)."""
        import math as _math
        pages_per_seq = -(-self.max_len // self.page_size)
        out, b = set(), 1
        while b < pages_per_seq:
            out.add(b)
            b *= 2
        out.add(b)
        return out

    def _sep_prefill_signature(self, n_stripes):
        # the chunk shape is fixed at the stripe length; the unrolled
        # ring loop makes the STRIPE COUNT part of the program identity
        sig = {"tokens": _co.tensor_arg((self.sep_stripe,), "int64"),
               "stripes": _co.tensor_arg((int(n_stripes),), "int32")}
        sig.update(self._static_args())
        return sig

    def _sep_decode_signature(self, n_stripes, tail_pages):
        sig = {"tokens": _co.tensor_arg((1,), "int64"),
               "stripes": _co.tensor_arg((int(n_stripes),), "int32"),
               "tail_pages": _co.tensor_arg((int(tail_pages),), "int32")}
        sig.update(self._static_args())
        return sig

    def _host_promote_signature(self):
        # one page's writeback is the compiled unit (fixed page shape)
        sig = {"pages": _co.tensor_arg((1,), "int32")}
        sig.update(self._static_args())
        return sig

    def _declare_programs(self):
        """Declare this engine's program families (bucket sets + warmup
        entries) with the compile observatory, so serve-time observations
        can be checked against the inventory and causes can name the
        offending bucket. Declaration is construction-time bookkeeping —
        the hot-path gate stays :func:`compile_observatory.is_enabled`."""
        import weakref
        ref = weakref.ref(self)

        def warm(names):
            eng = ref()
            return eng.warmup_programs(families=names) if eng else {}

        if self.enable_ragged:
            _co.declare_family(
                "serving.ragged",
                buckets={"tokens": sorted(self.declared_token_buckets())},
                warmup=lambda: warm(("serving.ragged",)))
        else:
            _co.declare_family(
                "serving.prefill_chunk",
                buckets={"tokens": sorted(self.declared_chunk_buckets())},
                warmup=lambda: warm(("serving.prefill_chunk",)))
            _co.declare_family(
                "serving.decode",
                buckets={"tokens": [self.max_batch]},
                warmup=lambda: warm(("serving.decode",)))
        draft = self.declared_draft_buckets()
        if draft is not None:
            rows, widths = draft
            _co.declare_family(
                "spec.draft_batch",
                buckets={"tokens": {0: sorted(rows), 1: sorted(widths)}},
                warmup=lambda: warm(("spec.draft_batch",)))
        if self.sep_prefill_enabled:
            max_stripes = self._sep_max_stripes()
            _co.declare_family(
                "serving.sep_prefill",
                buckets={"tokens": [self.sep_stripe],
                         "stripes": list(range(max_stripes + 1))},
                warmup=lambda: warm(("serving.sep_prefill",)))
            _co.declare_family(
                "serving.sep_decode",
                buckets={"tokens": [1],
                         "stripes": list(range(max_stripes + 1)),
                         "tail_pages": sorted(self._sep_tail_buckets())},
                warmup=lambda: warm(("serving.sep_decode",)))
        if self._host_pool.enabled:
            _co.declare_family(
                "kv.host_promote", buckets={"pages": [1]},
                warmup=lambda: warm(("kv.host_promote",)))

    def warmup_programs(self, families=None):
        """Pre-compile every declared signature of this engine's program
        families and record the observations, so steady-state traffic
        sees ZERO observatory misses (and pays no first-request compile
        tax). Runs each declared bucket shape once through the real
        forward path on a scratch KV cache; call before :meth:`start`
        (or through :meth:`run_on_loop` on a live engine). Returns
        ``{family: wall_seconds}``."""
        from ..autograd.tape import no_grad
        from ..models.generation import SlotPagedKVCache
        names = None if families is None else set(families)

        def want(n):
            return names is None or n in names

        out = {}
        was_training = self.model.training
        self.model.eval()
        try:
            with no_grad():
                cache = SlotPagedKVCache(
                    self.max_batch, page_size=self.page_size,
                    max_len=self.max_len, num_pages=self.num_pages,
                    enable_prefix_cache=False, kv_dtype=self.kv_dtype,
                    allow_page_overcommit=self.sep_prefill_enabled)
                if self.enable_ragged and want("serving.ragged"):
                    t0 = time.perf_counter()
                    for b in sorted(self.declared_token_buckets()):
                        flat = np.full(b, self.pad_token_id, np.int64)
                        pos = np.zeros(b, np.int32)
                        cache.begin_ragged([(0, 0, 1)])
                        t_run = time.perf_counter()
                        self.model.forward(Tensor(flat[None]), cache=cache,
                                           position_ids=pos)
                        _co.observe("serving.ragged",
                                    self._ragged_signature(b),
                                    seconds=time.perf_counter() - t_run)
                        cache.free(0)
                    out["serving.ragged"] = time.perf_counter() - t0
                if not self.enable_ragged and want("serving.prefill_chunk"):
                    t0 = time.perf_counter()
                    for b in sorted(self.declared_chunk_buckets()):
                        cache.assign(0, np.zeros(1, np.int64))
                        cache.begin_prefill(0, 1)
                        chunk = np.full(b, self.pad_token_id, np.int64)
                        pos = np.zeros(b, np.int32)
                        t_run = time.perf_counter()
                        self.model.forward(Tensor(chunk[None]), cache=cache,
                                           position_ids=pos)
                        _co.observe("serving.prefill_chunk",
                                    self._chunk_signature(b),
                                    seconds=time.perf_counter() - t_run)
                        cache.free(0)
                    out["serving.prefill_chunk"] = time.perf_counter() - t0
                if not self.enable_ragged and want("serving.decode"):
                    t0 = time.perf_counter()
                    cache.assign(0, np.zeros(1, np.int64))
                    cache.begin_prefill(0, 1)
                    self.model.forward(
                        Tensor(np.zeros((1, 8), np.int64)), cache=cache,
                        position_ids=np.zeros(8, np.int32))
                    mask = np.zeros(self.max_batch, bool)
                    mask[0] = True
                    cache.begin_decode(mask)
                    cur = np.full((self.max_batch, 1), self.pad_token_id,
                                  np.int64)
                    pos = cache.lens.astype(np.int32)[:, None]
                    t_run = time.perf_counter()
                    self.model.forward(Tensor(cur), cache=cache,
                                       position_ids=pos)
                    _co.observe("serving.decode", self._decode_signature(),
                                seconds=time.perf_counter() - t_run)
                    cache.free(0)
                    out["serving.decode"] = time.perf_counter() - t0
                draft = self.declared_draft_buckets()
                if draft is not None and want("spec.draft_batch"):
                    rows, widths = draft
                    t0 = time.perf_counter()
                    for r in sorted(rows):
                        for w in sorted(widths):
                            batch = np.zeros((r, w), np.int64)
                            t_run = time.perf_counter()
                            self._drafter.model.forward(Tensor(batch))
                            _co.observe(
                                "spec.draft_batch",
                                {"tokens": _co.tensor_arg((r, w), "int64")},
                                seconds=time.perf_counter() - t_run)
                    out["spec.draft_batch"] = time.perf_counter() - t0
                if self.sep_prefill_enabled and (
                        want("serving.sep_prefill")
                        or want("serving.sep_decode")):
                    # one full long-context span walks the ring-prefill
                    # family through every stripe count, then one decode
                    # step compiles the stripes+tail read
                    t0 = time.perf_counter()
                    sep_cache = SlotPagedKVCache(
                        self.max_batch, page_size=self.page_size,
                        max_len=self.max_len, num_pages=self.num_pages,
                        enable_prefix_cache=False, kv_dtype=self.kv_dtype,
                        allow_page_overcommit=True)
                    stripe = self.sep_stripe
                    n = min(self.max_len - 2,
                            self._sep_max_stripes() * stripe
                            + max(stripe // 2, 1))
                    sep_cache.assign_sep(0, n, stripe)
                    pos0 = 0
                    while pos0 < n:
                        nv = min(stripe, n - pos0)
                        ns = len(sep_cache._sep[0]["stripes"])
                        chunk = np.full(stripe, self.pad_token_id,
                                        np.int64)
                        pos = np.minimum(
                            np.arange(pos0, pos0 + stripe,
                                      dtype=np.int32), pos0 + nv - 1)
                        sep_cache.begin_sep_prefill(0, nv)
                        t_run = time.perf_counter()
                        self.model.forward(Tensor(chunk[None]),
                                           cache=sep_cache,
                                           position_ids=pos)
                        if want("serving.sep_prefill"):
                            _co.observe(
                                "serving.sep_prefill",
                                self._sep_prefill_signature(ns),
                                seconds=time.perf_counter() - t_run)
                        pos0 += nv
                    if want("serving.sep_prefill"):
                        out["serving.sep_prefill"] = \
                            time.perf_counter() - t0
                    if want("serving.sep_decode"):
                        t0 = time.perf_counter()
                        view = sep_cache.sep_view(0)
                        sep_cache.begin_sep_decode(0)
                        cur = np.full((1, 1), self.pad_token_id, np.int64)
                        dpos = np.asarray([[int(sep_cache.lens[0])]],
                                          np.int32)
                        t_run = time.perf_counter()
                        self.model.forward(Tensor(cur), cache=sep_cache,
                                           position_ids=dpos)
                        _co.observe(
                            "serving.sep_decode",
                            self._sep_decode_signature(
                                view["stripes"], view["tail_pages"]),
                            seconds=time.perf_counter() - t_run)
                        out["serving.sep_decode"] = \
                            time.perf_counter() - t0
                    sep_cache.free(0)
                if self._host_pool.enabled and want("kv.host_promote"):
                    # demote -> promote roundtrip on a scratch cache and
                    # a scratch pool (the live tier must stay untouched)
                    from ..models.generation import HostKVPool
                    t0 = time.perf_counter()
                    hcache = SlotPagedKVCache(
                        1, page_size=self.page_size, max_len=self.max_len,
                        enable_prefix_cache=True, kv_dtype=self.kv_dtype,
                        host_pool=HostKVPool(max(self.host_pool_mb, 64)))
                    n = 2 * self.page_size
                    prompt = np.zeros(n, np.int64)
                    hcache.assign(0, prompt)
                    hcache.begin_prefill(0, n)
                    self.model.forward(
                        Tensor(prompt[None]), cache=hcache,
                        position_ids=np.arange(n, dtype=np.int32))
                    hcache.commit_prefix(0)
                    hcache.free(0)
                    while hcache._evict_lru():
                        pass
                    t_run = time.perf_counter()
                    hcache.assign(0, prompt)   # host hit -> promotion
                    _co.observe("kv.host_promote",
                                self._host_promote_signature(),
                                seconds=time.perf_counter() - t_run)
                    hcache.free(0)
                    out["kv.host_promote"] = time.perf_counter() - t0
        finally:
            if was_training:
                self.model.train()
        return out

    def generate(self, input_ids, max_new_tokens=32, max_length=None,
                 timeout=None, trace=None, **kwargs):
        ids = input_ids.numpy() if isinstance(input_ids, Tensor) \
            else np.asarray(input_ids)
        if ids.ndim == 1:
            ids = ids[None]
        if max_length is not None:           # GenerationMixin contract
            max_new_tokens = max(int(max_length) - ids.shape[1], 0)
        if max_new_tokens <= 0:              # zero budget: prompt unchanged
            return Tensor(ids)
        if ids.shape[1] + max_new_tokens > self.max_len:
            # fail THIS request up front — admitted-then-overflowing would
            # poison every co-scheduled request via the batch error path
            raise ValueError(
                f"request needs {ids.shape[1]} + {max_new_tokens} tokens "
                f"> engine max_len {self.max_len}")
        return ServingEngine.generate(self, ids,
                                      max_new_tokens=max_new_tokens,
                                      timeout=timeout, trace=trace,
                                      **kwargs)

    start = ServingEngine.start
    run_on_loop = ServingEngine.run_on_loop
    abort = ServingEngine.abort
    stop = ServingEngine.stop
    _loop = ServingEngine._loop
    __enter__ = ServingEngine.__enter__
    __exit__ = ServingEngine.__exit__

    # -- scheduler ----------------------------------------------------------
    def _admit(self, cache, free, active, pending, prefill_q, sep_q=None):
        """Non-blocking admission: map waiting rows onto free slots and
        match their prompts against the prefix index — NO model work
        happens here (the prefill itself runs chunk-by-chunk in the main
        loop, interleaved with decode steps). Prompts past the sep
        threshold route to the sep-parallel ring-prefill queue instead
        of the paged prefix path."""
        tele = _telemetry()
        while free and pending:
            row = pending.popleft()
            if row.req.cancelled:          # client already gave up
                row.done = True
                self.cancelled_rows += 1
                continue
            slot = free.popleft()
            now = time.perf_counter()
            tele["queue_wait"].observe(now - row.req.t_submit,
                                       engine=self._ENGINE)
            _rt.add_span(row.req.trace, "queue_wait",
                         t0=row.req.t_submit, dur=now - row.req.t_submit,
                         engine=self._ENGINE)
            if row.prompt.shape[0] < 1:
                raise ValueError("cannot serve an empty prompt")
            if sep_q is not None and \
                    self._sep_engaged(cache, row.prompt.shape[0]):
                cache.assign_sep(slot, row.prompt.shape[0],
                                 self.sep_stripe)
                row.sep = True
                row.state = "prefill"
                active[slot] = row
                sep_q.append(slot)
                self.prefills += 1
                self.sep_requests += 1
                _rt.add_event(row.req.trace, "admit_sep", slot=slot,
                              tokens=int(row.prompt.shape[0]),
                              stripe=self.sep_stripe,
                              engine=self._ENGINE)
                continue
            p0 = self._host_pool.promotions
            t_assign = time.perf_counter()
            cached, hits, misses = cache.assign(slot, row.prompt)
            if _co.is_enabled() and self._host_pool.promotions > p0:
                # the promote path stages host blobs onto device pages —
                # a distinct program family (H2D copies + dequant)
                _co.observe("kv.host_promote",
                            self._host_promote_signature(),
                            seconds=time.perf_counter() - t_assign)
            tele["prefix_hits"].inc(hits)
            tele["prefix_misses"].inc(misses)
            tele["prefix_cached"].inc(cached)
            _rt.add_event(row.req.trace, "admit", slot=slot,
                          cached_tokens=int(cached), prefix_hits=int(hits),
                          prefix_misses=int(misses), engine=self._ENGINE)
            row.state = "prefill"
            active[slot] = row
            prefill_q.append(slot)
            self.prefills += 1

    def _prefill_chunk(self, cache, free, active, prefill_q):
        """Run ONE fixed-bucket prefill chunk for the longest-waiting
        mid-prefill slot. On the final chunk, sample the first token and
        hand the row to the decode path; the prompt's full blocks are
        registered in the prefix index for later reuse."""
        from ..models.generation import _sample_logits
        tele = _telemetry()
        slot = prefill_q[0]
        row = active[slot]
        start = int(cache.lens[slot])
        n_valid = min(self.chunk_tokens, row.prompt.shape[0] - start)
        # the padded shape comes ONLY from the fixed bucket set — never
        # clamped to max_len - start, which would compile a dedicated
        # program per request tail (pad positions past the slot's page
        # table scatter to the scratch page, so over-padding is safe)
        padded = _chunk_bucket(n_valid, self.chunk_tokens)
        chunk = np.full(padded, self.pad_token_id, row.prompt.dtype)
        chunk[:n_valid] = row.prompt[start:start + n_valid]
        # pad positions clip to the last valid position (their rope /
        # K/V output is garbage and discarded; clipping keeps them
        # inside the model's rope table)
        pos = np.minimum(np.arange(start, start + padded, dtype=np.int32),
                         start + n_valid - 1)
        cache.begin_prefill(slot, n_valid)
        t_chunk = time.perf_counter()
        logits = self.model.forward(Tensor(chunk[None]), cache=cache,
                                    position_ids=pos)
        self.prefill_chunks += 1
        self.padded_tokens_total += padded
        self.useful_tokens_total += n_valid
        tele["chunk_util"].observe(n_valid / max(padded, 1))
        done = start + n_valid >= row.prompt.shape[0]
        self.events.append(("chunk", slot, n_valid, done))
        chunk_dt = time.perf_counter() - t_chunk
        if _co.is_enabled():
            ev = _co.observe("serving.prefill_chunk",
                             self._chunk_signature(padded),
                             seconds=chunk_dt)
            if ev is not None and ev["miss"]:
                _rt.add_span(row.req.trace, "compile", t0=t_chunk,
                             dur=chunk_dt, family="serving.prefill_chunk",
                             cause=ev["cause"])
        _rt.add_span(row.req.trace, "prefill_chunk", t0=t_chunk,
                     dur=chunk_dt, slot=slot,
                     tokens=n_valid, start=start, last=done)
        if not done:
            return
        prefill_q.popleft()
        cache.commit_prefix(slot)
        kw = row.req.kwargs
        nxt = int(np.asarray(_sample_logits(
            logits._data[:, n_valid - 1].astype(jnp.float32),
            kw.get("do_sample", False), kw.get("top_k", 0),
            kw.get("top_p", 1.0), kw.get("temperature", 1.0),
            key=self._row_key(row, len(row.generated))))[0])
        row.state = "decode"
        self._push_token(cache, free, active, slot, nxt)

    def _push_token(self, cache, free, active, slot, token):
        row = active[slot]
        row.generated.append(token)
        tele = _telemetry()
        tele["tokens"].inc(engine=self._ENGINE)
        _rt.note_token(row.req.trace)
        if _ledger.is_enabled() and row.req.trace is not None:
            # determinism ledger: advance this (trace, attempt) delivered
            # token-stream chain digest — the attestation input
            _ledger.note_stream_token(
                row.req.trace.trace_id,
                row.req.trace.tags.get("attempt", 0), token)
        if row.req.t_first is None:
            row.req.t_first = time.perf_counter()
            tele["ttft"].observe(row.req.t_first - row.req.t_submit,
                                 engine=self._ENGINE)
        eos = row.req.kwargs.get("eos_token_id")
        if (eos is not None and token == eos) or \
                len(row.generated) >= row.req.max_new_tokens:
            row.done = True
            active[slot] = None
            cache.free(slot)
            free.append(slot)
            self._maybe_finish(row.req)

    def _maybe_finish(self, req):
        rows = req._rows
        if not all(r.done for r in rows):
            return
        if req.cancelled:              # caller already raised TimeoutError
            req.done.set()
            return
        eos = req.kwargs.get("eos_token_id")
        pad = self.pad_token_id if eos is None else eos
        width = req.ids.shape[1] + max(len(r.generated) for r in rows)
        out = np.full((len(rows), width), pad, req.ids.dtype)
        for i, r in enumerate(rows):
            seq = np.concatenate([r.prompt, np.asarray(r.generated,
                                                       req.ids.dtype)])
            out[i, :seq.shape[0]] = seq
        req.result = out
        req.done.set()

    def _serve(self):
        from ..autograd.tape import no_grad
        with no_grad():
            self._serve_impl()

    def _new_cache(self):
        from ..models.generation import SlotPagedKVCache
        cache = SlotPagedKVCache(self.max_batch, page_size=self.page_size,
                                 max_len=self.max_len,
                                 num_pages=self.num_pages,
                                 enable_prefix_cache=self.enable_prefix_cache,
                                 kv_dtype=self.kv_dtype,
                                 host_pool=self._host_pool,
                                 allow_page_overcommit=(
                                     self.sep_prefill_enabled))
        # cache-scoped counter baselines reset with the cache (a rebuilt
        # cache restarts them at 0; pool-scoped baselines persist with
        # the engine-owned host pool)
        self._kv_tier_seen.pop("dev_evict", None)
        self._cache = cache           # flight-recorder / test introspection
        return cache

    def _mirror_kv_tier(self, tele, cache):
        """Per-tick telemetry mirror for the tiered-KV counters: inc the
        registry by the delta since the last mirror (counters must never
        regress even when the cache — and its counters — rebuild after a
        serve-loop error)."""
        seen = self._kv_tier_seen
        hp = self._host_pool

        def bump(key, cur, metric, **labels):
            prev = seen.get(key, 0)
            if cur > prev:
                metric.inc(cur - prev, **labels)
            seen[key] = cur

        bump("dev_evict", cache.prefix_evictions_device,
             tele["prefix_evictions"], tier="device")
        bump("host_evict", hp.evictions,
             tele["prefix_evictions"], tier="host")
        bump("demote", hp.demotions, tele["host_demotions"])
        bump("promote", hp.promotions, tele["host_promotions"])
        tele["host_pool_bytes"].set(hp.used_bytes, kind="used")
        tele["host_pool_bytes"].set(hp.max_bytes, kind="capacity")

    def _sep_engaged(self, cache, prompt_tokens):
        """Route a prompt to sep-parallel prefill? Explicit threshold
        wins; the 0 default engages when the prompt would consume more
        than half the device page pool (long-context territory — the
        pool may not even hold it)."""
        if not self.sep_prefill_enabled:
            return False
        thr = self.sep_threshold
        if thr <= 0:
            cap = (cache.num_pages - 1) * self.page_size
            thr = max(cap // 2, self.sep_stripe)
        return int(prompt_tokens) >= thr

    @staticmethod
    def _row_key(row, token_idx):
        """Per-token PRNG key for seeded sampling: a request carrying
        ``seed=`` draws token ``i`` of row ``r`` with
        ``fold_in(fold_in(key(seed), r), i)`` — a pure function of the
        request, so sampled decode replays identically across runs,
        schedulers, and speculative verification. Returns None (global
        stateful generator) without a seed."""
        seed = row.req.kwargs.get("seed")
        if seed is None:
            return None
        import jax
        if row._key_base is None:
            row._key_base = jax.random.fold_in(
                jax.random.key(int(seed)), row.row_idx)
        return jax.random.fold_in(row._key_base, int(token_idx))

    def _serve_impl(self):
        if self.enable_ragged:
            return self._serve_ragged()
        return self._serve_legacy()

    def _serve_ragged(self):
        """Token-budget continuous batching: ONE ragged forward per tick
        covering every live decode slot's token plus as many prefill
        tokens as fit in ``token_budget`` (per-span cap
        ``chunk_tokens``), padded to the fixed bucket set — the single
        ragged program family replaces the legacy chunk+decode pair."""
        from ..models.generation import _sample_logits

        was_training = self.model.training
        self.model.eval()
        try:
            cache = self._new_cache()
            free: deque = deque(range(self.max_batch))
            active: list = [None] * self.max_batch
            pending: deque = deque()
            prefill_q: deque = deque()    # slots mid-prefill, FIFO
            sep_q: deque = deque()        # slots mid sep-ring prefill

            def enqueue(item):
                """False = stop token; otherwise split into rows."""
                if item is self._STOP or item is None:
                    return False
                if isinstance(item, _Control):
                    item.run(self)       # tick boundary: scheduler-safe
                    return True
                item._rows = [_Row(item, row, i)
                              for i, row in enumerate(item.ids)]
                pending.extend(item._rows)
                return True

            def drop_slot(i):
                active[i] = None
                cache.free(i)
                if i in prefill_q:
                    prefill_q.remove(i)
                if i in sep_q:
                    sep_q.remove(i)
                free.append(i)

            while True:
                if self._aborted:
                    # replica death (fleet abort()): no drain — every
                    # queued and in-flight request fails NOW so callers
                    # can requeue to a surviving replica
                    err = RuntimeError("ServingEngine aborted")
                    for row in list(pending) + [r for r in active
                                                if r is not None]:
                        _rt.add_event(row.req.trace, "engine_aborted",
                                      engine=self._ENGINE)
                        row.req.error = err
                        row.req.done.set()
                    break
                draining = not self._running
                if draining and all(r is None for r in active):
                    break
                # block only when idle; otherwise drain without waiting
                if not draining and not pending and \
                        all(r is None for r in active):
                    if not enqueue(self._q.get()):
                        self._running = False
                        continue     # drain in-flight rows before exit
                if not draining:
                    try:
                        while True:
                            if not enqueue(self._q.get_nowait()):
                                self._running = False
                                break
                    except queue.Empty:
                        pass
                if not self._running and pending:
                    # stop(): un-admitted rows fail fast — including any
                    # already-admitted SIBLING rows of the same request
                    # (the base engine's contract, see _serve_legacy)
                    dropped = {row.req for row in pending}
                    for row in pending:
                        row.req.error = RuntimeError("ServingEngine stopped")
                        row.req.done.set()
                    pending.clear()
                    for i, r in enumerate(active):
                        if r is not None and r.req in dropped:
                            drop_slot(i)
                # cancellation sweep (step boundary): free slots/pages a
                # timed-out client still holds
                for i, r in enumerate(active):
                    if r is not None and r.req.cancelled:
                        r.done = True
                        self.cancelled_rows += 1
                        _rt.add_event(r.req.trace, "cancelled", slot=i,
                                      engine=self._ENGINE)
                        drop_slot(i)
                tele = _telemetry()
                try:
                    if self._running:
                        self._admit(cache, free, active, pending, prefill_q,
                                    sep_q=sep_q)
                    # ---- pack the tick: decode tokens first (each
                    # optionally extended into a speculative verify span
                    # of 1 current + up to spec_k drafted tokens), then
                    # as many prefill tokens as the budget admits ------
                    # (sep rows run their own stripe-shaped programs in
                    # _sep_tick and never join the ragged pack)
                    decode_slots = [i for i, r in enumerate(active)
                                    if r is not None and r.state == "decode"
                                    and not r.sep]
                    spans = []        # (slot, q_start, start, n, kind)
                    tick_drafts = {}  # slot -> drafted tokens this tick
                    off = 0
                    drafter = self._drafter
                    draft_f0 = getattr(drafter, "forwards", None)
                    # batched drafting prepass: one padded draft forward
                    # per STEP for every decode slot at once. Each slot
                    # is over-asked up to an optimistic cap (>= any room
                    # the sequential packing below can grant, since
                    # every other slot takes at least 1 token) and the
                    # greedy proposal — prefix-stable in k — is trimmed
                    # to the exact sequential room, so packing is
                    # bit-identical to the per-slot propose() path.
                    batch_drafts = None
                    if (drafter is not None and self.draft_batch
                            and decode_slots
                            and hasattr(drafter, "propose_batch")):
                        hists, caps = [], []
                        for i in decode_slots:
                            row = active[i]
                            start = int(cache.lens[i])
                            caps.append(max(0, min(
                                self.token_budget - len(decode_slots),
                                self.spec_k,
                                self.max_len - start - 1,
                                row.req.max_new_tokens
                                - len(row.generated) - 1)))
                            hists.append(np.concatenate(
                                [row.prompt,
                                 np.asarray(row.generated,
                                            row.prompt.dtype)]))
                        batch_drafts = (
                            drafter.propose_batch(hists, caps)
                            if max(caps) > 0 else [[] for _ in caps])
                    for di, i in enumerate(decode_slots):
                        row = active[i]
                        start = int(cache.lens[i])
                        n = 1
                        if drafter is not None:
                            # drafts ride only on leftover budget: every
                            # remaining decode slot keeps its 1 token
                            # (decode liveness stays unconditional), and
                            # a draft never runs past max_len or past
                            # the row's remaining new-token budget
                            room = min(
                                self.token_budget - off - 1
                                - (len(decode_slots) - di - 1),
                                self.spec_k,
                                self.max_len - start - 1,
                                row.req.max_new_tokens
                                - len(row.generated) - 1)
                            if batch_drafts is not None:
                                draft = (batch_drafts[di][:room]
                                         if room > 0 else [])
                            else:
                                draft = (drafter.propose(
                                    np.concatenate(
                                        [row.prompt,
                                         np.asarray(row.generated,
                                                    row.prompt.dtype)]),
                                    room) if room > 0 else [])
                            if draft:
                                tick_drafts[i] = [int(t) for t in draft]
                                n = 1 + len(tick_drafts[i])
                        spans.append((i, off, start, n, "decode"))
                        off += n
                    if drafter is not None and decode_slots:
                        self.spec_draft_ticks += 1
                        if draft_f0 is not None:
                            self.spec_draft_forwards += (
                                drafter.forwards - draft_f0)
                    remaining = self.token_budget - off
                    for slot in list(prefill_q):
                        if remaining <= 0:
                            break
                        row = active[slot]
                        start = int(cache.lens[slot])
                        n = min(self.chunk_tokens,
                                row.prompt.shape[0] - start, remaining)
                        if n <= 0:
                            break
                        spans.append((slot, off, start, n, "prefill"))
                        off += n
                        remaining -= n
                    tele["active"].set(sum(r is not None for r in active))
                    tele["free_slots"].set(len(free))
                    tele["free_pages"].set(cache.free_page_count)
                    tele["pool_occupancy"].set(
                        cache.used_page_count / max(cache.num_pages - 1, 1))
                    page_nb = cache.page_nbytes     # dtype-aware bytes
                    tele["pool_bytes"].set(cache.used_page_count * page_nb,
                                           kind="used")
                    tele["pool_bytes"].set((cache.num_pages - 1) * page_nb,
                                           kind="capacity")
                    self._mirror_kv_tier(tele, cache)
                    self._sep_tick(cache, free, active, sep_q)
                    if not spans:
                        continue
                    total = off
                    padded = _token_bucket(total, self.token_budget)
                    flat = np.full(padded, self.pad_token_id, np.int64)
                    pos = np.zeros(padded, np.int32)
                    for slot, qs, start, n, kind in spans:
                        row = active[slot]
                        if kind == "decode":
                            flat[qs] = (row.generated[-1] if row.generated
                                        else row.prompt[-1])
                            draft = tick_drafts.get(slot)
                            if draft:
                                flat[qs + 1:qs + n] = draft
                            pos[qs:qs + n] = np.arange(start, start + n)
                        else:
                            flat[qs:qs + n] = row.prompt[start:start + n]
                            pos[qs:qs + n] = np.arange(start, start + n)
                    t_step = time.perf_counter()
                    cache.begin_ragged(
                        [(slot, qs, n) for slot, qs, _, n, _ in spans])
                    logits = self.model.forward(Tensor(flat[None]),
                                                cache=cache,
                                                position_ids=pos)
                    lg = logits._data[0].astype(jnp.float32)  # [padded, V]
                    greedy = np.asarray(jnp.argmax(lg, axis=-1))
                    step_dt = time.perf_counter() - t_step
                    self.ragged_steps += 1
                    self.ragged_buckets_used.add(padded)
                    # compile observatory: one program-boundary record
                    # per packed tick; on a miss every participating
                    # request gets a "compile" span so its TTFT
                    # decomposes into queue/compile/prefill
                    compile_ev = None
                    if _co.is_enabled():
                        ev = _co.observe("serving.ragged",
                                         self._ragged_signature(padded),
                                         seconds=step_dt)
                        if ev is not None and ev["miss"]:
                            compile_ev = ev
                    self.padded_tokens_total += padded
                    self.useful_tokens_total += total
                    tele["budget_util"].observe(total / max(padded, 1))
                    n_decode = sum(n for _, _, _, n, kind in spans
                                   if kind == "decode")
                    n_prefill = total - n_decode
                    self.ragged_decode_tokens += n_decode
                    self.ragged_prefill_tokens += n_prefill
                    if n_decode:
                        tele["ragged_tokens"].inc(n_decode, kind="decode")
                    if n_prefill:
                        tele["ragged_tokens"].inc(n_prefill, kind="prefill")
                    # request-trace: the packed tick lands as one span on
                    # every participating request (its kind/tokens in the
                    # tags — prefill chunks and decode ticks both)
                    for slot, qs, start, n, kind in spans:
                        row = active[slot]
                        if row is None:
                            continue
                        if compile_ev is not None:
                            _rt.add_span(row.req.trace, "compile",
                                         t0=t_step, dur=step_dt,
                                         family="serving.ragged",
                                         cause=compile_ev["cause"],
                                         tick=self.ragged_steps)
                        name = ("prefill_chunk" if kind == "prefill"
                                else "decode")
                        _rt.add_span(
                            row.req.trace, name, t0=t_step, dur=step_dt,
                            slot=slot, tokens=n, start=start,
                            tick=self.ragged_steps,
                            last=(kind == "prefill" and
                                  start + n >= row.prompt.shape[0]))

                    def sample(idx, row, offset=0):
                        """Target token for flat position ``idx``;
                        ``offset`` is the token's index past the row's
                        already-generated count (speculative verify
                        positions), keeping seeded-sampling keys a pure
                        function of the final token index."""
                        kw = row.req.kwargs
                        if kw.get("do_sample", False):
                            key = self._row_key(
                                row, len(row.generated) + offset)
                            return int(np.asarray(_sample_logits(
                                lg[idx:idx + 1], True, kw.get("top_k", 0),
                                kw.get("top_p", 1.0),
                                kw.get("temperature", 1.0), key=key))[0])
                        return int(greedy[idx])

                    # prefill spans: advance, register finished prompts,
                    # hand completed rows to the decode path
                    for slot, qs, start, n, kind in spans:
                        if kind != "prefill":
                            continue
                        row = active[slot]
                        self.prefill_chunks += 1
                        done = start + n >= row.prompt.shape[0]
                        self.events.append(("chunk", slot, n, done))
                        if not done:
                            continue
                        prefill_q.remove(slot)
                        cache.commit_prefix(slot)
                        row.state = "decode"
                        self._push_token(cache, free, active, slot,
                                         sample(qs + n - 1, row))
                    # decode spans: verify drafted tokens against the
                    # target model's own choices — the target token at
                    # span offset j is valid iff every draft before it
                    # matched, so the longest matching prefix (plus the
                    # free token after it) is emitted and the rejected
                    # tail's K/V rolls back out of the context
                    if decode_slots:
                        self.decode_steps += 1
                        self.events.append(("decode", len(decode_slots)))
                        tele["decode_step"].observe(step_dt)
                        emitted = 0
                        for slot, qs, start, n, kind in spans:
                            if kind != "decode":
                                continue
                            row = active[slot]
                            if row is None or row.done:
                                continue
                            draft = tick_drafts.get(slot, ())
                            kd = len(draft)
                            targets = [sample(qs + j, row, offset=j)
                                       for j in range(kd + 1)]
                            m = 0
                            while m < kd and draft[m] == targets[m]:
                                m += 1
                            if kd:
                                self.spec_rounds += 1
                                self.spec_drafted_tokens += kd
                                self.spec_accepted_tokens += m
                                tele["spec_tokens"].inc(kd, kind="drafted")
                                if m:
                                    tele["spec_tokens"].inc(
                                        m, kind="accepted")
                                tele["spec_accept"].observe(m / kd)
                                if kd > m:
                                    cache.rollback(slot, kd - m)
                            for t in targets[:m + 1]:
                                self._push_token(cache, free, active,
                                                 slot, t)
                                emitted += 1
                                if active[slot] is None \
                                        or active[slot].done:
                                    break
                        for _ in range(emitted):
                            tele["token"].observe(
                                step_dt / max(emitted, 1))
                except Exception as e:      # fail everything in flight
                    reqs = {r.req for r in pending}
                    reqs |= {r.req for r in active if r is not None}
                    for req in reqs:
                        req.error = e
                        req.done.set()
                    pending.clear()
                    prefill_q.clear()
                    sep_q.clear()
                    active = [None] * self.max_batch
                    free = deque(range(self.max_batch))
                    cache = self._new_cache()
        finally:
            if was_training:
                self.model.train()

    def _sep_tick(self, cache, free, active, sep_q):
        """One sep-parallel step per tick: a single ring-prefill stripe
        chunk for the longest-waiting sep slot, then one decode token
        for every sep row already decoding. Sep programs are stripe- or
        tail-shaped — never part of the ragged pack — so interleaving
        at tick granularity keeps paged traffic flowing underneath a
        100k-token prefill."""
        if sep_q:
            slot = sep_q[0]
            if self._sep_prefill_chunk(cache, free, active, slot,
                                       active[slot]):
                sep_q.popleft()
        for i, r in enumerate(active):
            if r is not None and r.sep and r.state == "decode":
                self._sep_decode_step(cache, free, active, i)

    def _sep_prefill_chunk(self, cache, free, active, slot, row):
        """Advance one stripe-sized ring-prefill chunk; on the final
        chunk sample the first token and flip the row to sep decode.
        Returns True when the prompt is fully consumed."""
        from ..models.generation import _sample_logits
        tele = _telemetry()
        stripe = self.sep_stripe
        start = int(cache.lens[slot])
        n_valid = min(stripe, row.prompt.shape[0] - start)
        chunk = np.full(stripe, self.pad_token_id, row.prompt.dtype)
        chunk[:n_valid] = row.prompt[start:start + n_valid]
        pos = np.minimum(np.arange(start, start + stripe, dtype=np.int32),
                         start + n_valid - 1)
        n_stripes = cache.sep_view(slot)["stripes"]
        cache.begin_sep_prefill(slot, n_valid)
        t_chunk = time.perf_counter()
        logits = self.model.forward(Tensor(chunk[None]), cache=cache,
                                    position_ids=pos)
        chunk_dt = time.perf_counter() - t_chunk
        self.prefill_chunks += 1
        self.padded_tokens_total += stripe
        self.useful_tokens_total += n_valid
        tele["chunk_util"].observe(n_valid / max(stripe, 1))
        done = start + n_valid >= row.prompt.shape[0]
        self.events.append(("sep_chunk", slot, n_valid, done))
        if _co.is_enabled():
            ev = _co.observe("serving.sep_prefill",
                             self._sep_prefill_signature(n_stripes),
                             seconds=chunk_dt)
            if ev is not None and ev["miss"]:
                _rt.add_span(row.req.trace, "compile", t0=t_chunk,
                             dur=chunk_dt, family="serving.sep_prefill",
                             cause=ev["cause"])
        _rt.add_span(row.req.trace, "sep_prefill_chunk", t0=t_chunk,
                     dur=chunk_dt, slot=slot, tokens=n_valid,
                     start=start, stripes=n_stripes, last=done)
        if not done:
            return False
        kw = row.req.kwargs
        nxt = int(np.asarray(_sample_logits(
            logits._data[:, n_valid - 1].astype(jnp.float32),
            kw.get("do_sample", False), kw.get("top_k", 0),
            kw.get("top_p", 1.0), kw.get("temperature", 1.0),
            key=self._row_key(row, len(row.generated))))[0])
        row.state = "decode"
        self._push_token(cache, free, active, slot, nxt)
        return True

    def _sep_decode_step(self, cache, free, active, slot):
        """One decode token for a sep row: the ring merge reads every
        stored stripe plus the pow2-padded device tail window."""
        from ..models.generation import _sample_logits
        tele = _telemetry()
        row = active[slot]
        view = cache.sep_view(slot)
        cur = np.asarray([[row.generated[-1] if row.generated
                           else row.prompt[-1]]], np.int64)
        pos = np.asarray([[int(cache.lens[slot])]], np.int32)
        cache.begin_sep_decode(slot)
        t_step = time.perf_counter()
        logits = self.model.forward(Tensor(cur), cache=cache,
                                    position_ids=pos)
        step_dt = time.perf_counter() - t_step
        self.decode_steps += 1
        tele["decode_step"].observe(step_dt)
        tele["token"].observe(step_dt)
        if _co.is_enabled():
            ev = _co.observe("serving.sep_decode",
                             self._sep_decode_signature(
                                 view["stripes"], view["tail_pages"]),
                             seconds=step_dt)
            if ev is not None and ev["miss"]:
                _rt.add_span(row.req.trace, "compile", t0=t_step,
                             dur=step_dt, family="serving.sep_decode",
                             cause=ev["cause"])
        _rt.add_span(row.req.trace, "decode", t0=t_step, dur=step_dt,
                     slot=slot, tokens=1, sep=True,
                     tick=self.decode_steps)
        kw = row.req.kwargs
        tok = int(np.asarray(_sample_logits(
            logits._data[:, -1].astype(jnp.float32),
            kw.get("do_sample", False), kw.get("top_k", 0),
            kw.get("top_p", 1.0), kw.get("temperature", 1.0),
            key=self._row_key(row, len(row.generated))))[0])
        self._push_token(cache, free, active, slot, tok)

    def _serve_legacy(self):
        from ..models.generation import _sample_logits

        was_training = self.model.training
        self.model.eval()
        try:
            cache = self._new_cache()
            free: deque = deque(range(self.max_batch))
            active: list = [None] * self.max_batch
            pending: deque = deque()
            prefill_q: deque = deque()    # slots mid-prefill, FIFO

            def enqueue(item):
                """False = stop token; otherwise split into rows."""
                if item is self._STOP or item is None:
                    return False
                if isinstance(item, _Control):
                    item.run(self)       # tick boundary: scheduler-safe
                    return True
                item._rows = [_Row(item, row, i)
                              for i, row in enumerate(item.ids)]
                pending.extend(item._rows)
                return True

            def drop_slot(i):
                active[i] = None
                cache.free(i)
                if i in prefill_q:
                    prefill_q.remove(i)
                free.append(i)

            while True:
                if self._aborted:
                    # replica death (fleet abort()): no drain — every
                    # queued and in-flight request fails NOW so callers
                    # can requeue to a surviving replica
                    err = RuntimeError("ServingEngine aborted")
                    for row in list(pending) + [r for r in active
                                                if r is not None]:
                        _rt.add_event(row.req.trace, "engine_aborted",
                                      engine=self._ENGINE)
                        row.req.error = err
                        row.req.done.set()
                    break
                draining = not self._running
                if draining and all(r is None for r in active):
                    break
                # block only when idle; otherwise drain without waiting
                if not draining and not pending and \
                        all(r is None for r in active):
                    if not enqueue(self._q.get()):
                        self._running = False
                        continue     # drain in-flight rows before exit
                if not draining:
                    try:
                        while True:
                            if not enqueue(self._q.get_nowait()):
                                self._running = False
                                break
                    except queue.Empty:
                        pass
                if not self._running and pending:
                    # stop(): un-admitted rows fail fast — including any
                    # already-admitted SIBLING rows of the same request
                    # (finishing them would be wasted work: the caller
                    # already got the error). Fully-admitted requests
                    # decode to completion (the base engine's contract).
                    dropped = {row.req for row in pending}
                    for row in pending:
                        row.req.error = RuntimeError("ServingEngine stopped")
                        row.req.done.set()
                    pending.clear()
                    for i, r in enumerate(active):
                        if r is not None and r.req in dropped:
                            drop_slot(i)
                # cancellation sweep (step boundary): free slots/pages a
                # timed-out client still holds
                for i, r in enumerate(active):
                    if r is not None and r.req.cancelled:
                        r.done = True
                        self.cancelled_rows += 1
                        _rt.add_event(r.req.trace, "cancelled", slot=i,
                                      engine=self._ENGINE)
                        drop_slot(i)
                tele = _telemetry()
                try:
                    if self._running:
                        self._admit(cache, free, active, pending, prefill_q)
                    # ONE prefill chunk per tick: a long prompt advances
                    # chunk-by-chunk while decodes keep flowing below
                    if prefill_q:
                        self._prefill_chunk(cache, free, active, prefill_q)
                    mask = np.asarray([r is not None and r.state == "decode"
                                       for r in active])
                    n_active = int(mask.sum())
                    tele["active"].set(sum(r is not None for r in active))
                    tele["free_slots"].set(len(free))
                    tele["free_pages"].set(cache.free_page_count)
                    tele["pool_occupancy"].set(
                        cache.used_page_count / max(cache.num_pages - 1, 1))
                    page_nb = cache.page_nbytes     # dtype-aware bytes
                    tele["pool_bytes"].set(cache.used_page_count * page_nb,
                                           kind="used")
                    tele["pool_bytes"].set((cache.num_pages - 1) * page_nb,
                                           kind="capacity")
                    self._mirror_kv_tier(tele, cache)
                    if not mask.any():
                        continue
                    t_step = time.perf_counter()
                    # ONE fixed-shape decode step for every decoding slot
                    cache.begin_decode(mask)
                    cur = np.full((self.max_batch, 1), self.pad_token_id,
                                  np.int64)
                    for i, r in enumerate(active):
                        if r is not None and r.state == "decode":
                            cur[i, 0] = (r.generated[-1] if r.generated
                                         else r.prompt[-1])
                    pos = cache.lens.astype(np.int32)[:, None]
                    logits = self.model.forward(Tensor(cur), cache=cache,
                                                position_ids=pos)
                    lg = logits._data[:, -1].astype(jnp.float32)
                    self.decode_steps += 1
                    # the fixed-shape decode step burns a token position
                    # for every slot, live or not — the padding waste the
                    # ragged scheduler exists to remove
                    self.padded_tokens_total += self.max_batch
                    self.useful_tokens_total += n_active
                    self.events.append(("decode", n_active))
                    step_dt = time.perf_counter() - t_step
                    tele["decode_step"].observe(step_dt)
                    # every active slot earned one token this step
                    for _ in range(n_active):
                        tele["token"].observe(step_dt / max(n_active, 1))
                    compile_ev = None
                    if _co.is_enabled():
                        ev = _co.observe("serving.decode",
                                         self._decode_signature(),
                                         seconds=step_dt)
                        if ev is not None and ev["miss"]:
                            compile_ev = ev
                    greedy = np.asarray(jnp.argmax(lg, axis=-1))
                    for i, r in enumerate(list(active)):
                        if r is None or r.state != "decode":
                            continue
                        if compile_ev is not None:
                            _rt.add_span(r.req.trace, "compile", t0=t_step,
                                         dur=step_dt,
                                         family="serving.decode",
                                         cause=compile_ev["cause"])
                        _rt.add_span(r.req.trace, "decode", t0=t_step,
                                     dur=step_dt, slot=i, tokens=1,
                                     tick=self.decode_steps)
                        kw = r.req.kwargs
                        if kw.get("do_sample", False):
                            tok = int(np.asarray(_sample_logits(
                                lg[i:i + 1], True, kw.get("top_k", 0),
                                kw.get("top_p", 1.0),
                                kw.get("temperature", 1.0),
                                key=self._row_key(r, len(r.generated))))[0])
                        else:
                            tok = int(greedy[i])
                        self._push_token(cache, free, active, i, tok)
                except Exception as e:      # fail everything in flight
                    reqs = {r.req for r in pending}
                    reqs |= {r.req for r in active if r is not None}
                    for req in reqs:
                        req.error = e
                        req.done.set()
                    pending.clear()
                    prefill_q.clear()
                    active = [None] * self.max_batch
                    free = deque(range(self.max_batch))
                    cache = self._new_cache()
        finally:
            if was_training:
                self.model.train()
