"""Speculative-decode drafters for the continuous serving engine
(reference direction: PaddleNLP's speculative decoding tier around the
``fused_multi_transformer`` serving block; decode-bandwidth argument per
"Ragged Paged Attention", arxiv 2604.15464 — one target-model forward
per generated token is the decode-latency floor this module breaks).

A drafter proposes up to ``k`` next tokens for a sequence from its token
history alone; the engine verifies the proposal in ONE ragged forward (a
``q_len = k+1`` span over the paged cache — exactly what the ragged
kernel already computes for a chunked-prefill span) and keeps the
longest matching prefix. Greedy acceptance makes the output
**bit-identical** to plain greedy decode regardless of drafter quality:
a bad drafter only costs speed, never correctness.

Two tiers:

* :class:`NGramDrafter` (default, ``PADDLE_SPEC_DRAFTER=ngram``) —
  model-free prompt-lookup: the most recent earlier occurrence of the
  history's trailing n-gram supplies the continuation. Zero extra
  weights, zero forwards; shines on extraction/summarization traffic
  where outputs quote the prompt.
* :class:`DraftModelDrafter` (``PADDLE_SPEC_DRAFTER=model``) — a small
  causal LM sharing the tokenizer (e.g. a shallower config from
  ``models/``) decodes ``k`` tokens greedily as the proposal. Passing
  the target model itself is "self-speculation": acceptance is ~1.0 and
  the verify path is exercised end to end (the test/bench harness tier).
"""
from __future__ import annotations

import os
import time

import numpy as np

__all__ = ["NGramDrafter", "DraftModelDrafter", "make_drafter",
           "DEFAULT_SPEC_K", "DEFAULT_SPEC_NGRAM"]

#: default drafted tokens per sequence per tick (PADDLE_SPEC_K)
DEFAULT_SPEC_K = 4

#: default longest trailing n-gram the lookup drafter matches
#: (PADDLE_SPEC_NGRAM); it backs off to shorter n-grams before giving up
DEFAULT_SPEC_NGRAM = 3


class NGramDrafter:
    """Model-free prompt-lookup drafter: propose the continuation of the
    most recent earlier occurrence of the history's trailing n-gram,
    backing off from ``max_ngram`` down to 1. Returns an empty proposal
    when nothing matches — the engine then runs a plain 1-token decode
    for that sequence."""

    def __init__(self, max_ngram=None):
        if max_ngram is None:
            max_ngram = int(os.environ.get("PADDLE_SPEC_NGRAM",
                                           str(DEFAULT_SPEC_NGRAM)))
        self.max_ngram = max(int(max_ngram), 1)

    def propose(self, history, k):
        h = np.asarray(history).reshape(-1)
        n_hist = h.shape[0]
        k = int(k)
        if k <= 0 or n_hist < 2:
            return []
        for n in range(min(self.max_ngram, n_hist - 1), 0, -1):
            pat = h[n_hist - n:]
            # candidate match ends (exclusive) in [n, n_hist-1]: the
            # trailing occurrence itself is excluded, most recent first
            windows = np.lib.stride_tricks.sliding_window_view(
                h[:n_hist - 1], n)
            hits = np.nonzero((windows == pat).all(axis=1))[0]
            if hits.size == 0:
                continue
            start = int(hits[-1]) + n          # continuation start
            out = h[start:start + k]
            if out.size:
                return [int(t) for t in out]
        return []


def _pow2_bucket(n, cap=None):
    """Smallest power of two >= n (>= 1), optionally clamped to ``cap``
    — batched draft forwards quantize their shapes to these buckets so
    the compiled-program family stays bounded."""
    b = 1 << max(int(n) - 1, 0).bit_length()
    if cap is not None:
        b = min(b, int(cap))
    return max(b, 1)


class DraftModelDrafter:
    """Tier-2 drafter: a small causal LM (same tokenizer as the target)
    greedily decodes ``k`` tokens as the proposal. The draft forward
    runs on the trailing ``window`` tokens of the history — a drafter
    needs recency, not the full context, and the window bounds its
    cost. Proposals are suggestions only: the target model's verify
    forward decides every emitted token.

    ``propose_batch`` drafts for EVERY live sequence in one padded
    forward per draft step instead of one forward per sequence per step
    — rows are right-padded to a power-of-two width (causal attention
    makes the pad positions invisible to each row's own logits, so the
    proposals are bit-identical to per-sequence :meth:`propose`).
    ``self.forwards`` counts draft-model forwards for both paths (the
    engine's ``spec_draft_forwards_per_tick`` metric)."""

    def __init__(self, model, window=64):
        if model is None:
            raise ValueError("DraftModelDrafter needs a draft model "
                             "(PADDLE_SPEC_DRAFTER=model requires the "
                             "engine's draft_model= kwarg)")
        self.model = model
        self.window = max(int(window), 1)
        self.forwards = 0

    def propose(self, history, k):
        import jax.numpy as jnp
        from ..framework.core import Tensor
        from ..autograd.tape import no_grad

        h = np.asarray(history).reshape(-1)
        k = int(k)
        if k <= 0 or h.size == 0:
            return []
        ids = h[-self.window:].astype(np.int64)
        out = []
        was_training = self.model.training
        self.model.eval()
        try:
            with no_grad():
                for _ in range(k):
                    logits = self.model.forward(Tensor(ids[None]))
                    self.forwards += 1
                    nxt = int(np.asarray(
                        jnp.argmax(logits._data[0, -1])))
                    out.append(nxt)
                    ids = np.concatenate([ids, [nxt]])[-self.window:]
        finally:
            if was_training:
                self.model.train()
        return out

    def propose_batch(self, histories, ks):
        """Draft up to ``ks[i]`` tokens for every ``histories[i]`` with
        ONE padded forward per draft step (not one per sequence): rows
        still drafting at a step are right-padded to a power-of-two
        (rows, width) bucket and each row's next token reads from its
        own last valid position. Greedy proposals are bit-identical to
        calling :meth:`propose` per sequence, and a row's proposal list
        is prefix-stable in ``k`` — callers may over-ask and trim."""
        import jax.numpy as jnp
        from ..framework.core import Tensor
        from ..autograd.tape import no_grad
        from ..profiler import compile_observatory as _co

        ks = [int(k) for k in ks]
        rows = [np.asarray(h).reshape(-1)[-self.window:].astype(np.int64)
                for h in histories]
        outs = [[] for _ in rows]
        todo = [i for i, (r, k) in enumerate(zip(rows, ks))
                if k > 0 and r.size > 0]
        if not todo:
            return outs
        kmax = max(ks[i] for i in todo)
        was_training = self.model.training
        self.model.eval()
        try:
            with no_grad():
                for step in range(kmax):
                    act = [i for i in todo if ks[i] > step]
                    if not act:
                        break
                    lens = [rows[i].shape[0] for i in act]
                    width = _pow2_bucket(max(lens), cap=self.window)
                    batch = np.zeros((_pow2_bucket(len(act)), width),
                                     np.int64)
                    for r, i in enumerate(act):
                        batch[r, :lens[r]] = rows[i]
                    t_fwd = (time.perf_counter()
                             if _co.is_enabled() else None)
                    logits = self.model.forward(Tensor(batch))
                    self.forwards += 1
                    if t_fwd is not None:
                        _co.observe(
                            "spec.draft_batch",
                            {"tokens": _co.tensor_arg(batch.shape,
                                                      "int64")},
                            seconds=time.perf_counter() - t_fwd)
                    last = np.asarray(jnp.argmax(
                        logits._data[np.arange(len(act)),
                                     np.asarray(lens) - 1], axis=-1))
                    for r, i in enumerate(act):
                        nxt = int(last[r])
                        outs[i].append(nxt)
                        rows[i] = np.concatenate(
                            [rows[i], [nxt]])[-self.window:]
        finally:
            if was_training:
                self.model.train()
        return outs


def make_drafter(kind=None, draft_model=None, max_ngram=None, window=64):
    """Drafter factory for the serving engine. ``kind`` defaults to
    ``PADDLE_SPEC_DRAFTER`` (``ngram`` | ``model``); ``model`` requires
    ``draft_model``. A drafter object passed straight through the
    engine's ``drafter=`` kwarg bypasses this factory entirely."""
    if kind is None:
        kind = os.environ.get(
            "PADDLE_SPEC_DRAFTER",
            "model" if draft_model is not None else "ngram")
    kind = str(kind).lower()
    if kind == "ngram":
        return NGramDrafter(max_ngram=max_ngram)
    if kind == "model":
        return DraftModelDrafter(draft_model, window=window)
    raise ValueError(f"unknown drafter kind {kind!r} "
                     f"(expected 'ngram' or 'model')")
