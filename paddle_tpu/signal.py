"""paddle.signal (reference: ``python/paddle/signal.py`` — stft/istft over
frame + fft ops; SURVEY.md §2.2). TPU-native: framing is a gather (XLA
batches it); FFT is the XLA FFT HLO."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .autograd.tape import apply

__all__ = ["frame", "overlap_add", "stft", "istft"]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice overlapping frames: [..., seq] -> [..., frame_length, n]
    (axis=-1; reference layout)."""
    def fn(a):
        n = (a.shape[axis] - frame_length) // hop_length + 1
        starts = jnp.arange(n) * hop_length
        idx = starts[:, None] + jnp.arange(frame_length)[None, :]  # [n, fl]
        out = jnp.take(a, idx, axis=axis)            # [..., n, fl]
        return jnp.swapaxes(out, -1, -2)             # [..., fl, n]

    return apply(fn, x, op_name="frame")


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame: [..., frame_length, n] -> [..., seq]."""
    def fn(a):
        fl, n = a.shape[-2], a.shape[-1]
        seq = (n - 1) * hop_length + fl
        frames = jnp.moveaxis(a, -1, 0)              # [n, ..., fl]
        out = jnp.zeros(a.shape[:-2] + (seq,), a.dtype)

        def body(i, acc):
            start = i * hop_length
            pad = jnp.zeros_like(acc)
            seg = jax.lax.dynamic_update_slice_in_dim(
                pad, frames[i], start, axis=-1)
            return acc + seg

        return jax.lax.fori_loop(0, n, body, out)

    return apply(fn, x, op_name="overlap_add")


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Short-time Fourier transform; returns [..., n_fft//2+1, frames]
    complex (onesided default, reference semantics)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    def fn(a, *w):
        x = a
        if center:
            pads = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            x = jnp.pad(x, pads, mode=pad_mode)
        n = (x.shape[-1] - n_fft) // hop_length + 1
        starts = jnp.arange(n) * hop_length
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]
        frames = jnp.take(x, idx, axis=-1)           # [..., n, n_fft]
        if w:
            win = w[0]
            if win_length < n_fft:
                lpad = (n_fft - win_length) // 2
                win = jnp.pad(win, (lpad, n_fft - win_length - lpad))
            frames = frames * win
        sp = (jnp.fft.rfft(frames, axis=-1) if onesided
              else jnp.fft.fft(frames, axis=-1))     # [..., n, bins]
        if normalized:
            sp = sp / jnp.sqrt(jnp.asarray(n_fft, sp.real.dtype))
        return jnp.swapaxes(sp, -1, -2)              # [..., bins, n]

    args = (x,) + ((window,) if window is not None else ())
    return apply(fn, *args, op_name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    def fn(sp, *w):
        sp_t = jnp.swapaxes(sp, -1, -2)              # [..., n, bins]
        if normalized:
            sp_t = sp_t * jnp.sqrt(jnp.asarray(n_fft, sp_t.real.dtype))
        frames = (jnp.fft.irfft(sp_t, n=n_fft, axis=-1) if onesided
                  else jnp.fft.ifft(sp_t, axis=-1).real)
        if w:
            win = w[0]
            if win_length < n_fft:
                lpad = (n_fft - win_length) // 2
                win = jnp.pad(win, (lpad, n_fft - win_length - lpad))
        else:
            win = jnp.ones((n_fft,), frames.dtype)
        frames = frames * win
        n = frames.shape[-2]
        seq = (n - 1) * hop_length + n_fft
        shape = frames.shape[:-2] + (seq,)
        num = jnp.zeros(shape, frames.dtype)
        den = jnp.zeros((seq,), frames.dtype)
        fmoved = jnp.moveaxis(frames, -2, 0)         # [n, ..., n_fft]
        wsq = win * win

        def body(i, carry):
            num, den = carry
            start = i * hop_length
            zn = jnp.zeros_like(num)
            num = num + jax.lax.dynamic_update_slice_in_dim(
                zn, fmoved[i], start, axis=-1)
            zd = jnp.zeros_like(den)
            den = den + jax.lax.dynamic_update_slice_in_dim(
                zd, wsq, start, axis=-1)
            return num, den

        num, den = jax.lax.fori_loop(0, n, body, (num, den))
        out = num / jnp.maximum(den, 1e-10)
        if center:
            out = out[..., n_fft // 2: out.shape[-1] - n_fft // 2]
        if length is not None:
            out = out[..., :length]
        return out

    args = (x,) + ((window,) if window is not None else ())
    return apply(fn, *args, op_name="istft")
