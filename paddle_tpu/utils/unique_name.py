"""reference: ``paddle.utils.unique_name`` — process-wide unique name
generation (``generate``/``guard``/``switch``); ``guard('prefix')``
namespaces generated names by the prefix."""
from __future__ import annotations

import contextlib

_counters: dict[str, int] = {}
_prefix: list[str] = [""]


def generate(key="tmp"):
    full = _prefix[0] + key
    n = _counters.get(full, 0)
    _counters[full] = n + 1
    return f"{full}_{n}"


def switch(new_generator=None):
    """Swap the counter state; returns the old (counters, prefix)."""
    global _counters
    old = (_counters, _prefix[0])
    if isinstance(new_generator, tuple):
        _counters, _prefix[0] = new_generator
    elif isinstance(new_generator, dict):
        _counters, _prefix[0] = new_generator, ""
    elif isinstance(new_generator, str):
        # reference: guard('prefix') namespaces names as 'prefix_name_N'
        _counters = {}
        _prefix[0] = new_generator if new_generator.endswith("_") \
            else new_generator + "_"
    else:
        _counters, _prefix[0] = {}, ""
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
