"""paddle.utils (reference: ``python/paddle/utils/`` — download cache,
cpp_extension, deprecations; SURVEY.md §2.2)."""
from __future__ import annotations

import hashlib
import os
import shutil

__all__ = ["run_check", "get_weights_path_from_url", "download",
           "cpp_extension", "deprecated", "try_import",
           "register_op", "get_op"]

from .custom_op import register_op, get_op  # noqa: E402,F401


def run_check():
    import paddle_tpu
    return paddle_tpu.run_check()


_WEIGHTS_HOME = os.path.expanduser("~/.cache/paddle_tpu/weights")


def get_weights_path_from_url(url, md5sum=None):
    """Reference: download+cache pretrained weights. Zero-egress build:
    resolves only from the local cache; a missing file raises with the
    expected cache path so users can place weights manually."""
    fname = os.path.basename(url)
    path = os.path.join(_WEIGHTS_HOME, fname)
    if os.path.exists(path):
        if md5sum:
            with open(path, "rb") as f:
                if hashlib.md5(f.read()).hexdigest() != md5sum:
                    raise IOError(f"md5 mismatch for cached {path}")
        return path
    raise IOError(
        f"no network egress in the TPU build: place the weights file at "
        f"{path} (wanted {url})")


class download:
    get_weights_path_from_url = staticmethod(get_weights_path_from_url)


class cpp_extension:
    """JIT-compile host-side C++ extensions (reference:
    ``python/paddle/utils/cpp_extension/`` — there it builds CUDA kernels
    against the paddle::Tensor ABI; on TPU, device kernels are Pallas
    (``paddle_tpu/ops/pallas`` + ``utils.register_op``) and this loader
    covers the HOST tier: compile C++ with the system toolchain, load via
    ctypes, lift into the op layer with ``register_op(host_callback=True)``."""

    _BUILD_HOME = os.path.expanduser("~/.cache/paddle_tpu/extensions")

    @staticmethod
    def load(name, sources, extra_cflags=None, extra_ldflags=None,
             build_directory=None, verbose=False, **kw):
        """Compile ``sources`` (paths or literal C++ code) into a shared
        library and return the loaded ``ctypes.CDLL`` (cached by content
        hash)."""
        import ctypes
        import subprocess
        import tempfile

        srcs, blobs = [], []
        for s in sources if isinstance(sources, (list, tuple)) else [sources]:
            if os.path.exists(s):
                with open(s) as f:
                    blobs.append(f.read())
                srcs.append(os.path.abspath(s))
            else:                      # literal source code
                blobs.append(s)
                srcs.append(None)
        # cache key covers sources AND build flags
        tag = hashlib.md5("\x00".join(
            blobs + (extra_cflags or []) + (extra_ldflags or []) + [name]
        ).encode()).hexdigest()[:16]
        bdir = build_directory or os.path.join(cpp_extension._BUILD_HOME, name)
        os.makedirs(bdir, exist_ok=True)
        so_path = os.path.join(bdir, f"{name}.{tag}.so")
        if not os.path.exists(so_path):
            files, scratch = [], []
            for src, blob in zip(srcs, blobs):
                if src is None:
                    # per-process unique scratch name: concurrent builders
                    # of the same tag must not share (or delete) sources
                    fd, src = tempfile.mkstemp(suffix=".cpp", dir=bdir)
                    with os.fdopen(fd, "w") as f:
                        f.write(blob)
                    scratch.append(src)
                files.append(src)
            # build to a private temp name, publish atomically: a concurrent
            # loader (multi-process launch) never CDLLs a half-written .so
            fd, tmp_so = tempfile.mkstemp(suffix=".so", dir=bdir)
            os.close(fd)
            cmd = (["g++", "-O2", "-shared", "-fPIC", "-std=c++17"]
                   + (extra_cflags or []) + files
                   + ["-o", tmp_so] + (extra_ldflags or []))
            if verbose:
                print("cpp_extension:", " ".join(cmd))
            try:
                proc = subprocess.run(cmd, capture_output=True, text=True)
                if proc.returncode != 0:
                    raise RuntimeError(
                        f"cpp_extension build failed:\n{proc.stderr[-4000:]}")
                os.replace(tmp_so, so_path)
            finally:
                for p in scratch + [tmp_so]:
                    try:
                        os.remove(p)
                    except OSError:
                        pass
        return ctypes.CDLL(so_path)


def deprecated(update_to="", since="", reason=""):
    def wrap(fn):
        return fn
    return wrap


def try_import(name, err_msg=None):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError as e:
        raise ImportError(err_msg or str(e))


def dataset_cache_path(filename):
    """Shared local dataset cache (~/.cache/paddle/dataset — the same root
    MNIST/Cifar resolve from) for the zero-egress build."""
    return os.path.join(os.path.expanduser("~/.cache/paddle/dataset"),
                        filename)

from . import unique_name  # noqa: F401
