"""paddle.utils (reference: ``python/paddle/utils/`` — download cache,
cpp_extension, deprecations; SURVEY.md §2.2)."""
from __future__ import annotations

import hashlib
import os
import shutil

__all__ = ["run_check", "get_weights_path_from_url", "download",
           "cpp_extension", "deprecated", "try_import"]


def run_check():
    import paddle_tpu
    return paddle_tpu.run_check()


_WEIGHTS_HOME = os.path.expanduser("~/.cache/paddle_tpu/weights")


def get_weights_path_from_url(url, md5sum=None):
    """Reference: download+cache pretrained weights. Zero-egress build:
    resolves only from the local cache; a missing file raises with the
    expected cache path so users can place weights manually."""
    fname = os.path.basename(url)
    path = os.path.join(_WEIGHTS_HOME, fname)
    if os.path.exists(path):
        if md5sum:
            with open(path, "rb") as f:
                if hashlib.md5(f.read()).hexdigest() != md5sum:
                    raise IOError(f"md5 mismatch for cached {path}")
        return path
    raise IOError(
        f"no network egress in the TPU build: place the weights file at "
        f"{path} (wanted {url})")


class download:
    get_weights_path_from_url = staticmethod(get_weights_path_from_url)


class cpp_extension:
    """Reference: JIT-compile CUDA/C++ custom ops. The TPU analogue for
    device kernels is Pallas (paddle_tpu/ops/pallas); host-side C++ builds
    via the same g++ path the native DataLoader uses (io/native)."""

    @staticmethod
    def load(name=None, sources=None, **kw):
        raise NotImplementedError(
            "custom device kernels on TPU are Pallas kernels "
            "(see paddle_tpu/ops/pallas); host-side C++ extensions build "
            "via ctypes like paddle_tpu/io/native")


def deprecated(update_to="", since="", reason=""):
    def wrap(fn):
        return fn
    return wrap


def try_import(name, err_msg=None):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError as e:
        raise ImportError(err_msg or str(e))
