"""Donation / aliasing misuse guards — the TPU analogue of the
reference's memory sanitizers (SURVEY.md §5.2: where CUDA builds lean on
compute-sanitizer/ASAN for use-after-free, the XLA equivalent failure
class is *buffer donation*: a donated input's HBM is reused for outputs,
and any later host access to the donated array is a use-after-free that
jax reports as a bare "Array has been deleted").

Two guards:

* :func:`donated_jit` — ``jax.jit`` + ``donate_argnums`` wrapper for
  Tensor-level training steps. After each call the donated Tensors'
  storage is replaced by a poison object, so ANY later use raises
  :class:`DonatedTensorError` naming the argument and the fix (rebind
  the returned arrays), instead of a deep-in-XLA deletion error.
* :func:`find_aliases` / :func:`assert_no_aliases` — detect distinct
  Parameters/Tensors silently sharing one backing buffer (unintended
  weight tying — the aliasing half of the sanitizer row; deliberate
  ties like tied embeddings can be allowlisted).
"""
from __future__ import annotations

import time

import jax

from ..framework.core import Tensor
from ..profiler import compile_observatory as _co


class DonatedTensorError(RuntimeError):
    pass


class _PoisonedStorage:
    """Stand-in for a donated Tensor's array: every use raises a clear
    diagnostic instead of XLA's 'Array has been deleted'."""

    __slots__ = ("_msg",)

    def __init__(self, msg):
        object.__setattr__(self, "_msg", msg)

    def _raise(self, *a, **k):
        raise DonatedTensorError(object.__getattribute__(self, "_msg"))

    def __getattr__(self, name):
        self._raise()

    __array__ = __iter__ = __len__ = __bool__ = _raise
    __add__ = __radd__ = __mul__ = __rmul__ = __sub__ = __rsub__ = _raise
    __matmul__ = __getitem__ = __neg__ = _raise

    def __repr__(self):
        return f"<donated tensor: {object.__getattribute__(self, '_msg')}>"


def donated_jit(fn, donate_argnums=(), **jit_kwargs):
    """jit ``fn`` with buffer donation over Tensor arguments, poisoning
    each donated Tensor after the call.

    ``fn`` receives/returns raw arrays (the usual functional train-step
    shape); the wrapper accepts Tensors or arrays at the donated
    positions. Typical use::

        step = donated_jit(train_step, donate_argnums=(0,))
        new_params = step(params_tensor_list, batch)   # params poisoned
    """
    donate = tuple(donate_argnums)
    jitted = jax.jit(fn, donate_argnums=donate, **jit_kwargs)
    # compile observatory: the donated train step is a jit boundary; a
    # shape/dtype churn in the step inputs is a silent retrace the
    # observatory must attribute (family "train.<fn>")
    family = f"train.{getattr(fn, '__name__', 'fn')}"
    _co.declare_family(family,
                       warmup=lambda: "warmed by first donated step")

    def unwrap(x):
        return x._data if isinstance(x, Tensor) else x

    def signature(raw, raw_kw):
        sig = {"donate_argnums": _co.static_arg(str(donate))}
        leaves = jax.tree.leaves((raw, raw_kw))
        for i, leaf in enumerate(leaves[:32]):
            if hasattr(leaf, "shape"):
                sig[f"leaf{i}"] = _co.tensor_arg(
                    leaf.shape, getattr(leaf, "dtype", "?"))
            else:
                sig[f"leaf{i}"] = _co.static_arg(leaf)
        if len(leaves) > 32:
            sig["extra_leaves"] = _co.static_arg(len(leaves) - 32)
        return sig

    def call(*args, **kwargs):
        is_t = lambda t: isinstance(t, Tensor)     # noqa: E731
        raw = [jax.tree.map(unwrap, a, is_leaf=is_t) for a in args]
        raw_kw = {k: jax.tree.map(unwrap, v, is_leaf=is_t)
                  for k, v in kwargs.items()}
        t_step = time.perf_counter() if _co.is_enabled() else None
        out = jitted(*raw, **raw_kw)
        if t_step is not None:
            _co.observe(family, signature(raw, raw_kw),
                        seconds=time.perf_counter() - t_step)
        for i in donate:
            msg = (f"argument {i} of {getattr(fn, '__name__', 'fn')} was "
                   f"DONATED to XLA (its HBM now backs the outputs); "
                   f"rebind the returned arrays instead of reusing it")

            def poison(t):
                if isinstance(t, Tensor):
                    t._data = _PoisonedStorage(msg)
                return t
            jax.tree.map(poison, args[i],
                         is_leaf=lambda t: isinstance(t, Tensor))
        return out

    return call


def find_aliases(tensors, names=None):
    """Group distinct Tensor objects that share one backing jax.Array.
    Returns a list of groups (each a list of names/indices, len >= 2)."""
    by_buf = {}
    for i, t in enumerate(tensors):
        if not isinstance(t, Tensor) or isinstance(t._data,
                                                   _PoisonedStorage):
            continue
        key = id(t._data)
        label = names[i] if names is not None else i
        by_buf.setdefault(key, []).append(label)
    return [g for g in by_buf.values() if len(g) > 1]


def assert_no_aliases(layer_or_tensors, allow=()):
    """Raise if two distinct Parameters share a buffer (unintended weight
    tying). ``allow``: name-substring allowlist for deliberate ties
    (e.g. ``("embed",)`` for tied embeddings)."""
    if hasattr(layer_or_tensors, "named_parameters"):
        named = [(n, p) for n, p in layer_or_tensors.named_parameters()
                 if p is not None]
        names = [n for n, _ in named]
        tensors = [p for _, p in named]
    else:
        tensors = list(layer_or_tensors)
        names = list(range(len(tensors)))
    groups = find_aliases(tensors, names)
    bad = [g for g in groups
           if not any(any(str(a) in str(n) for a in allow) for n in g)]
    if bad:
        raise AssertionError(
            f"distinct parameters share one buffer (unintended aliasing / "
            f"weight tying): {bad}; pass allow=(...) for deliberate ties")
    return groups
