"""First-compile guard for in-repo Pallas TPU kernels.

Reference analogue: the fail-fast watchdog semantics of the launch
controllers (``python/paddle/distributed/launch/controllers/`` — an
unhealthy worker is detected and killed by a supervisor instead of
hanging the job; SURVEY.md §5.3).

Round-2 post-mortem (VERDICT.md "What's weak" 1): under
``PALLAS_AXON_REMOTE_COMPILE=1`` the Mosaic compile of a brand-new
kernel runs server-side with **no error or timeout path** — one hung
compile of the from-scratch paged-attention kernel wedged the single
TPU tunnel for the rest of the session. This module makes "first Mosaic
compile of kernel X" an explicitly supervised event:

* :func:`prove` runs a kernel's canary (tiny tile-aligned shapes,
  fwd+bwd where the kernel has a VJP) in a DISPOSABLE subprocess under a
  hard timeout, and latches the outcome (``ok`` / ``bad``) to a marker
  file. A hang kills the child and latches ``bad``; it is never retried
  implicitly — a latched-bad kernel stays quarantined until
  :func:`clear` is called deliberately.
* kernel entry points call :func:`kernel_allowed` before their first
  real TPU dispatch. Unproven or quarantined kernels fall back to their
  pure-XLA reference path (slower but safe) with a warning, instead of
  risking the chip from a long-lived process that cannot be killed
  without losing session state.
* orchestrators (``bench.py``, ``tools/tpu_watch.sh``) call
  :func:`prove_all` for the kernels their workload needs *before*
  spawning the TPU child, so benches still get the fast kernels — every
  first compile having happened in a process that was safe to lose.

Guard policy (``PADDLE_TPU_KERNEL_GUARD`` env):

* ``strict`` (default) — only ``ok``-proven kernels may Mosaic-compile
  in this process; everything else uses the XLA fallback.
* ``prove``  — like strict, but an ``unknown`` kernel triggers a lazy
  one-time :func:`prove` at first dispatch (self-healing; the proof
  subprocess claims the TPU concurrently with this process, so only
  use it on runtimes that allow a second client — on a single-tunnel
  setup run the CLI before starting the job instead).
* ``trust`` — unproven kernels may compile (latched-``bad`` kernels are
  still blocked). For environments without the wedge failure mode.
* ``off``  — guard disabled entirely (unit tests, interpret mode).

The guard only engages on real TPU backends: CPU/interpret runs never
consult it (Mosaic interpret mode executes in-process and cannot hang
the tunnel).
"""
from __future__ import annotations

import os
import subprocess
import sys
import time
import warnings

_OK, _BAD, _UNKNOWN = "ok", "bad", "unknown"

# Canary sources. Contract: print PROOF_OK only after the kernel has
# BOTH Mosaic-compiled/run AND matched its XLA reference numerically
# (a miscompile that returns garbage must not latch ok); print
# PROOF_SKIP (and exit 3) when the environment can't answer the
# question (e.g. not actually on a TPU backend) — skips latch nothing.
# Shapes are small but tile-aligned (second-minor >= 8, minor 128) so
# the Mosaic lowering exercised is the same one real workloads hit.
_REQUIRE_TPU = """
import jax
if jax.default_backend() != "tpu":
    print("PROOF_SKIP: backend is " + jax.default_backend())
    raise SystemExit(3)
"""

CANARIES = {
    "flash_attention": _REQUIRE_TPU + """
import os
import numpy as np, jax, jax.numpy as jnp
from paddle_tpu.ops.pallas.flash_attention import (
    flash_attention, flash_attention_with_lse, mha_reference)
# the proof must compile the ACTUAL block configuration: _fwd clamps
# block_q/k to the sequence length, so a 256-long canary would silently
# prove a clamped kernel for a 512-block sweep config
seq = max(256,
          2 * int(os.environ.get("PADDLE_TPU_FA_BLOCK_Q", "128")),
          2 * int(os.environ.get("PADDLE_TPU_FA_BLOCK_K", "128")))
rs = np.random.RandomState(0)
q = jnp.asarray(rs.randn(1, seq, 4, 128), jnp.bfloat16)
k = jnp.asarray(rs.randn(1, seq, 2, 128), jnp.bfloat16)   # GQA group 2
v = jnp.asarray(rs.randn(1, seq, 2, 128), jnp.bfloat16)
def loss(q, k, v):
    out = flash_attention(q, k, v, causal=True, interpret=False)
    return out.astype(jnp.float32).sum()
def ref_loss(q, k, v):
    qk, kk, vk = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    out = jnp.swapaxes(mha_reference(qk, kk, vk, causal=True), 1, 2)
    return out.astype(jnp.float32).sum()
g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
gr = jax.jit(jax.grad(ref_loss, argnums=(0, 1, 2)))(q, k, v)
for got, want in zip(g, gr):
    got = got.astype(jnp.float32); want = want.astype(jnp.float32)
    gerr = float(jnp.max(jnp.abs(got - want)))
    scale = max(1.0, float(jnp.max(jnp.abs(want))))
    assert gerr < 5e-2 * scale, ("bwd numeric mismatch", gerr, scale)
qk, kk, vk = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
out, lse = flash_attention_with_lse(qk, kk, vk, q_offset=256, kv_offset=0,
                                    interpret=False)
ref, ref_lse = mha_reference(qk, kk, vk, q_offset=256, kv_offset=0,
                             with_lse=True)
err = float(jnp.max(jnp.abs(out.astype(jnp.float32) -
                            ref.astype(jnp.float32))))
lse_err = float(jnp.max(jnp.abs(lse - ref_lse)))
assert err < 5e-2 and lse_err < 5e-2, ("numeric mismatch", err, lse_err)
print("PROOF_OK")
""",
    "paged_attention": _REQUIRE_TPU + """
import numpy as np, jax, jax.numpy as jnp
from paddle_tpu.ops.pallas.paged_attention import (
    _paged_attention_pallas, paged_attention_reference)
rs = np.random.RandomState(0)
batch, kv_heads, group, d, page, npages = 4, 2, 4, 128, 16, 8
q = jnp.asarray(rs.randn(batch, kv_heads * group, d), jnp.bfloat16)
kp = jnp.asarray(rs.randn(kv_heads, npages, page, d), jnp.bfloat16)
vp = jnp.asarray(rs.randn(kv_heads, npages, page, d), jnp.bfloat16)
tbl = jnp.asarray(rs.randint(0, npages, (batch, 4)), jnp.int32)
lens = jnp.asarray([64, 33, 17, 50], jnp.int32)
out = _paged_attention_pallas(q, kp, vp, tbl, lens,
                              sm_scale=d ** -0.5, interpret=False)
ref = paged_attention_reference(q, kp, vp, tbl, lens)
err = float(jnp.max(jnp.abs(out.astype(jnp.float32) -
                            ref.astype(jnp.float32))))
assert err < 5e-2, ("numeric mismatch", err)
print("PROOF_OK")
""",
    "ragged_paged_attention": _REQUIRE_TPU + """
import numpy as np, jax, jax.numpy as jnp
from paddle_tpu.ops.pallas.ragged_paged_attention import (
    _ragged_paged_attention_pallas, _token_descriptors,
    ragged_paged_attention_reference)
rs = np.random.RandomState(0)
kv_heads, group, d, page, npages, pps = 2, 4, 128, 16, 12, 4
kp = jnp.asarray(rs.randn(kv_heads, npages, page, d), jnp.bfloat16)
vp = jnp.asarray(rs.randn(kv_heads, npages, page, d), jnp.bfloat16)
tbl = jnp.asarray(rs.randint(0, npages, (3, pps)), jnp.int32)
# mixed spans: decode, chunked-prefill continuation, fresh prefill
slots = jnp.asarray([0, 1, 2], jnp.int32)
q_starts = jnp.asarray([0, 1, 10], jnp.int32)
q_lens = jnp.asarray([1, 9, 6], jnp.int32)
ctx = jnp.asarray([33, 25, 6], jnp.int32)
q = jnp.asarray(rs.randn(16, kv_heads * group, d), jnp.bfloat16)
slot_t, ctx_t = _token_descriptors(16, slots, q_starts, q_lens, ctx)
out = _ragged_paged_attention_pallas(q, kp, vp, tbl, slot_t, ctx_t,
                                     sm_scale=d ** -0.5, interpret=False)
ref = ragged_paged_attention_reference(q, kp, vp, tbl, slots, q_starts,
                                       q_lens, ctx)
for s, qs, ql in ((0, 0, 1), (1, 1, 9), (2, 10, 6)):
    err = float(jnp.max(jnp.abs(
        out[qs:qs + ql].astype(jnp.float32)
        - ref[qs:qs + ql].astype(jnp.float32))))
    assert err < 5e-2, ("numeric mismatch", s, err)
print("PROOF_OK")
""",
    "paged_attention_int8": _REQUIRE_TPU + """
import numpy as np, jax, jax.numpy as jnp
from paddle_tpu.ops.pallas.paged_attention import (
    _paged_attention_pallas_quant, paged_attention_reference)
from paddle_tpu.models.generation import quantize_kv_rows, \
    dequantize_kv_rows
rs = np.random.RandomState(0)
batch, kv_heads, group, d, page, npages = 4, 2, 4, 128, 16, 8
q = jnp.asarray(rs.randn(batch, kv_heads * group, d), jnp.float32)
kq, ks = quantize_kv_rows(rs.randn(kv_heads, npages, page, d))
vq, vs = quantize_kv_rows(rs.randn(kv_heads, npages, page, d))
tbl = jnp.asarray(rs.randint(0, npages, (batch, 4)), jnp.int32)
lens = jnp.asarray([64, 33, 17, 50], jnp.int32)
out = _paged_attention_pallas_quant(q, kq, vq, ks, vs, tbl, lens,
                                    sm_scale=d ** -0.5, interpret=False)
ref = paged_attention_reference(q, dequantize_kv_rows(kq, ks),
                                dequantize_kv_rows(vq, vs), tbl, lens)
err = float(jnp.max(jnp.abs(out.astype(jnp.float32) -
                            ref.astype(jnp.float32))))
assert err < 5e-2, ("numeric mismatch", err)
print("PROOF_OK")
""",
    "ragged_paged_attention_int8": _REQUIRE_TPU + """
import numpy as np, jax, jax.numpy as jnp
from paddle_tpu.ops.pallas.ragged_paged_attention import (
    _ragged_paged_attention_pallas_quant, _token_descriptors,
    ragged_paged_attention_reference)
from paddle_tpu.models.generation import quantize_kv_rows, \
    dequantize_kv_rows
rs = np.random.RandomState(0)
kv_heads, group, d, page, npages, pps = 2, 4, 128, 16, 12, 4
kq, ks = quantize_kv_rows(rs.randn(kv_heads, npages, page, d))
vq, vs = quantize_kv_rows(rs.randn(kv_heads, npages, page, d))
tbl = jnp.asarray(rs.randint(0, npages, (3, pps)), jnp.int32)
# mixed spans incl. a q_len=5 speculative verify span
slots = jnp.asarray([0, 1, 2], jnp.int32)
q_starts = jnp.asarray([0, 1, 6], jnp.int32)
q_lens = jnp.asarray([1, 5, 9], jnp.int32)
ctx = jnp.asarray([33, 25, 9], jnp.int32)
q = jnp.asarray(rs.randn(16, kv_heads * group, d), jnp.float32)
slot_t, ctx_t = _token_descriptors(16, slots, q_starts, q_lens, ctx)
out = _ragged_paged_attention_pallas_quant(q, kq, vq, ks, vs, tbl,
                                           slot_t, ctx_t,
                                           sm_scale=d ** -0.5,
                                           interpret=False)
ref = ragged_paged_attention_reference(
    q, dequantize_kv_rows(kq, ks), dequantize_kv_rows(vq, vs), tbl,
    slots, q_starts, q_lens, ctx)
for s, qs, ql in ((0, 0, 1), (1, 1, 5), (2, 6, 9)):
    err = float(jnp.max(jnp.abs(
        out[qs:qs + ql].astype(jnp.float32)
        - ref[qs:qs + ql].astype(jnp.float32))))
    assert err < 5e-2, ("numeric mismatch", s, err)
print("PROOF_OK")
""",
    "ragged_paged_attention_qblock": _REQUIRE_TPU + """
import numpy as np, jax, jax.numpy as jnp
from paddle_tpu.ops.pallas.ragged_paged_attention import (
    _ragged_paged_attention_pallas_qblock, ragged_paged_attention_reference)
rs = np.random.RandomState(0)
kv_heads, group, d, page, npages, pps = 2, 4, 128, 16, 12, 4
kp = jnp.asarray(rs.randn(kv_heads, npages, page, d), jnp.bfloat16)
vp = jnp.asarray(rs.randn(kv_heads, npages, page, d), jnp.bfloat16)
tbl = jnp.asarray(rs.randint(0, npages, (3, pps)), jnp.int32)
# mixed spans chosen so q-blocks straddle span boundaries (q_block=8:
# block 0 holds the decode token + 7 prefill rows, block 1 the prefill
# tail + the fresh prefill head) and the last block is half padding
slots = jnp.asarray([0, 1, 2], jnp.int32)
q_starts = jnp.asarray([0, 1, 10], jnp.int32)
q_lens = jnp.asarray([1, 9, 6], jnp.int32)
ctx = jnp.asarray([33, 25, 6], jnp.int32)
q = jnp.asarray(rs.randn(16, kv_heads * group, d), jnp.bfloat16)
out = _ragged_paged_attention_pallas_qblock(
    q, kp, vp, tbl, slots, q_starts, q_lens, ctx,
    sm_scale=d ** -0.5, interpret=False, q_block=8)
ref = ragged_paged_attention_reference(q, kp, vp, tbl, slots, q_starts,
                                       q_lens, ctx)
for s, qs, ql in ((0, 0, 1), (1, 1, 9), (2, 10, 6)):
    err = float(jnp.max(jnp.abs(
        out[qs:qs + ql].astype(jnp.float32)
        - ref[qs:qs + ql].astype(jnp.float32))))
    assert err < 5e-2, ("numeric mismatch", s, err)
print("PROOF_OK")
""",
    "ragged_paged_attention_qblock_int8": _REQUIRE_TPU + """
import numpy as np, jax, jax.numpy as jnp
from paddle_tpu.ops.pallas.ragged_paged_attention import (
    _ragged_paged_attention_pallas_qblock, ragged_paged_attention_reference)
from paddle_tpu.models.generation import quantize_kv_rows, \
    dequantize_kv_rows
rs = np.random.RandomState(0)
kv_heads, group, d, page, npages, pps = 2, 4, 128, 16, 12, 4
kq, ks = quantize_kv_rows(rs.randn(kv_heads, npages, page, d))
vq, vs = quantize_kv_rows(rs.randn(kv_heads, npages, page, d))
tbl = jnp.asarray(rs.randint(0, npages, (3, pps)), jnp.int32)
# mixed spans incl. a q_len=5 speculative verify span straddling blocks
slots = jnp.asarray([0, 1, 2], jnp.int32)
q_starts = jnp.asarray([0, 1, 6], jnp.int32)
q_lens = jnp.asarray([1, 5, 9], jnp.int32)
ctx = jnp.asarray([33, 25, 9], jnp.int32)
q = jnp.asarray(rs.randn(16, kv_heads * group, d), jnp.float32)
out = _ragged_paged_attention_pallas_qblock(
    q, kq, vq, tbl, slots, q_starts, q_lens, ctx,
    sm_scale=d ** -0.5, interpret=False, k_scales=ks, v_scales=vs,
    q_block=8)
ref = ragged_paged_attention_reference(
    q, dequantize_kv_rows(kq, ks), dequantize_kv_rows(vq, vs), tbl,
    slots, q_starts, q_lens, ctx)
for s, qs, ql in ((0, 0, 1), (1, 1, 5), (2, 6, 9)):
    err = float(jnp.max(jnp.abs(
        out[qs:qs + ql].astype(jnp.float32)
        - ref[qs:qs + ql].astype(jnp.float32))))
    assert err < 5e-2, ("numeric mismatch", s, err)
print("PROOF_OK")
""",
    "quant_matmul": _REQUIRE_TPU + """
import numpy as np, jax, jax.numpy as jnp
from paddle_tpu.ops.pallas.quant_matmul import int8_matmul, quantize_weight
rs = np.random.RandomState(0)
x = jnp.asarray(rs.randn(128, 256), jnp.float32)
w8, scale = quantize_weight(jnp.asarray(rs.randn(256, 256), jnp.float32))
out = int8_matmul(x, w8, scale, interpret=False)
ref = x @ (w8.astype(jnp.float32) * scale[None, :])
err = float(jnp.max(jnp.abs(out - ref)))
assert err < 1e-3, ("numeric mismatch", err)
print("PROOF_OK")
""",
    # Proves the flash kernel compiles inside a shard_map/ppermute ring
    # context (the CP path). Requires the plain flash proof first — with
    # flash quarantined the ring would silently exercise only the XLA
    # fallback, proving nothing.
    "ring_attention": _REQUIRE_TPU + """
from paddle_tpu.utils import guarded_compile as _gc
if _gc.status("flash_attention") != "ok":
    print("PROOF_SKIP: flash_attention not proven ok yet")
    raise SystemExit(3)
import numpy as np, jax, jax.numpy as jnp
from jax.sharding import Mesh
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P
from paddle_tpu.ops.pallas.ring_attention import ring_flash_attention
from paddle_tpu.ops.pallas.flash_attention import mha_reference
rs = np.random.RandomState(0)
q = jnp.asarray(rs.randn(1, 256, 4, 128), jnp.bfloat16)
mesh = Mesh(np.asarray(jax.devices()[:1]), ("sep",))
f = shard_map(
    lambda a, b, c: ring_flash_attention(a, b, c, axis_name="sep",
                                         axis_size=1, interpret=False),
    mesh=mesh, in_specs=(P("sep"), P("sep"), P("sep")), out_specs=P("sep"),
    check_rep=False)
out = jax.jit(f)(q, q, q)
qk = jnp.swapaxes(q, 1, 2)
ref = jnp.swapaxes(mha_reference(qk, qk, qk), 1, 2)
err = float(jnp.max(jnp.abs(out.astype(jnp.float32) -
                            ref.astype(jnp.float32))))
assert err < 5e-2, ("numeric mismatch", err)
print("PROOF_OK")
""",
}

# Kernels each bench workload needs proven before its TPU child starts.
def _fa_kernel_id() -> str:
    """The flash-attention kernel id for the current block-size config —
    read from the SAME import-time module constants the call-site gate
    (ops/pallas/flash_attention._mosaic_allowed) uses, so the proved id
    and the gated id can never diverge (env changes after import are
    consistently ignored by both)."""
    import importlib
    _fa_mod = importlib.import_module(
        "paddle_tpu.ops.pallas.flash_attention")
    bq, bk = _fa_mod.DEFAULT_BLOCK_Q, _fa_mod.DEFAULT_BLOCK_K
    if (bq, bk) == (128, 128):
        return "flash_attention"
    return f"flash_attention_q{bq}k{bk}"


def bench_kernels(mode: str):
    """Kernel ids a bench mode must prove before spawning its child."""
    serving = [_fa_kernel_id(), "paged_attention", "ragged_paged_attention",
               "ragged_paged_attention_qblock"]
    if os.environ.get("BENCH_KV_DTYPE", "").lower() == "int8":
        serving += ["paged_attention_int8", "ragged_paged_attention_int8",
                    "ragged_paged_attention_qblock_int8"]
    if os.environ.get("BENCH_WEIGHT_DTYPE", "").lower() == "int8" \
            or os.environ.get("PADDLE_WEIGHT_DTYPE", "").lower() == "int8":
        serving += ["quant_matmul"]
    return {
        "resnet": [],
        "llama": [_fa_kernel_id()],
        "llama_decode": [_fa_kernel_id(), "paged_attention"],
        "serving": serving,
        "data": [],
    }.get(mode, [])


def _proof_dir() -> str:
    d = os.environ.get("PADDLE_TPU_KERNEL_PROOF_DIR")
    if not d:
        d = os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                         "kernel_proofs")
    os.makedirs(d, exist_ok=True)
    return d


def _marker(kernel_id: str, state: str) -> str:
    return os.path.join(_proof_dir(), f"{kernel_id}.{state}")


def _canary_src(kernel_id: str, missing_ok: bool = False):
    """Canary source for a kernel id. Configuration-suffixed ids (e.g.
    ``flash_attention_q256k128`` from the block-size sweep) reuse the base
    kernel's canary — the child inherits the env that selects the config,
    so the proof compiles the ACTUAL variant while the id keeps the latch
    distinct per configuration."""
    if kernel_id in CANARIES:
        return CANARIES[kernel_id]
    base = max((k for k in CANARIES if kernel_id.startswith(k + "_")),
               key=len, default=None)
    if base is not None:
        return CANARIES[base]
    if missing_ok:
        return None
    raise KeyError(kernel_id)


# Per-process memo of terminal proof states: one stat() per kernel per
# process instead of per dispatch. prove()/clear() keep it coherent;
# cross-process coherence is by convention (orchestrators prove BEFORE
# spawning the worker that consults the markers).
_STATUS_CACHE: dict = {}


def status(kernel_id: str) -> str:
    """Latched proof state: 'ok', 'bad' or 'unknown'. 'bad' wins — a
    kernel that ever hung stays quarantined until clear()."""
    key = (_proof_dir(), kernel_id)
    st = _STATUS_CACHE.get(key)
    if st in (_OK, _BAD):
        return st
    if os.path.exists(_marker(kernel_id, _BAD)):
        st = _BAD
    elif os.path.exists(_marker(kernel_id, _OK)):
        st = _OK
    else:
        st = _UNKNOWN
    if st != _UNKNOWN:
        _STATUS_CACHE[key] = st
    return st


def clear(kernel_id: str) -> None:
    _STATUS_CACHE.pop((_proof_dir(), kernel_id), None)
    for state in (_OK, _BAD):
        try:
            os.remove(_marker(kernel_id, state))
        except OSError:
            pass


def prove(kernel_id: str, timeout: float = 420.0, src: str | None = None,
          env: dict | None = None) -> bool:
    """Run the kernel's canary in a disposable subprocess under a hard
    timeout; latch and return the outcome. Idempotent: an existing
    latch is returned without re-running.

    Latch rules: a timeout or a real failure latches ``bad``; a
    PROOF_SKIP (canary found the environment unable to answer, e.g. not
    on a TPU backend) or a spawn error latches NOTHING — those are
    transient, not evidence about the kernel."""
    st = status(kernel_id)
    if st != _UNKNOWN:
        return st == _OK
    if src is None:
        src = _canary_src(kernel_id)
    child_env = dict(env if env is not None else os.environ)
    # Unconditional, NOT setdefault: if the child inherited strict it
    # would gate its own kernel, exercise only the XLA fallback, and
    # latch a vacuous PROOF_OK — the canary must compile the real
    # Mosaic kernel. The child process is disposable by construction.
    child_env["PADDLE_TPU_KERNEL_GUARD"] = "trust"
    note = ""
    t_prove = time.perf_counter()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", src], env=child_env,
            capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
        if "PROOF_SKIP" in proc.stdout or proc.returncode == 3:
            print(f"guarded_compile: '{kernel_id}' canary skipped (no "
                  f"latch): {proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else 'rc=3'}",
                  file=sys.stderr)
            return False
        ok = proc.returncode == 0 and "PROOF_OK" in proc.stdout
        if not ok:
            note = (proc.stdout[-400:] + "\n" + proc.stderr[-800:]).strip()
    except subprocess.TimeoutExpired:
        ok = False
        note = f"canary timed out after {timeout}s (possible Mosaic hang)"
    except OSError as e:
        print(f"guarded_compile: '{kernel_id}' canary spawn failed (no "
              f"latch): {e}", file=sys.stderr)
        return False
    with open(_marker(kernel_id, _OK if ok else _BAD), "w") as f:
        f.write(note or "proved")
    _STATUS_CACHE[(_proof_dir(), kernel_id)] = _OK if ok else _BAD
    # compile observatory: a canary run IS a compile event for the
    # kernel's program family (re-proofs after clear() are re-observed;
    # latched short-circuits above never reach here)
    try:
        from ..profiler import compile_observatory as _co
        if _co.is_enabled():
            fam = f"kernel.{kernel_id}"
            _co.declare_family(fam, warmup=lambda kid=kernel_id: prove(kid))
            _co.observe(fam, {"canary": _co.static_arg(kernel_id)},
                        seconds=time.perf_counter() - t_prove)
    except Exception:
        pass
    if not ok:
        print(f"guarded_compile: kernel '{kernel_id}' QUARANTINED: "
              f"{note.splitlines()[0] if note else 'failed'}",
              file=sys.stderr)
    return ok


def prove_all(kernel_ids, timeout: float = 420.0) -> dict:
    return {k: prove(k, timeout=timeout) for k in kernel_ids}


def kernel_allowed(kernel_id: str, what: str = "Pallas kernel",
                   fallback: str = "the XLA fallback path") -> bool:
    """Call-site gate for a kernel's first real-TPU dispatch from this
    (long-lived, not-safe-to-lose) process."""
    mode = os.environ.get("PADDLE_TPU_KERNEL_GUARD", "strict").lower()
    if mode == "off":
        return True
    st = status(kernel_id)
    if st == _OK:
        return True
    if st == _BAD:
        warnings.warn(
            f"{what} '{kernel_id}' is quarantined (its canary compile "
            f"hung or failed); using {fallback}. "
            f"`python -m paddle_tpu.utils.guarded_compile clear "
            f"{kernel_id}` to retry.", RuntimeWarning, stacklevel=3)
        return False
    if mode == "trust":
        return True
    if mode == "prove" and _canary_src(kernel_id, missing_ok=True):
        return prove(kernel_id)
    warnings.warn(
        f"{what} '{kernel_id}' has not been proven on this backend; "
        f"using {fallback}. Run `python -m "
        f"paddle_tpu.utils.guarded_compile prove {kernel_id}` (disposable "
        f"subprocess + timeout) first, set PADDLE_TPU_KERNEL_GUARD=prove "
        f"for lazy proving, or =trust to compile unproven kernels.",
        RuntimeWarning, stacklevel=3)
    return False


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(prog="paddle_tpu.utils.guarded_compile")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("prove")
    p.add_argument("kernels", nargs="+",
                   help=f"kernel ids or 'all' ({', '.join(CANARIES)})")
    p.add_argument("--timeout", type=float, default=420.0)
    s = sub.add_parser("status")
    s.add_argument("kernels", nargs="*", default=[])
    c = sub.add_parser("clear")
    c.add_argument("kernels", nargs="+")
    args = ap.parse_args(argv)
    names = list(CANARIES) if getattr(args, "kernels", None) in (["all"],) \
        else list(getattr(args, "kernels", []) or CANARIES)
    if args.cmd == "prove":
        unknown = [k for k in names if k not in CANARIES]
        if unknown:
            print(f"no canary for: {unknown} (known: {list(CANARIES)})",
                  file=sys.stderr)
            return 2
        res = prove_all(names, timeout=args.timeout)
        print(res)
        return 0 if all(res.values()) else 1
    if args.cmd == "clear":
        for k in names:
            clear(k)
        return 0
    for k in names:
        print(k, status(k))
    return 0


if __name__ == "__main__":
    sys.exit(main())
