"""Custom-op extension API — the TPU-native `PD_BUILD_OP`
(reference: ``paddle/phi/api/ext/op_meta_info.h`` macros +
``python/paddle/utils/cpp_extension/`` JIT loader; SURVEY.md §2.1
"Custom-op ext API").

On GPU the reference compiles user CUDA kernels against the `paddle::Tensor`
stable ABI and registers them into the op registry. The TPU analogue has two
tiers:

* **Device tier** — :func:`register_op`: any pure-jax callable (jnp/lax or a
  Pallas ``pallas_call`` kernel) becomes a first-class op: Tensor in/out,
  recorded on the autograd tape, jit/`to_static`-compatible, AMP-visible by
  its registered name, optional custom VJP (``jax.custom_vjp`` under the
  hood, so it also works under ``paddle.grad(create_graph=True)``).
* **Host tier** — :func:`paddle_tpu.utils.cpp_extension.load`: compile C++
  sources with the system toolchain into a shared library (ctypes), then lift
  a host function into the op layer with ``register_op(...,
  host_callback=True)`` (``jax.pure_callback`` under jit).

Worked in-tree example: ``paddle_tpu.ops.fused.fused_swiglu`` is registered
through this API with a hand-written VJP.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..autograd.tape import apply
from ..framework.core import Tensor

# name -> {fn, has_vjp, doc} (reference: OpMetaInfoMap singleton)
REGISTRY: dict = {}


def _as_array(x):
    return x._data if isinstance(x, Tensor) else x


def register_op(fwd=None, *, name=None, vjp=None, nondiff_argnums=(),
                host_callback=False, out_shape=None, override=False):
    """Register a custom op (decorator or functional form).

    ``fwd(*arrays, **static_kwargs)`` is a pure function of jax arrays.

    Without ``vjp``: gradients come from jax's autodiff of ``fwd``.

    With ``vjp``: ``fwd`` must return ``(out, residuals)`` and
    ``vjp(residuals, *out_cotangents) -> tuple`` must return one cotangent
    per differentiable positional input (``jax.custom_vjp`` convention;
    reference: the ``SetBackwardFn`` half of PD_BUILD_OP).

    ``nondiff_argnums``: positional args treated as static (hashable)
    configuration, not tensors.

    ``host_callback=True``: ``fwd`` runs on host (a ctypes call into a
    cpp_extension, numpy code, ...); it is wrapped in ``jax.pure_callback``
    so the op stays jit-compatible. ``out_shape(*inputs)`` must return the
    output ShapeDtypeStruct (or a pytree of them); host ops have no autodiff
    unless ``vjp`` is also given.
    """
    if fwd is None:
        return functools.partial(register_op, name=name, vjp=vjp,
                                 nondiff_argnums=nondiff_argnums,
                                 host_callback=host_callback,
                                 out_shape=out_shape, override=override)

    op_name = name or fwd.__name__
    if op_name in REGISTRY and not override:
        raise ValueError(f"custom op '{op_name}' is already registered "
                         "(pass override=True to replace)")

    if host_callback:
        if out_shape is None:
            raise ValueError("host_callback ops need out_shape")
        inner = fwd

        def device_fn(*args, **kwargs):
            shapes = out_shape(*args, **kwargs)
            return jax.pure_callback(
                lambda *a: inner(*a, **kwargs), shapes, *args,
                vmap_method="sequential")
        base = device_fn
    else:
        base = fwd

    if vjp is not None:
        # static kwargs bind by CLOSURE (cached per combination) so they
        # never become custom_vjp primal args needing cotangents
        @functools.lru_cache(maxsize=64)
        def _bound(kw_items):
            kw = dict(kw_items)
            wrapped = jax.custom_vjp(lambda *a: base(*a, **kw)[0],
                                     nondiff_argnums=tuple(nondiff_argnums))

            def _fwd(*a):
                return base(*a, **kw)

            def _bwd(*res_and_cot):
                # custom_vjp passes (nondiff..., residuals, cotangent)
                *nd, res, cot = res_and_cot
                cots = cot if isinstance(cot, tuple) else (cot,)
                grads = vjp(res, *cots) if not nd else vjp(*nd, res, *cots)
                return tuple(grads)

            wrapped.defvjp(_fwd, _bwd)
            return wrapped

        jfn = _bound(())
    else:
        jfn = base

    @functools.wraps(fwd)
    def op(*args, **kwargs):
        if vjp is not None and kwargs:
            if any(isinstance(v, Tensor) for v in kwargs.values()):
                raise TypeError(
                    f"custom op '{op_name}': Tensors must be passed "
                    "positionally when a vjp is registered (keyword args "
                    "are static configuration bound by closure)")
            try:
                fn = _bound(tuple(sorted(kwargs.items())))
            except TypeError:
                raise TypeError(
                    f"custom op '{op_name}': static kwargs must be hashable "
                    f"(got {kwargs})") from None
            return apply(fn, *args, op_name=op_name)
        return apply(jfn, *args, op_name=op_name, **kwargs)

    op.raw = jfn
    op.op_name = op_name
    REGISTRY[op_name] = {"fn": jfn, "has_vjp": vjp is not None,
                         "host": host_callback, "doc": fwd.__doc__}
    return op


def get_op(name):
    """Look up a registered custom op's raw jax callable."""
    return REGISTRY[name]["fn"]
