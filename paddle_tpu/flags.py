"""Global flags (reference: gflags-style ``FLAGS_*`` in
``paddle/phi/core/flags.cc`` + ``paddle.set_flags`` — SURVEY.md §5.6).

One typed registry; env overrides (``FLAGS_x=v``) read at import; unknown
flags are accepted with a warning-free passthrough so reference scripts run.
XLA knobs pass through to ``XLA_FLAGS``.
"""
from __future__ import annotations

import os
from typing import Any

_DEFAULTS: dict[str, Any] = {
    "FLAGS_cudnn_deterministic": False,
    "FLAGS_check_nan_inf": False,
    "FLAGS_allocator_strategy": "auto_growth",
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_use_cinn": False,          # XLA always on; kept for compat
    "FLAGS_nccl_blocking_wait": False,
    "FLAGS_embedding_deterministic": 0,
    "FLAGS_max_inplace_grad_add": 0,
    "FLAGS_conv_workspace_size_limit": 512,
    "FLAGS_use_flash_attention": True,   # Pallas FA kernel in sdpa (TPU only)
    # jax.checkpoint policy used by fleet.utils.recompute: "full" (drop
    # everything — reference recompute_granularity='full'), "dots" (save
    # non-batch matmul outputs, recompute elementwise — much cheaper
    # recompute at similar activation memory on TPU), "everything"
    # (checkpoint is a no-op; debugging)
    "FLAGS_recompute_policy": "full",
    # capture each op's primal replay closure on its GradNode so
    # paddle.grad(create_graph=True) works; disable to shed the extra
    # pinned input arrays on retained graphs when higher-order grads are
    # never taken (autograd/tape.py)
    "FLAGS_enable_double_grad": True,
}

_flags: dict[str, Any] = {}


def _coerce(cur, val):
    if isinstance(cur, bool):
        return val in (True, "1", "true", "True", 1)
    if isinstance(cur, int):
        return int(val)
    if isinstance(cur, float):
        return float(val)
    return val


def _init():
    for k, v in _DEFAULTS.items():
        env = os.environ.get(k)
        _flags[k] = _coerce(v, env) if env is not None else v


_init()


def set_flags(flags: dict):
    for k, v in flags.items():
        cur = _flags.get(k, _DEFAULTS.get(k))
        _flags[k] = _coerce(cur, v) if cur is not None else v
        if k == "FLAGS_check_nan_inf":
            from .autograd import tape
            tape._nan_check = bool(_flags[k])


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    return {k: _flags.get(k, _DEFAULTS.get(k)) for k in flags}


def flag(name, default=None):
    return _flags.get(name, _DEFAULTS.get(name, default))
