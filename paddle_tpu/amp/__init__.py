"""paddle.amp (reference: ``python/paddle/amp/`` — SURVEY.md §2.2: auto_cast
O1 white/black lists, O2 pure-fp16/bf16; GradScaler dynamic loss scaling;
amp.decorate master weights).

Integration point: ``tape.apply`` consults :func:`amp_cast_inputs` before
running each op — the TPU-native analogue of the reference's
``eager_amp_auto_cast.h`` hooks in generated forwards (SURVEY.md §3.1).
"""
from __future__ import annotations

import contextlib
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit)
def _check_finite_and_unscale(grads, inv):
    """Fused multi-tensor unscale + global finite check (reference:
    ``check_finite_and_unscale`` CUDA kernel) — one compiled program, one
    host sync per optimizer step."""
    outs = [(g.astype(jnp.float32) * inv).astype(g.dtype) for g in grads]
    finite = jnp.all(jnp.stack(
        [jnp.all(jnp.isfinite(g.astype(jnp.float32))) for g in grads]))
    return outs, jnp.logical_not(finite)

from ..framework.core import Tensor
from ..framework import dtype as dtypes
from ..autograd import tape as _tape
from ..autograd.tape import no_grad

# fp16/bf16-safe ops (matmul-class: MXU-friendly)
WHITE_LIST = {
    "matmul", "mm", "bmm", "linear", "conv1d", "conv2d", "conv3d",
    "conv2d_transpose", "einsum", "sdpa", "addmm",
}
# numerically sensitive: force fp32
BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "logsumexp", "softmax",
    "log_softmax", "cross_entropy", "bce", "bce_with_logits", "kl_div",
    "mse_loss", "l1_loss", "smooth_l1_loss", "sum", "mean", "norm", "cumsum",
    "pow", "square", "rsqrt", "sigmoid_focal_loss", "cosine_similarity",
    "softmax_with_cross_entropy", "layer_norm", "batch_norm", "group_norm",
    "instance_norm", "rms_norm",
}


class _AmpState:
    enabled = False
    level = "O1"
    dtype = jnp.float16
    white = WHITE_LIST
    black = BLACK_LIST


_state = _AmpState()


def amp_state():
    return _state


def _cast_tensors(args, dt):
    out = []
    changed = False
    for a in args:
        if isinstance(a, Tensor) and a.dtype in (jnp.float32, jnp.float16, jnp.bfloat16) \
                and a.dtype != jnp.dtype(dt):
            t = a.astype(dt)
            t.stop_gradient = a.stop_gradient
            # preserve autograd linkage: astype goes through the tape, so t
            # carries a cast node back to a. Good.
            out.append(t)
            changed = True
        else:
            out.append(a)
    return out, changed


def amp_cast_inputs(op_name, args):
    """Called by tape.apply: maybe cast Tensor args per AMP policy."""
    if not _state.enabled:
        return args
    if op_name == "cast":
        # the cast op IS the policy's tool — recasting its input would
        # recurse forever (cast -> amp cast -> cast ...)
        return args
    if _state.level == "O2":
        if op_name in _state.black:
            return _cast_tensors(args, jnp.float32)[0]
        return _cast_tensors(args, _state.dtype)[0]
    # O1
    if op_name in _state.white:
        return _cast_tensors(args, _state.dtype)[0]
    if op_name in _state.black:
        return _cast_tensors(args, jnp.float32)[0]
    return args


_tape._amp_cast_inputs = amp_cast_inputs


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="float16", use_promote=True):
    prev = (_state.enabled, _state.level, _state.dtype, _state.white, _state.black)
    _state.enabled = enable
    _state.level = level
    _state.dtype = dtypes.convert_dtype(dtype)
    _state.white = WHITE_LIST | set(custom_white_list or ())
    _state.black = (BLACK_LIST | set(custom_black_list or ())) - set(custom_white_list or ())
    try:
        yield
    finally:
        (_state.enabled, _state.level, _state.dtype, _state.white,
         _state.black) = prev


amp_guard = auto_cast  # legacy alias


def decorate(models, optimizers=None, level="O1", dtype="float16",
             master_weight=None, save_dtype=None, master_grad=False,
             excluded_layers=None):
    """O2: cast model params to fp16/bf16; optimizer keeps fp32 master weights."""
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        excluded = set()
        from ..nn.layers.norm import _BatchNormBase, LayerNorm, GroupNorm
        for m in model_list:
            for lyr in m.sublayers(include_self=True):
                skip = isinstance(lyr, (_BatchNormBase, LayerNorm, GroupNorm))
                if excluded_layers and isinstance(lyr, tuple(excluded_layers)):
                    skip = True
                if skip:
                    continue
                for p in lyr._parameters.values():
                    if p is not None and p.dtype == jnp.float32:
                        p._data = p._data.astype(dtypes.convert_dtype(dtype))
        if optimizers is not None:
            opt_list = optimizers if isinstance(optimizers, (list, tuple)) else [optimizers]
            for opt in opt_list:
                opt._multi_precision = True
    if optimizers is None:
        return models
    return models, optimizers


class GradScaler:
    """Dynamic loss scaling (reference: ``python/paddle/amp/grad_scaler.py`` —
    scale/unscale/inf-check via ``check_finite_and_unscale``, SURVEY.md §2.2)."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * self._scale

    def _unscale(self, optimizer):
        """ONE fused jitted unscale+finite-check over all grads (reference:
        the ``check_finite_and_unscale`` multi-tensor kernel) — a single
        device sync for the whole step instead of one blocking round-trip
        per parameter."""
        if not self._enable or self._unscaled:
            return
        grads = [p.grad._data for p in optimizer._parameter_list
                 if p.grad is not None]
        if grads:
            new_grads, found = _check_finite_and_unscale(
                grads, jnp.asarray(1.0 / self._scale, jnp.float32))
            i = 0
            for p in optimizer._parameter_list:
                if p.grad is None:
                    continue
                p.grad._data = new_grads[i]
                i += 1
            self._found_inf = bool(found)
        else:
            self._found_inf = False
        self._unscaled = True

    def unscale_(self, optimizer):
        self._unscale(optimizer)

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self._unscale(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._update()
        self._unscaled = False

    def update(self):
        pass  # paddle's step() already updates; kept for torch-style loops

    def _update(self):
        if not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        optimizer.clear_grad()

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_scale_ratio(self):
        return self._scale

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, state):
        self._scale = state["scale"]
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)


from . import debugging  # noqa: F401,E402  (full module: paddle.amp.debugging)


def _device_platform(device=None):
    import jax
    if device is None:
        return jax.devices()[0].platform.lower()
    s = str(device).lower()
    for p in ("tpu", "axon", "xpu", "gpu", "cuda", "cpu"):
        if p in s:
            # this build aliases every accelerator place to the TPU
            return {"cuda": "gpu", "xpu": "tpu"}.get(p, p)
    return s


def is_bfloat16_supported(device=None):
    """bf16 is the MXU-native dtype on TPU and runs everywhere XLA does."""
    return _device_platform(device) in ("tpu", "axon", "gpu", "cpu")


def is_float16_supported(device=None):
    return _device_platform(device) in ("tpu", "axon", "gpu")
