"""paddle.amp.debugging (reference: ``python/paddle/amp/debugging.py`` —
tensor checker utilities + the ``FLAGS_check_nan_inf`` per-op scan in
``nan_inf_utils``; SURVEY.md §5.2).

TPU-native: XLA is value-semantic so there are no data races to detect; the
useful guards are NaN/Inf detection — per-op (eager tape hook via
``FLAGS_check_nan_inf``) and under jit (``jax_debug_nans``).
"""
from __future__ import annotations

import contextlib
import enum

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from .. import flags as _flags


class DebugMode(enum.Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 2


def enable_tensor_checker(checker_config=None):
    """Turn on the per-op NaN/Inf scan (eager tape) + jit-time debug_nans."""
    _flags.set_flags({"FLAGS_check_nan_inf": True})
    try:
        jax.config.update("jax_debug_nans", True)
    except Exception:
        pass


def disable_tensor_checker():
    _flags.set_flags({"FLAGS_check_nan_inf": False})
    try:
        jax.config.update("jax_debug_nans", False)
    except Exception:
        pass


class TensorCheckerConfig:
    def __init__(self, enable=True, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None,
                 skipped_op_list=None, debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    """Scan one tensor; raises on NaN/Inf with identity info (reference
    behavior of the per-op checker)."""
    arr = tensor._data if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    if isinstance(arr, jax.core.Tracer):
        return tensor
    if jnp.issubdtype(arr.dtype, jnp.inexact):
        finite = bool(jnp.all(jnp.isfinite(arr)))
        if not finite:
            n_nan = int(jnp.isnan(arr).sum())
            n_inf = int(jnp.isinf(arr).sum())
            raise FloatingPointError(
                f"check_numerics: op={op_type or '?'} var="
                f"{var_name or getattr(tensor, 'name', '?')} has "
                f"{n_nan} NaN / {n_inf} Inf values")
    return tensor


@contextlib.contextmanager
def collect_operator_stats():
    """Count ops dispatched inside the region (reference collects per-dtype
    op stats for AMP debugging) — uses the profiler tape hook."""
    from ..profiler import Profiler, ProfilerTarget
    p = Profiler(targets=[ProfilerTarget.CPU], timer_only=True)
    p.start()
    try:
        yield p
    finally:
        p.stop()


def compare_accuracy(dump_path, another_dump_path, output_filename,
                     loss_scale=1, dump_all_tensors=False):
    raise NotImplementedError(
        "compare_accuracy needs the static dump pipeline; use "
        "check_numerics / enable_tensor_checker in the TPU build")
