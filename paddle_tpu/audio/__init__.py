"""paddle.audio (reference: ``python/paddle/audio/`` — Spectrogram /
MelSpectrogram / LogMelSpectrogram / MFCC features over the fft ops;
SURVEY.md §2.2). TPU-native: stft → XLA FFT; mel filterbank is a matmul."""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor
from ..autograd.tape import apply
from .. import signal as psignal

__all__ = ["functional", "features", "Spectrogram", "MelSpectrogram",
           "LogMelSpectrogram", "MFCC"]


def hz_to_mel(f, htk=False):
    if htk:
        return 2595.0 * np.log10(1.0 + np.asarray(f) / 700.0)
    f = np.asarray(f, dtype=np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    mel = (f - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    safe = np.maximum(f, 1e-10)       # where() evaluates both branches
    return np.where(f >= min_log_hz,
                    min_log_mel + np.log(safe / min_log_hz) / logstep, mel)


def mel_to_hz(mel, htk=False):
    if htk:
        return 700.0 * (10.0 ** (np.asarray(mel) / 2595.0) - 1.0)
    mel = np.asarray(mel, dtype=np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * mel
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(mel >= min_log_mel,
                    min_log_hz * np.exp(logstep * (mel - min_log_mel)), freqs)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney"):
    """Mel filterbank [n_mels, n_fft//2+1] (numpy; a constant)."""
    f_max = f_max or sr / 2
    n_bins = n_fft // 2 + 1
    fft_freqs = np.linspace(0, sr / 2, n_bins)
    mel_pts = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                          n_mels + 2)
    hz_pts = mel_to_hz(mel_pts, htk)
    fb = np.zeros((n_mels, n_bins))
    for m in range(n_mels):
        lo, ctr, hi = hz_pts[m], hz_pts[m + 1], hz_pts[m + 2]
        up = (fft_freqs - lo) / max(ctr - lo, 1e-10)
        down = (hi - fft_freqs) / max(hi - ctr, 1e-10)
        fb[m] = np.clip(np.minimum(up, down), 0, None)
    if norm == "slaney":
        enorm = 2.0 / (hz_pts[2:] - hz_pts[:-2])
        fb *= enorm[:, None]
    return fb.astype(np.float32)


class functional:
    hz_to_mel = staticmethod(hz_to_mel)
    mel_to_hz = staticmethod(mel_to_hz)
    compute_fbank_matrix = staticmethod(compute_fbank_matrix)

    @staticmethod
    def create_dct(n_mfcc, n_mels, norm="ortho"):
        n = np.arange(n_mels)
        k = np.arange(n_mfcc)[:, None]
        dct = np.cos(math.pi / n_mels * (n + 0.5) * k)
        if norm == "ortho":
            dct[0] *= 1.0 / math.sqrt(2)
            dct *= math.sqrt(2.0 / n_mels)
        return dct.astype(np.float32)


class Spectrogram:
    """Power spectrogram via stft: [..., n_fft//2+1, frames]."""

    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect"):
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        w = np.hanning(self.win_length) if window == "hann" \
            else np.hamming(self.win_length) if window == "hamming" \
            else np.ones(self.win_length)
        self.window = Tensor(w.astype(np.float32))

    def __call__(self, x):
        sp = psignal.stft(x, self.n_fft, self.hop_length, self.win_length,
                          window=self.window, center=self.center,
                          pad_mode=self.pad_mode)
        power = self.power
        return apply(lambda s: jnp.abs(s) ** power, sp, op_name="spec_power")


class MelSpectrogram(Spectrogram):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney"):
        super().__init__(n_fft, hop_length, win_length, window, power,
                         center, pad_mode)
        self.fbank = Tensor(compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm))

    def __call__(self, x):
        spec = super().__call__(x)                    # [..., bins, frames]
        return apply(lambda s, fb: jnp.einsum("mf,...ft->...mt", fb, s),
                     spec, self.fbank, op_name="mel_spec")


class LogMelSpectrogram(MelSpectrogram):
    def __init__(self, *a, ref_value=1.0, amin=1e-10, top_db=None, **kw):
        super().__init__(*a, **kw)
        self.amin = amin
        self.ref_value = ref_value
        self.top_db = top_db

    def __call__(self, x):
        mel = super().__call__(x)

        def fn(m):
            db = 10.0 * jnp.log10(jnp.maximum(m, self.amin))
            db = db - 10.0 * math.log10(max(self.amin, self.ref_value))
            if self.top_db is not None:
                db = jnp.maximum(db, db.max() - self.top_db)
            return db

        return apply(fn, mel, op_name="log_mel")


class MFCC:
    def __init__(self, sr=22050, n_mfcc=40, n_mels=64, **kw):
        self.logmel = LogMelSpectrogram(sr=sr, n_mels=n_mels, **kw)
        self.dct = Tensor(functional.create_dct(n_mfcc, n_mels))

    def __call__(self, x):
        lm = self.logmel(x)
        return apply(lambda m, d: jnp.einsum("km,...mt->...kt", d, m),
                     lm, self.dct, op_name="mfcc")


class features:
    Spectrogram = Spectrogram
    MelSpectrogram = MelSpectrogram
    LogMelSpectrogram = LogMelSpectrogram
    MFCC = MFCC


# ---------------------------------------------------------------------------
# datasets (reference: ``python/paddle/audio/datasets/`` — TESS, ESC50).
# Zero-egress: resolve pre-extracted arrays from the shared local cache.
# ---------------------------------------------------------------------------

class _CachedAudioDataset:
    """Waveform datasets from a pre-extracted ``<name>_<mode>.npz``
    ({'waveforms': float32 [N, T], 'labels': int64 [N]})."""

    _name = None

    def __init__(self, mode="train", feat_type="raw", data_file=None,
                 sample_rate=16000, **kw):
        import os
        self.mode = mode
        self.feat_type = feat_type
        if data_file is None:
            from ..utils import dataset_cache_path
            data_file = dataset_cache_path(f"{self._name}_{mode}.npz")
        if not os.path.exists(data_file):
            raise IOError(
                f"{type(self).__name__}: no network egress in the TPU "
                f"build — place the pre-extracted arrays at {data_file}")
        blob = np.load(data_file)
        self.waveforms = blob["waveforms"].astype(np.float32)
        self.labels = blob["labels"].astype(np.int64)
        # build the (filterbank-heavy) transform ONCE, not per sample
        self._mfcc = (MFCC(sr=sample_rate) if feat_type == "mfcc" else None)

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, i):
        wav = self.waveforms[i]
        if self._mfcc is not None:
            wav = np.asarray(self._mfcc(Tensor(wav[None])).numpy())[0]
        return wav, int(self.labels[i])


class TESS(_CachedAudioDataset):
    """Toronto emotional speech set (reference paddle.audio.datasets.TESS)."""

    _name = "tess"


class ESC50(_CachedAudioDataset):
    """ESC-50 environmental sounds (reference paddle.audio.datasets.ESC50)."""

    _name = "esc50"


# namespace packaging (reference: paddle.audio.{datasets,features,
# functional,backends} submodules) — this build keeps one module; expose
# the same access paths as lightweight namespace objects.
import types as _types

datasets = _types.SimpleNamespace(TESS=TESS, ESC50=ESC50)


def _load_wav(path, sr=None, mono=True, dtype="float32"):
    """Minimal WAV loader (reference backend ``soundfile.load``) — PCM
    16/32-bit and float32, stdlib ``wave`` only (zero-egress image)."""
    import wave as _wave
    with _wave.open(str(path), "rb") as w:
        nch, sw, rate, nframes = (w.getnchannels(), w.getsampwidth(),
                                  w.getframerate(), w.getnframes())
        raw = w.readframes(nframes)
    if sr is not None and int(sr) != rate:
        raise ValueError(
            f"audio.load: file is {rate} Hz but sr={sr} was requested — "
            "the wave backend does not resample; load at native rate and "
            "resample explicitly")
    if sw == 2:
        arr = np.frombuffer(raw, np.int16).astype(np.float32) / 32768.0
    elif sw == 4:
        arr = np.frombuffer(raw, np.int32).astype(np.float32) / 2147483648.0
    else:
        raise ValueError(f"unsupported WAV sample width {sw}")
    arr = arr.reshape(-1, nch).T
    if mono and nch > 1:
        arr = arr.mean(0, keepdims=True)
    return Tensor(jnp.asarray(arr.astype(dtype))), rate


backends = _types.SimpleNamespace(
    list_available_backends=lambda: ["wave"],
    get_current_backend=lambda: "wave",
    set_backend=lambda name: None,
    load=_load_wav,
)
load = _load_wav
