"""Device memory runtime (reference: ``paddle/fluid/memory`` /
``phi/core/memory`` — the stats/allocator layer; SURVEY.md §2.1
"Memory/allocators". On TPU the BFC allocator itself belongs to XLA
(SURVEY §7.0), so the runtime surface here is the part users actually
touch: per-device stats, live-buffer accounting, leak triage, and the
torch/paddle-style summary — built on PJRT ``memory_stats()`` plus
``jax.live_arrays()`` (real buffer-level introspection, not a facade).
"""
from __future__ import annotations

import jax

# reset_peak baselines per device index (XLA reports process-lifetime
# peaks; paddle/torch semantics want peaks since the last reset — we
# snapshot the lifetime peak at reset and report growth beyond it)
_PEAK_BASE: dict = {}


def _dev(device=None):
    devs = jax.local_devices()
    if device is None:
        return devs[0]
    if isinstance(device, int):
        return devs[device]
    if isinstance(device, str):          # "tpu:0" / "gpu:1" / "cpu"
        _, _, idx = device.partition(":")
        return devs[int(idx) if idx else 0]
    return device


def memory_stats(device=None) -> dict:
    """Raw PJRT allocator stats (bytes_in_use, peak_bytes_in_use,
    bytes_limit, num_allocs, ... — keys are backend-dependent)."""
    return dict(_dev(device).memory_stats() or {})


def memory_allocated(device=None) -> int:
    return int(memory_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    """Peak bytes in use since :func:`reset_peak_memory_stats` (or
    process start). XLA only exposes the lifetime peak, so after a
    reset this reports max(current, lifetime-peak growth)."""
    d = _dev(device)
    peak = int(memory_stats(d).get("peak_bytes_in_use", 0))
    base = _PEAK_BASE.get(d.id)
    if base is None:
        return peak
    # a lifetime peak above the reset snapshot must have happened after
    # the reset; otherwise the best observable bound is current usage
    return peak if peak > base else memory_allocated(d)


def reset_peak_memory_stats(device=None) -> None:
    d = _dev(device)
    _PEAK_BASE[d.id] = int(memory_stats(d).get("peak_bytes_in_use", 0))


def memory_reserved(device=None) -> int:
    return int(memory_stats(device).get("bytes_limit", 0))


def empty_cache() -> None:
    """No-op by design: XLA owns the device allocator and there is no
    fragmentation-fighting pool to release (the CUDA idiom of calling
    this per-N-steps must stay cheap). Use
    :func:`clear_compile_caches` to deliberately drop compiled
    executables (expensive: everything recompiles)."""


def clear_compile_caches() -> None:
    """Drop jit/compilation caches — reclaims host memory at the cost of
    full recompilation on next dispatch."""
    jax.clear_caches()


# -- live-buffer accounting (leak triage) ------------------------------------

def live_arrays(device=None):
    """All live jax Arrays on ``device`` (or every local device)."""
    arrs = jax.live_arrays()
    if device is None:
        return arrs
    want = _dev(device)
    out = []
    for a in arrs:
        try:
            if want in a.devices():
                out.append(a)
        except RuntimeError:        # deleted/donated between list & query
            pass
    return out


def live_tensor_report(device=None, top=20):
    """Aggregate live buffers by (shape, dtype): count and total bytes,
    largest first — the 'what is eating HBM' view."""
    groups: dict = {}
    for a in live_arrays(device):
        try:
            key = (tuple(a.shape), str(a.dtype))
            nbytes = a.size * a.dtype.itemsize
        except RuntimeError:
            continue
        cnt, tot = groups.get(key, (0, 0))
        groups[key] = (cnt + 1, tot + nbytes)
    rows = [{"shape": list(k[0]), "dtype": k[1], "count": c,
             "total_bytes": t} for k, (c, t) in groups.items()]
    rows.sort(key=lambda r: -r["total_bytes"])
    return rows[:top]


def memory_summary(device=None) -> str:
    """Human-readable report (torch.cuda.memory_summary shape)."""
    d = _dev(device)
    st = memory_stats(d)
    gib = 2.0 ** 30
    lines = [
        f"=== device memory summary: {d} ===",
        f"in use       : {st.get('bytes_in_use', 0) / gib:8.3f} GiB",
        f"lifetime peak: {st.get('peak_bytes_in_use', 0) / gib:8.3f} GiB",
        f"limit        : {st.get('bytes_limit', 0) / gib:8.3f} GiB",
        f"allocations  : {st.get('num_allocs', 'n/a')}",
        "--- largest live buffer groups ---",
    ]
    for r in live_tensor_report(d, top=8):
        lines.append(f"  {r['count']:4d} x {str(r['shape']):24s} "
                     f"{r['dtype']:10s} {r['total_bytes'] / gib:8.4f} GiB")
    return "\n".join(lines)
