"""paddle.device (reference: ``python/paddle/device/`` — SURVEY.md §2.2).
Streams/events are no-ops under XLA's async runtime (documented deviation:
XLA schedules and overlaps; there is no user-visible stream)."""
from __future__ import annotations

import jax

from ..framework.core import (  # noqa: F401
    set_device, get_device, current_place, device_count, Place, CPUPlace,
    TPUPlace, CUDAPlace, is_compiled_with_cuda, is_compiled_with_xpu,
)
from . import memory  # noqa: F401
from .memory import (  # noqa: F401
    memory_stats, memory_allocated, max_memory_allocated, memory_reserved,
    reset_peak_memory_stats, empty_cache, memory_summary,
    live_tensor_report,
)


def get_all_device_type():
    return ["cpu", "tpu"]


def get_available_device():
    return [f"{jax.default_backend()}:{i}" for i in range(jax.local_device_count())]


def get_available_custom_device():
    return []


def is_compiled_with_rocm():
    return False


def is_compiled_with_custom_device(name="tpu"):
    return name == "tpu"


def synchronize(device=None):
    """Block until all queued work on the device is done."""
    for d in jax.local_devices():
        try:
            d.synchronize_all_activity()
        except AttributeError:
            pass


class Stream:
    """Stream facade: XLA has no user streams; kept for API compat."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()

    def query(self):
        return True


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


def current_stream(device=None):
    return Stream(device)


def set_stream(stream):
    return stream


def stream_guard(stream):
    import contextlib
    return contextlib.nullcontext()


class cuda:
    """paddle.device.cuda namespace alias — maps to the accelerator."""
    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def synchronize(device=None):
        synchronize()

    memory_allocated = staticmethod(memory.memory_allocated)
    max_memory_allocated = staticmethod(memory.max_memory_allocated)
    # PJRT has no reserved-pool concept; the limit is the honest analogue
    max_memory_reserved = staticmethod(memory.memory_reserved)
    memory_reserved = staticmethod(memory.memory_reserved)
    empty_cache = staticmethod(memory.empty_cache)
    memory_summary = staticmethod(memory.memory_summary)
    reset_peak_memory_stats = staticmethod(memory.reset_peak_memory_stats)
    memory_stats = staticmethod(memory.memory_stats)

    @staticmethod
    def get_device_properties(device=None):
        d = jax.local_devices()[0]
        class Props:
            name = str(d)
            total_memory = (d.memory_stats() or {}).get("bytes_limit", 0)
        return Props()


# import-statement compatibility: ``import paddle.device.cuda`` must
# resolve even though cuda is a namespace class here
import sys as _sys

_sys.modules[__name__ + ".cuda"] = cuda
