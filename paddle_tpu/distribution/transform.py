"""Bijective transforms + TransformedDistribution (reference:
``python/paddle/distribution/transform.py``,
``transformed_distribution.py``)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..autograd.tape import apply
from .distribution import Distribution, _arr, _wrap, _shape_tuple


class Transform:
    """Bijection y = f(x) with log|det J|. ``_event_rank`` is the event
    rank of the OUTPUT space consumed by one application."""

    _event_rank = 0

    def forward(self, x):
        return apply(self._forward, x, op_name=type(self).__name__ + "_fwd")

    def inverse(self, y):
        return apply(self._inverse, y, op_name=type(self).__name__ + "_inv")

    def forward_log_det_jacobian(self, x):
        return apply(self._log_det, x,
                     op_name=type(self).__name__ + "_logdet")

    def inverse_log_det_jacobian(self, y):
        x = self.inverse(y)
        ld = self.forward_log_det_jacobian(x)
        return apply(lambda a: -a, ld, op_name="neg_logdet")

    # subclasses implement pure-jnp versions
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _log_det(self, x):
        raise NotImplementedError


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = loc
        self.scale = scale

    def _forward(self, x):
        return _arr(self.loc) + _arr(self.scale) * x

    def _inverse(self, y):
        return (y - _arr(self.loc)) / _arr(self.scale)

    def _log_det(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(_arr(self.scale))), x.shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _log_det(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = power

    def _forward(self, x):
        return jnp.power(x, _arr(self.power))

    def _inverse(self, y):
        return jnp.power(y, 1.0 / _arr(self.power))

    def _log_det(self, x):
        p = _arr(self.power)
        return jnp.log(jnp.abs(p * jnp.power(x, p - 1)))


class AbsTransform(Transform):
    """Non-bijective |x| (forward-only, like the reference)."""

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y   # principal branch

    def _log_det(self, x):
        return jnp.zeros_like(x)


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _log_det(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _log_det(self, x):
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    """exp + normalize over the last axis (not bijective; matches the
    reference's forward/inverse pair)."""

    _event_rank = 1

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)

    def _log_det(self, x):
        raise NotImplementedError("SoftmaxTransform has no log-det")


class StickBreakingTransform(Transform):
    """R^{K-1} -> K-simplex (reference StickBreakingTransform)."""

    _event_rank = 1

    def _forward(self, x):
        offset = x.shape[-1] - jnp.cumsum(jnp.ones_like(x), -1) + 1
        z = jax.nn.sigmoid(x - jnp.log(offset))
        zpad = jnp.concatenate([z, jnp.ones_like(z[..., :1])], -1)
        one_minus = jnp.concatenate(
            [jnp.ones_like(z[..., :1]), jnp.cumprod(1 - z, -1)], -1)
        return zpad * one_minus

    def _inverse(self, y):
        ycum = jnp.cumsum(y[..., :-1], -1)
        rest = 1 - jnp.concatenate(
            [jnp.zeros_like(y[..., :1]), ycum[..., :-1]], -1)
        offset = y.shape[-1] - 1 - jnp.cumsum(
            jnp.ones_like(y[..., :-1]), -1) + 1
        z = y[..., :-1] / rest
        return jnp.log(z / (1 - z)) + jnp.log(offset)

    def _log_det(self, x):
        offset = x.shape[-1] - jnp.cumsum(jnp.ones_like(x), -1) + 1
        t = x - jnp.log(offset)
        z = jax.nn.sigmoid(t)
        rest = jnp.concatenate(
            [jnp.ones_like(z[..., :1]), jnp.cumprod(1 - z, -1)[..., :-1]], -1)
        return jnp.sum(jnp.log(z) + jnp.log1p(-z) + jnp.log(rest), -1)


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = _shape_tuple(in_event_shape)
        self.out_event_shape = _shape_tuple(out_event_shape)
        if int(np.prod(self.in_event_shape or (1,))) != int(
                np.prod(self.out_event_shape or (1,))):
            raise ValueError("reshape must preserve the event size")
        self._event_rank = len(self.out_event_shape)

    def _forward(self, x):
        lead = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(lead + self.out_event_shape)

    def _inverse(self, y):
        lead = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(lead + self.in_event_shape)

    def _log_det(self, x):
        lead = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(lead, x.dtype)


class IndependentTransform(Transform):
    """Promote ``reinterpreted_batch_ndims`` batch dims of a base transform
    to event dims (log-det summed over them)."""

    def __init__(self, base, reinterpreted_batch_ndims):
        self.base = base
        self.reinterpreted_batch_ndims = int(reinterpreted_batch_ndims)
        self._event_rank = base._event_rank + self.reinterpreted_batch_ndims

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _log_det(self, x):
        ld = self.base._log_det(x)
        n = self.reinterpreted_batch_ndims
        if n == 0:
            return ld
        return jnp.sum(ld, axis=tuple(range(ld.ndim - n, ld.ndim)))


class StackTransform(Transform):
    """Apply a list of transforms along slices of ``axis``."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _map(self, fn_name, x):
        parts = jnp.split(x, len(self.transforms), axis=self.axis)
        outs = [getattr(t, fn_name)(p.squeeze(self.axis))
                for t, p in zip(self.transforms, parts)]
        return jnp.stack(outs, axis=self.axis)

    def _forward(self, x):
        return self._map("_forward", x)

    def _inverse(self, y):
        return self._map("_inverse", y)

    def _log_det(self, x):
        return self._map("_log_det", x)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)
        self._event_rank = max((t._event_rank for t in self.transforms),
                               default=0)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _log_det(self, x):
        total = None
        for t in self.transforms:
            ld = t._log_det(x)
            total = ld if total is None else total + ld
            x = t._forward(x)
        return total


class TransformedDistribution(Distribution):
    """reference ``python/paddle/distribution/transformed_distribution.py``."""

    def __init__(self, base, transforms):
        self.base = base
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.transforms = list(transforms)
        self._chain = ChainTransform(self.transforms)
        extra = self._chain._event_rank - len(base.event_shape)
        if extra > 0:
            # transform consumes batch dims as event dims
            shape = base.batch_shape + base.event_shape
            super().__init__(shape[:len(shape) - self._chain._event_rank],
                             shape[len(shape) - self._chain._event_rank:])
        else:
            super().__init__(base.batch_shape, base.event_shape)

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        x = x.detach()
        x.stop_gradient = True
        return x

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        """base log_prob at the pulled-back value minus the accumulated
        log-det, with event-rank reduction matching the reference."""
        y = value
        event_rank = max(self._chain._event_rank, len(self.base.event_shape))
        lp = None
        for t in reversed(self.transforms):
            x = t.inverse(y)

            def reduce_ld(a, rank=event_rank, trank=t._event_rank):
                n = rank - trank
                if n > 0:
                    return jnp.sum(
                        a, axis=tuple(range(a.ndim - n, a.ndim)))
                return a
            ld = apply(lambda xv, tt=t, rl=reduce_ld: rl(tt._log_det(xv)),
                       x, op_name="td_logdet")
            lp = ld if lp is None else apply(
                lambda a, b: a + b, lp, ld, op_name="td_logdet_acc")
            y = x
        base_lp = self.base.log_prob(y)
        # base event rank may be smaller than ours: sum the difference
        extra = event_rank - len(self.base.event_shape)

        def fin(blp, ldt=None):
            out = blp
            if extra > 0:
                out = jnp.sum(out,
                              axis=tuple(range(out.ndim - extra, out.ndim)))
            return out
        base_red = apply(fin, base_lp, op_name="td_base_red")
        if lp is None:
            return base_red
        return apply(lambda a, b: a - b, base_red, lp,
                     op_name="td_log_prob")
