"""Distribution families (reference: ``python/paddle/distribution/*.py`` —
one module per family upstream; gathered here since each is a thin
parameterization over jnp math + the framework PRNG).

Differentiable quantities (``log_prob``/``entropy``/``rsample``) run
through :func:`paddle_tpu.autograd.tape.apply` so they record on the tape
and trace under jit; draws use ``jax.random`` with counter-derived keys.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, to_tensor
from ..autograd.tape import apply
from .distribution import (
    Distribution, ExponentialFamily, _arr, _wrap, _shape_tuple, _HALF_LOG_2PI,
)

_EULER = float(np.euler_gamma)


def _param(x):
    if isinstance(x, Tensor):
        return x
    t = to_tensor(np.asarray(x, np.float32))
    t.stop_gradient = True
    return t


def _bshape(*xs):
    return tuple(np.broadcast_shapes(*[tuple(_arr(x).shape) for x in xs]))


class Normal(ExponentialFamily):
    """reference ``python/paddle/distribution/normal.py``."""

    def __init__(self, loc, scale, name=None):
        self.loc = _param(loc)
        self.scale = _param(scale)
        super().__init__(_bshape(self.loc, self.scale))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(_arr(self.loc), self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(_arr(self.scale) ** 2, self.batch_shape))

    def rsample(self, shape=()):
        full = self._extend_shape(shape)
        eps = jax.random.normal(self._key(), full, jnp.float32)
        return apply(lambda l, s: l + s * eps, self.loc, self.scale,
                     op_name="normal_rsample")

    def log_prob(self, value):
        def fn(l, s, v):
            return (-((v - l) ** 2) / (2.0 * s ** 2) - jnp.log(s)
                    - _HALF_LOG_2PI)
        return apply(fn, self.loc, self.scale, _param(value),
                     op_name="normal_log_prob")

    def entropy(self):
        def fn(l, s):
            return jnp.broadcast_to(0.5 + _HALF_LOG_2PI + jnp.log(s),
                                    _bshape(l, s))
        return apply(fn, self.loc, self.scale, op_name="normal_entropy")


class Uniform(Distribution):
    """reference ``python/paddle/distribution/uniform.py`` (support
    ``[low, high)``)."""

    def __init__(self, low, high, name=None):
        self.low = _param(low)
        self.high = _param(high)
        super().__init__(_bshape(self.low, self.high))

    @property
    def mean(self):
        return apply(lambda a, b: (a + b) / 2.0, self.low, self.high,
                     op_name="uniform_mean")

    @property
    def variance(self):
        return apply(lambda a, b: (b - a) ** 2 / 12.0, self.low, self.high,
                     op_name="uniform_var")

    def rsample(self, shape=()):
        full = self._extend_shape(shape)
        u = jax.random.uniform(self._key(), full, jnp.float32)
        return apply(lambda a, b: a + (b - a) * u, self.low, self.high,
                     op_name="uniform_rsample")

    def log_prob(self, value):
        def fn(a, b, v):
            inside = (v >= a) & (v < b)
            return jnp.where(inside, -jnp.log(b - a), -jnp.inf)
        return apply(fn, self.low, self.high, _param(value),
                     op_name="uniform_log_prob")

    def entropy(self):
        return apply(lambda a, b: jnp.log(b - a), self.low, self.high,
                     op_name="uniform_entropy")


class Bernoulli(ExponentialFamily):
    """reference ``python/paddle/distribution/bernoulli.py`` (probs
    parameterization)."""

    def __init__(self, probs, name=None):
        self.probs_param = _param(probs)
        super().__init__(_bshape(self.probs_param))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(_arr(self.probs_param),
                                      self.batch_shape))

    @property
    def variance(self):
        return apply(lambda p: p * (1 - p), self.probs_param,
                     op_name="bernoulli_var")

    def sample(self, shape=()):
        full = self._extend_shape(shape)
        p = jnp.broadcast_to(_arr(self.probs_param), self.batch_shape)
        out = jax.random.bernoulli(self._key(), p, full)
        return Tensor(out.astype(jnp.float32))

    rsample = sample

    def log_prob(self, value):
        def fn(p, v):
            eps = 1e-7
            pc = jnp.clip(p, eps, 1 - eps)
            return v * jnp.log(pc) + (1 - v) * jnp.log1p(-pc)
        return apply(fn, self.probs_param, _param(value),
                     op_name="bernoulli_log_prob")

    def entropy(self):
        def fn(p):
            eps = 1e-7
            pc = jnp.clip(p, eps, 1 - eps)
            return -(pc * jnp.log(pc) + (1 - pc) * jnp.log1p(-pc))
        return apply(fn, self.probs_param, op_name="bernoulli_entropy")


class Categorical(Distribution):
    """reference ``python/paddle/distribution/categorical.py`` — takes
    unnormalized ``logits``; last axis indexes categories."""

    def __init__(self, logits, name=None):
        self.logits = _param(logits)
        shape = tuple(_arr(self.logits).shape)
        self._num_categories = shape[-1]
        super().__init__(shape[:-1])

    @property
    def probs_tensor(self):
        return apply(jax.nn.softmax, self.logits, op_name="categorical_probs")

    def sample(self, shape=()):
        sample_shape = _shape_tuple(shape)
        lg = _arr(self.logits)
        # normalize: reference treats rows as unnormalized probabilities when
        # non-negative; we follow logits convention (log-space)
        out = jax.random.categorical(
            self._key(), lg, axis=-1,
            shape=sample_shape + tuple(lg.shape[:-1]))
        from ..framework.dtype import INT_DTYPE
        return Tensor(out.astype(INT_DTYPE))

    def log_prob(self, value):
        def fn(lg, v):
            logp = jax.nn.log_softmax(lg, axis=-1)
            vi = v.astype(jnp.int32)
            return jnp.take_along_axis(
                logp, vi[..., None], axis=-1)[..., 0]
        return apply(fn, self.logits, _param(value),
                     op_name="categorical_log_prob")

    def entropy(self):
        def fn(lg):
            logp = jax.nn.log_softmax(lg, axis=-1)
            return -jnp.sum(jnp.exp(logp) * logp, axis=-1)
        return apply(fn, self.logits, op_name="categorical_entropy")


class Beta(ExponentialFamily):
    """reference ``python/paddle/distribution/beta.py``."""

    def __init__(self, alpha, beta, name=None):
        self.alpha = _param(alpha)
        self.beta = _param(beta)
        super().__init__(_bshape(self.alpha, self.beta))

    @property
    def mean(self):
        return apply(lambda a, b: a / (a + b), self.alpha, self.beta,
                     op_name="beta_mean")

    @property
    def variance(self):
        return apply(lambda a, b: a * b / ((a + b) ** 2 * (a + b + 1)),
                     self.alpha, self.beta, op_name="beta_var")

    def rsample(self, shape=()):
        full = self._extend_shape(shape)
        k1, k2 = jax.random.split(self._key())

        def fn(a, b):
            ga = jax.random.gamma(k1, jnp.broadcast_to(a, full))
            gb = jax.random.gamma(k2, jnp.broadcast_to(b, full))
            return ga / (ga + gb)
        return apply(fn, self.alpha, self.beta, op_name="beta_rsample")

    def log_prob(self, value):
        def fn(a, b, v):
            lbeta = (jax.lax.lgamma(a) + jax.lax.lgamma(b)
                     - jax.lax.lgamma(a + b))
            return (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - lbeta
        return apply(fn, self.alpha, self.beta, _param(value),
                     op_name="beta_log_prob")

    def entropy(self):
        def fn(a, b):
            dg = jax.lax.digamma
            lbeta = (jax.lax.lgamma(a) + jax.lax.lgamma(b)
                     - jax.lax.lgamma(a + b))
            return (lbeta - (a - 1) * dg(a) - (b - 1) * dg(b)
                    + (a + b - 2) * dg(a + b))
        return apply(fn, self.alpha, self.beta, op_name="beta_entropy")


class Gamma(ExponentialFamily):
    """reference ``python/paddle/distribution/gamma.py`` (concentration /
    rate)."""

    def __init__(self, concentration, rate, name=None):
        self.concentration = _param(concentration)
        self.rate = _param(rate)
        super().__init__(_bshape(self.concentration, self.rate))

    @property
    def mean(self):
        return apply(lambda c, r: c / r, self.concentration, self.rate,
                     op_name="gamma_mean")

    @property
    def variance(self):
        return apply(lambda c, r: c / r ** 2, self.concentration, self.rate,
                     op_name="gamma_var")

    def rsample(self, shape=()):
        full = self._extend_shape(shape)
        key = self._key()

        def fn(c, r):
            g = jax.random.gamma(key, jnp.broadcast_to(c, full))
            return g / r
        return apply(fn, self.concentration, self.rate,
                     op_name="gamma_rsample")

    def log_prob(self, value):
        def fn(c, r, v):
            return (c * jnp.log(r) + (c - 1) * jnp.log(v) - r * v
                    - jax.lax.lgamma(c))
        return apply(fn, self.concentration, self.rate, _param(value),
                     op_name="gamma_log_prob")

    def entropy(self):
        def fn(c, r):
            return (c - jnp.log(r) + jax.lax.lgamma(c)
                    + (1 - c) * jax.lax.digamma(c))
        return apply(fn, self.concentration, self.rate,
                     op_name="gamma_entropy")


class Dirichlet(ExponentialFamily):
    """reference ``python/paddle/distribution/dirichlet.py``."""

    def __init__(self, concentration, name=None):
        self.concentration = _param(concentration)
        shape = tuple(_arr(self.concentration).shape)
        super().__init__(shape[:-1], shape[-1:])

    @property
    def mean(self):
        return apply(lambda c: c / jnp.sum(c, -1, keepdims=True),
                     self.concentration, op_name="dirichlet_mean")

    @property
    def variance(self):
        def fn(c):
            a0 = jnp.sum(c, -1, keepdims=True)
            m = c / a0
            return m * (1 - m) / (a0 + 1)
        return apply(fn, self.concentration, op_name="dirichlet_var")

    def rsample(self, shape=()):
        full = self._extend_shape(shape)
        key = self._key()

        def fn(c):
            g = jax.random.gamma(key, jnp.broadcast_to(c, full))
            return g / jnp.sum(g, -1, keepdims=True)
        return apply(fn, self.concentration, op_name="dirichlet_rsample")

    def log_prob(self, value):
        def fn(c, v):
            return (jnp.sum((c - 1) * jnp.log(v), -1)
                    + jax.lax.lgamma(jnp.sum(c, -1))
                    - jnp.sum(jax.lax.lgamma(c), -1))
        return apply(fn, self.concentration, _param(value),
                     op_name="dirichlet_log_prob")

    def entropy(self):
        def fn(c):
            a0 = jnp.sum(c, -1)
            k = c.shape[-1]
            lnB = jnp.sum(jax.lax.lgamma(c), -1) - jax.lax.lgamma(a0)
            return (lnB + (a0 - k) * jax.lax.digamma(a0)
                    - jnp.sum((c - 1) * jax.lax.digamma(c), -1))
        return apply(fn, self.concentration, op_name="dirichlet_entropy")


class Exponential(ExponentialFamily):
    """reference ``python/paddle/distribution/exponential.py`` (rate)."""

    def __init__(self, rate, name=None):
        self.rate = _param(rate)
        super().__init__(_bshape(self.rate))

    @property
    def mean(self):
        return apply(lambda r: 1.0 / r, self.rate, op_name="exp_mean")

    @property
    def variance(self):
        return apply(lambda r: 1.0 / r ** 2, self.rate, op_name="exp_var")

    def rsample(self, shape=()):
        full = self._extend_shape(shape)
        e = jax.random.exponential(self._key(), full, jnp.float32)
        return apply(lambda r: e / r, self.rate, op_name="exp_rsample")

    def log_prob(self, value):
        return apply(lambda r, v: jnp.log(r) - r * v, self.rate,
                     _param(value), op_name="exp_log_prob")

    def entropy(self):
        return apply(lambda r: 1.0 - jnp.log(r), self.rate,
                     op_name="exp_entropy")


class Geometric(Distribution):
    """reference ``python/paddle/distribution/geometric.py`` — pmf
    ``p (1-p)^k`` over failures ``k >= 0`` before the first success."""

    def __init__(self, probs, name=None):
        self.probs_param = _param(probs)
        super().__init__(_bshape(self.probs_param))

    @property
    def mean(self):
        return apply(lambda p: (1 - p) / p, self.probs_param,
                     op_name="geom_mean")

    @property
    def variance(self):
        return apply(lambda p: (1 - p) / p ** 2, self.probs_param,
                     op_name="geom_var")

    def sample(self, shape=()):
        full = self._extend_shape(shape)
        u = jax.random.uniform(self._key(), full, jnp.float32,
                               minval=1e-7, maxval=1.0)
        p = _arr(self.probs_param)
        out = jnp.floor(jnp.log(u) / jnp.log1p(-p))
        return Tensor(out.astype(jnp.float32))

    rsample = sample

    def log_prob(self, value):
        def fn(p, v):
            return v * jnp.log1p(-p) + jnp.log(p)
        return apply(fn, self.probs_param, _param(value),
                     op_name="geom_log_prob")

    def entropy(self):
        def fn(p):
            q = 1 - p
            return -(q * jnp.log(q) + p * jnp.log(p)) / p
        return apply(fn, self.probs_param, op_name="geom_entropy")


class Gumbel(Distribution):
    """reference ``python/paddle/distribution/gumbel.py``."""

    def __init__(self, loc, scale, name=None):
        self.loc = _param(loc)
        self.scale = _param(scale)
        super().__init__(_bshape(self.loc, self.scale))

    @property
    def mean(self):
        return apply(lambda l, s: l + s * _EULER, self.loc, self.scale,
                     op_name="gumbel_mean")

    @property
    def variance(self):
        return apply(lambda l, s: (math.pi ** 2 / 6.0) * s ** 2
                     + jnp.zeros_like(l),
                     self.loc, self.scale, op_name="gumbel_var")

    def rsample(self, shape=()):
        full = self._extend_shape(shape)
        g = jax.random.gumbel(self._key(), full, jnp.float32)
        return apply(lambda l, s: l + s * g, self.loc, self.scale,
                     op_name="gumbel_rsample")

    def log_prob(self, value):
        def fn(l, s, v):
            z = (v - l) / s
            return -(z + jnp.exp(-z)) - jnp.log(s)
        return apply(fn, self.loc, self.scale, _param(value),
                     op_name="gumbel_log_prob")

    def entropy(self):
        return apply(lambda l, s: jnp.log(s) + 1.0 + _EULER
                     + jnp.zeros_like(l),
                     self.loc, self.scale, op_name="gumbel_entropy")


class Laplace(Distribution):
    """reference ``python/paddle/distribution/laplace.py``."""

    def __init__(self, loc, scale, name=None):
        self.loc = _param(loc)
        self.scale = _param(scale)
        super().__init__(_bshape(self.loc, self.scale))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(_arr(self.loc), self.batch_shape))

    @property
    def variance(self):
        return apply(lambda l, s: 2 * s ** 2 + jnp.zeros_like(l),
                     self.loc, self.scale, op_name="laplace_var")

    def rsample(self, shape=()):
        full = self._extend_shape(shape)
        u = jax.random.uniform(self._key(), full, jnp.float32,
                               minval=-0.5 + 1e-7, maxval=0.5)
        return apply(lambda l, s: l - s * jnp.sign(u)
                     * jnp.log1p(-2 * jnp.abs(u)),
                     self.loc, self.scale, op_name="laplace_rsample")

    def log_prob(self, value):
        def fn(l, s, v):
            return -jnp.abs(v - l) / s - jnp.log(2 * s)
        return apply(fn, self.loc, self.scale, _param(value),
                     op_name="laplace_log_prob")

    def entropy(self):
        return apply(lambda l, s: 1.0 + jnp.log(2 * s) + jnp.zeros_like(l),
                     self.loc, self.scale, op_name="laplace_entropy")


class LogNormal(Distribution):
    """reference ``python/paddle/distribution/lognormal.py`` (upstream
    builds it as exp-transformed Normal; closed forms here)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _param(loc)
        self.scale = _param(scale)
        super().__init__(_bshape(self.loc, self.scale))

    @property
    def mean(self):
        return apply(lambda l, s: jnp.exp(l + s ** 2 / 2), self.loc,
                     self.scale, op_name="lognormal_mean")

    @property
    def variance(self):
        return apply(lambda l, s: (jnp.exp(s ** 2) - 1)
                     * jnp.exp(2 * l + s ** 2),
                     self.loc, self.scale, op_name="lognormal_var")

    def rsample(self, shape=()):
        full = self._extend_shape(shape)
        eps = jax.random.normal(self._key(), full, jnp.float32)
        return apply(lambda l, s: jnp.exp(l + s * eps), self.loc, self.scale,
                     op_name="lognormal_rsample")

    def log_prob(self, value):
        def fn(l, s, v):
            lv = jnp.log(v)
            return (-((lv - l) ** 2) / (2 * s ** 2) - jnp.log(s)
                    - _HALF_LOG_2PI - lv)
        return apply(fn, self.loc, self.scale, _param(value),
                     op_name="lognormal_log_prob")

    def entropy(self):
        return apply(lambda l, s: 0.5 + _HALF_LOG_2PI + jnp.log(s) + l,
                     self.loc, self.scale, op_name="lognormal_entropy")


class Multinomial(Distribution):
    """reference ``python/paddle/distribution/multinomial.py``."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs_param = _param(probs)
        shape = tuple(_arr(self.probs_param).shape)
        super().__init__(shape[:-1], shape[-1:])

    @property
    def mean(self):
        return apply(lambda p: self.total_count
                     * (p / jnp.sum(p, -1, keepdims=True)),
                     self.probs_param, op_name="multinomial_mean")

    @property
    def variance(self):
        def fn(p):
            pn = p / jnp.sum(p, -1, keepdims=True)
            return self.total_count * pn * (1 - pn)
        return apply(fn, self.probs_param, op_name="multinomial_var")

    def sample(self, shape=()):
        sample_shape = _shape_tuple(shape)
        p = _arr(self.probs_param)
        logits = jnp.log(p / jnp.sum(p, -1, keepdims=True))
        k = p.shape[-1]
        draws = jax.random.categorical(
            self._key(), logits, axis=-1,
            shape=(self.total_count,) + sample_shape + tuple(p.shape[:-1]))
        counts = jnp.sum(jax.nn.one_hot(draws, k, dtype=jnp.float32), axis=0)
        return Tensor(counts)

    rsample = sample

    def log_prob(self, value):
        def fn(p, v):
            pn = p / jnp.sum(p, -1, keepdims=True)
            # xlogy semantics: v=0 contributes 0 even when pn=0 (else
            # 0 * -inf poisons entropy() for zero-prob categories)
            term = jnp.where(v == 0, 0.0,
                             v * jnp.log(jnp.maximum(pn, 1e-38)))
            return (jax.lax.lgamma(jnp.asarray(self.total_count + 1.0))
                    - jnp.sum(jax.lax.lgamma(v + 1.0), -1)
                    + jnp.sum(term, -1))
        return apply(fn, self.probs_param, _param(value),
                     op_name="multinomial_log_prob")

    def entropy(self):
        """Monte-Carlo entropy (no closed form; reference estimates
        similarly): -E[log_prob] over framework-PRNG draws."""
        draws = self.sample((256,))
        lp = self.log_prob(draws)
        return apply(lambda a: -jnp.mean(a, axis=0), lp,
                     op_name="multinomial_entropy")


class MultivariateNormal(Distribution):
    """reference ``python/paddle/distribution/multivariate_normal.py``."""

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = _param(loc)
        given = [a is not None for a in
                 (covariance_matrix, precision_matrix, scale_tril)]
        if sum(given) != 1:
            raise ValueError("pass exactly one of covariance_matrix / "
                             "precision_matrix / scale_tril")
        if scale_tril is not None:
            self.scale_tril = _param(scale_tril)
        elif covariance_matrix is not None:
            cov = _param(covariance_matrix)
            self.scale_tril = apply(jnp.linalg.cholesky, cov,
                                    op_name="mvn_chol")
        else:
            prec = _param(precision_matrix)

            def fn(pm):
                c = jnp.linalg.cholesky(jnp.linalg.inv(pm))
                return c
            self.scale_tril = apply(fn, prec, op_name="mvn_chol_prec")
        d = tuple(_arr(self.loc).shape)[-1]
        batch = tuple(np.broadcast_shapes(
            tuple(_arr(self.loc).shape)[:-1],
            tuple(_arr(self.scale_tril).shape)[:-2]))
        self._dim = d
        super().__init__(batch, (d,))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(_arr(self.loc),
                                      self.batch_shape + self.event_shape))

    @property
    def variance(self):
        def fn(st):
            return jnp.broadcast_to(jnp.sum(st * st, -1),
                                    self.batch_shape + self.event_shape)
        return apply(fn, self.scale_tril, op_name="mvn_var")

    def rsample(self, shape=()):
        full = self._extend_shape(shape)
        eps = jax.random.normal(self._key(), full, jnp.float32)

        def fn(l, st):
            return l + jnp.einsum("...ij,...j->...i", st, eps)
        return apply(fn, self.loc, self.scale_tril, op_name="mvn_rsample")

    def log_prob(self, value):
        def fn(l, st, v):
            diff = v - l
            sol = jax.scipy.linalg.solve_triangular(
                jnp.broadcast_to(st, diff.shape[:-1] + st.shape[-2:]),
                diff[..., None], lower=True)[..., 0]
            m = jnp.sum(sol ** 2, -1)
            half_logdet = jnp.sum(
                jnp.log(jnp.diagonal(st, axis1=-2, axis2=-1)), -1)
            return (-0.5 * m - half_logdet
                    - self._dim * _HALF_LOG_2PI)
        return apply(fn, self.loc, self.scale_tril, _param(value),
                     op_name="mvn_log_prob")

    def entropy(self):
        def fn(st):
            half_logdet = jnp.sum(
                jnp.log(jnp.diagonal(st, axis1=-2, axis2=-1)), -1)
            return jnp.broadcast_to(
                0.5 * self._dim * (1.0 + 2.0 * _HALF_LOG_2PI) + half_logdet,
                self.batch_shape)
        return apply(fn, self.scale_tril, op_name="mvn_entropy")


class Poisson(ExponentialFamily):
    """reference ``python/paddle/distribution/poisson.py``."""

    _ENTROPY_TERMS = 128   # static series cutoff (accurate for rate < ~60)

    def __init__(self, rate, name=None):
        self.rate = _param(rate)
        super().__init__(_bshape(self.rate))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(_arr(self.rate), self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(_arr(self.rate), self.batch_shape))

    def sample(self, shape=()):
        full = self._extend_shape(shape)
        lam = jnp.broadcast_to(_arr(self.rate), full)
        out = jax.random.poisson(self._key(), lam)
        return Tensor(out.astype(jnp.float32))

    rsample = sample

    def log_prob(self, value):
        def fn(r, v):
            return v * jnp.log(r) - r - jax.lax.lgamma(v + 1.0)
        return apply(fn, self.rate, _param(value), op_name="poisson_log_prob")

    def entropy(self):
        def fn(r):
            k = jnp.arange(self._ENTROPY_TERMS, dtype=jnp.float32)
            shape = r.shape + (1,)
            rr = r.reshape(shape)
            logpmf = (k * jnp.log(rr) - rr - jax.lax.lgamma(k + 1.0))
            return -jnp.sum(jnp.exp(logpmf) * logpmf, -1)
        return apply(fn, self.rate, op_name="poisson_entropy")


class Binomial(Distribution):
    """reference ``python/paddle/distribution/binomial.py``."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs_param = _param(probs)
        super().__init__(_bshape(self.probs_param))

    @property
    def mean(self):
        return apply(lambda p: self.total_count * p, self.probs_param,
                     op_name="binomial_mean")

    @property
    def variance(self):
        return apply(lambda p: self.total_count * p * (1 - p),
                     self.probs_param, op_name="binomial_var")

    def sample(self, shape=()):
        full = self._extend_shape(shape)
        p = jnp.broadcast_to(_arr(self.probs_param), full)
        draws = jax.random.bernoulli(
            self._key(), p[None], (self.total_count,) + full)
        return Tensor(jnp.sum(draws.astype(jnp.float32), axis=0))

    rsample = sample

    def log_prob(self, value):
        n = float(self.total_count)

        def fn(p, v):
            eps = 1e-7
            pc = jnp.clip(p, eps, 1 - eps)
            logc = (jax.lax.lgamma(jnp.asarray(n + 1.0))
                    - jax.lax.lgamma(v + 1.0) - jax.lax.lgamma(n - v + 1.0))
            return logc + v * jnp.log(pc) + (n - v) * jnp.log1p(-pc)
        return apply(fn, self.probs_param, _param(value),
                     op_name="binomial_log_prob")

    def entropy(self):
        """Exact entropy by summing -pmf*log_pmf over the (static)
        support 0..total_count."""
        n = self.total_count

        def fn(p):
            eps = 1e-7
            pc = jnp.clip(p, eps, 1 - eps)
            k = jnp.arange(n + 1, dtype=jnp.float32)
            shape = pc.shape + (1,)
            pcr = pc.reshape(shape)
            logpmf = (jax.lax.lgamma(jnp.asarray(n + 1.0))
                      - jax.lax.lgamma(k + 1.0)
                      - jax.lax.lgamma(n - k + 1.0)
                      + k * jnp.log(pcr) + (n - k) * jnp.log1p(-pcr))
            return -jnp.sum(jnp.exp(logpmf) * logpmf, -1)
        return apply(fn, self.probs_param, op_name="binomial_entropy")


class Cauchy(Distribution):
    """reference ``python/paddle/distribution/cauchy.py`` (undefined
    mean/variance, matching upstream which raises)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _param(loc)
        self.scale = _param(scale)
        super().__init__(_bshape(self.loc, self.scale))

    @property
    def mean(self):
        raise ValueError("Cauchy distribution has no mean")

    @property
    def variance(self):
        raise ValueError("Cauchy distribution has no variance")

    def rsample(self, shape=()):
        full = self._extend_shape(shape)
        u = jax.random.uniform(self._key(), full, jnp.float32,
                               minval=1e-6, maxval=1 - 1e-6)
        return apply(lambda l, s: l + s * jnp.tan(math.pi * (u - 0.5)),
                     self.loc, self.scale, op_name="cauchy_rsample")

    def log_prob(self, value):
        def fn(l, s, v):
            z = (v - l) / s
            return -jnp.log(math.pi * s * (1 + z ** 2))
        return apply(fn, self.loc, self.scale, _param(value),
                     op_name="cauchy_log_prob")

    def entropy(self):
        return apply(lambda l, s: jnp.log(4 * math.pi * s)
                     + jnp.zeros_like(l),
                     self.loc, self.scale, op_name="cauchy_entropy")


class StudentT(Distribution):
    """reference ``python/paddle/distribution/student_t.py`` (df, loc,
    scale)."""

    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _param(df)
        self.loc = _param(loc)
        self.scale = _param(scale)
        super().__init__(_bshape(self.df, self.loc, self.scale))

    @property
    def mean(self):
        def fn(df, l):
            return jnp.where(df > 1, jnp.broadcast_to(l, _bshape(df, l)),
                             jnp.nan)
        return apply(fn, self.df, self.loc, op_name="studentt_mean")

    @property
    def variance(self):
        def fn(df, s):
            v = s ** 2 * df / (df - 2)
            return jnp.where(df > 2, v,
                             jnp.where(df > 1, jnp.inf, jnp.nan))
        return apply(fn, self.df, self.scale, op_name="studentt_var")

    def rsample(self, shape=()):
        full = self._extend_shape(shape)
        k1, k2 = jax.random.split(self._key())
        eps = jax.random.normal(k1, full, jnp.float32)

        def fn(df, l, s):
            g = jax.random.gamma(k2, jnp.broadcast_to(df / 2.0, full))
            chi2 = 2.0 * g
            t = eps * jnp.sqrt(df / chi2)
            return l + s * t
        return apply(fn, self.df, self.loc, self.scale,
                     op_name="studentt_rsample")

    def log_prob(self, value):
        def fn(df, l, s, v):
            z = (v - l) / s
            return (jax.lax.lgamma((df + 1) / 2)
                    - jax.lax.lgamma(df / 2)
                    - 0.5 * jnp.log(df * math.pi) - jnp.log(s)
                    - (df + 1) / 2 * jnp.log1p(z ** 2 / df))
        return apply(fn, self.df, self.loc, self.scale, _param(value),
                     op_name="studentt_log_prob")

    def entropy(self):
        def fn(df, s):
            dg = jax.lax.digamma
            return ((df + 1) / 2 * (dg((df + 1) / 2) - dg(df / 2))
                    + 0.5 * jnp.log(df)
                    + jax.lax.lgamma(df / 2)
                    + jax.lax.lgamma(jnp.asarray(0.5))
                    - jax.lax.lgamma((df + 1) / 2)
                    + jnp.log(s))
        return apply(fn, self.df, self.scale, op_name="studentt_entropy")


class ContinuousBernoulli(ExponentialFamily):
    """reference ``python/paddle/distribution/continuous_bernoulli.py`` —
    CB(λ) on [0,1]: p(x|λ) = C(λ)·λ^x·(1-λ)^(1-x), with normalizer
    C(λ) = 2·artanh(1-2λ)/(1-2λ) (→ 2 as λ→1/2). Sampling is exact via
    the closed-form inverse CDF."""

    _EPS = 1e-6

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs_param = _param(probs)
        self._lims = lims
        super().__init__(_bshape(self.probs_param))

    def _safe(self, p):
        # pull λ out of the unstable neighborhood of 1/2 for the
        # closed-form branches; the jnp.where selects the Taylor value
        # there instead
        lo, hi = self._lims
        mid = (p >= lo) & (p <= hi)
        return mid, jnp.where(mid, 0.25, jnp.clip(p, self._EPS,
                                                  1 - self._EPS))

    def _log_norm(self, p):
        mid, ps = self._safe(p)
        c = jnp.log(2 * jnp.arctanh(1 - 2 * ps) / (1 - 2 * ps))
        # Taylor at 1/2: log C ≈ log 2 + 4(λ-1/2)²/3
        return jnp.where(mid, jnp.log(2.0) + 4 * (p - 0.5) ** 2 / 3, c)

    def _mean_expr(self, p):
        mid, ps = self._safe(p)
        m = ps / (2 * ps - 1) + 1 / (2 * jnp.arctanh(1 - 2 * ps))
        return jnp.where(mid, 0.5 + (p - 0.5) / 3, m)

    @property
    def mean(self):
        return apply(self._mean_expr, self.probs_param, op_name="cb_mean")

    @property
    def variance(self):
        def fn(p):
            mid, ps = self._safe(p)
            v = ps * (ps - 1) / (1 - 2 * ps) ** 2 \
                + 1 / (2 * jnp.arctanh(1 - 2 * ps)) ** 2
            return jnp.where(mid, 1 / 12 - (p - 0.5) ** 2 / 15, v)
        return apply(fn, self.probs_param, op_name="cb_var")

    def log_prob(self, value):
        def fn(p, v):
            pc = jnp.clip(p, self._EPS, 1 - self._EPS)
            return (v * jnp.log(pc) + (1 - v) * jnp.log1p(-pc)
                    + self._log_norm(p))
        return apply(fn, self.probs_param, _param(value),
                     op_name="cb_log_prob")

    def icdf(self, value):
        def fn(p, u):
            mid, ps = self._safe(p)
            x = jnp.log1p(u * (2 * ps - 1) / (1 - ps)) \
                / jnp.log(ps / (1 - ps))
            return jnp.clip(jnp.where(mid, u, x), 0.0, 1.0)
        return apply(fn, self.probs_param, _param(value), op_name="cb_icdf")

    def cdf(self, value):
        def fn(p, x):
            mid, ps = self._safe(p)
            c = (ps ** x * (1 - ps) ** (1 - x) + ps - 1) / (2 * ps - 1)
            return jnp.clip(jnp.where(mid, x, c), 0.0, 1.0)
        return apply(fn, self.probs_param, _param(value), op_name="cb_cdf")

    def sample(self, shape=()):
        full = self._extend_shape(shape)
        u = jax.random.uniform(self._key(), full)
        return self.icdf(Tensor(u))

    def rsample(self, shape=()):
        return self.sample(shape)

    def entropy(self):
        def fn(p):
            pc = jnp.clip(p, self._EPS, 1 - self._EPS)
            mean = self._mean_expr(p)
            return -(mean * jnp.log(pc) + (1 - mean) * jnp.log1p(-pc)
                     + self._log_norm(p))
        return apply(fn, self.probs_param, op_name="cb_entropy")
