"""KL divergence registry + closed forms (reference:
``python/paddle/distribution/kl.py`` — ``register_kl`` double-dispatch
over distribution types)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..autograd.tape import apply
from .distribution import _arr
from . import families as F

_REGISTRY = {}


def register_kl(p_cls, q_cls):
    """Decorator: register ``fn(p, q) -> Tensor`` for (type(p), type(q));
    dispatch walks the MRO like the reference."""
    def deco(fn):
        _REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p, q):
    best, depth = None, None
    for (pc, qc), fn in _REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            d = (type(p).__mro__.index(pc), type(q).__mro__.index(qc))
            if depth is None or d < depth:
                best, depth = fn, d
    if best is None:
        raise NotImplementedError(
            f"no KL registered for ({type(p).__name__}, {type(q).__name__}); "
            "use register_kl to add one")
    return best(p, q)


@register_kl(F.Normal, F.Normal)
def _kl_normal(p, q):
    def fn(pl, ps, ql, qs):
        var_ratio = (ps / qs) ** 2
        t1 = ((pl - ql) / qs) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
    return apply(fn, p.loc, p.scale, q.loc, q.scale, op_name="kl_normal")


@register_kl(F.Uniform, F.Uniform)
def _kl_uniform(p, q):
    def fn(pa, pb, qa, qb):
        out = jnp.log((qb - qa) / (pb - pa))
        return jnp.where((qa <= pa) & (pb <= qb), out, jnp.inf)
    return apply(fn, p.low, p.high, q.low, q.high, op_name="kl_uniform")


@register_kl(F.Bernoulli, F.Bernoulli)
def _kl_bernoulli(p, q):
    def fn(pp, qp):
        eps = 1e-7
        pp = jnp.clip(pp, eps, 1 - eps)
        qp = jnp.clip(qp, eps, 1 - eps)
        return (pp * (jnp.log(pp) - jnp.log(qp))
                + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qp)))
    return apply(fn, p.probs_param, q.probs_param, op_name="kl_bernoulli")


@register_kl(F.Categorical, F.Categorical)
def _kl_categorical(p, q):
    def fn(pl, ql):
        plog = jax.nn.log_softmax(pl, axis=-1)
        qlog = jax.nn.log_softmax(ql, axis=-1)
        return jnp.sum(jnp.exp(plog) * (plog - qlog), -1)
    return apply(fn, p.logits, q.logits, op_name="kl_categorical")


@register_kl(F.Beta, F.Beta)
def _kl_beta(p, q):
    def fn(pa, pb, qa, qb):
        lg, dg = jax.lax.lgamma, jax.lax.digamma

        def lbeta(a, b):
            return lg(a) + lg(b) - lg(a + b)
        return (lbeta(qa, qb) - lbeta(pa, pb)
                + (pa - qa) * dg(pa) + (pb - qb) * dg(pb)
                + (qa - pa + qb - pb) * dg(pa + pb))
    return apply(fn, p.alpha, p.beta, q.alpha, q.beta, op_name="kl_beta")


@register_kl(F.Gamma, F.Gamma)
def _kl_gamma(p, q):
    def fn(pc, pr, qc, qr):
        lg, dg = jax.lax.lgamma, jax.lax.digamma
        return ((pc - qc) * dg(pc) - lg(pc) + lg(qc)
                + qc * (jnp.log(pr) - jnp.log(qr))
                + pc * (qr - pr) / pr)
    return apply(fn, p.concentration, p.rate, q.concentration, q.rate,
                 op_name="kl_gamma")


@register_kl(F.Dirichlet, F.Dirichlet)
def _kl_dirichlet(p, q):
    def fn(pc, qc):
        lg, dg = jax.lax.lgamma, jax.lax.digamma
        p0 = jnp.sum(pc, -1)
        q0 = jnp.sum(qc, -1)
        return (lg(p0) - lg(q0)
                - jnp.sum(lg(pc) - lg(qc), -1)
                + jnp.sum((pc - qc) * (dg(pc) - dg(p0)[..., None]), -1))
    return apply(fn, p.concentration, q.concentration, op_name="kl_dirichlet")


@register_kl(F.Exponential, F.Exponential)
def _kl_exponential(p, q):
    def fn(pr, qr):
        ratio = qr / pr
        return ratio - 1 - jnp.log(ratio)
    return apply(fn, p.rate, q.rate, op_name="kl_exponential")


@register_kl(F.Laplace, F.Laplace)
def _kl_laplace(p, q):
    def fn(pl, ps, ql, qs):
        # KL(La(u1,b1)||La(u2,b2)) = log(b2/b1) + |u1-u2|/b2
        #                            + (b1/b2) exp(-|u1-u2|/b1) - 1
        adiff = jnp.abs(pl - ql)
        return (jnp.log(qs / ps) + adiff / qs
                + (ps / qs) * jnp.exp(-adiff / ps) - 1.0)
    return apply(fn, p.loc, p.scale, q.loc, q.scale, op_name="kl_laplace")


@register_kl(F.Geometric, F.Geometric)
def _kl_geometric(p, q):
    def fn(pp, qp):
        return (-(1 - pp) / pp * (jnp.log1p(-qp) - jnp.log1p(-pp))
                + jnp.log(pp) - jnp.log(qp))
    return apply(fn, p.probs_param, q.probs_param, op_name="kl_geometric")


@register_kl(F.MultivariateNormal, F.MultivariateNormal)
def _kl_mvn(p, q):
    def fn(pl, pst, ql, qst):
        d = pl.shape[-1]
        half_logdet_p = jnp.sum(
            jnp.log(jnp.diagonal(pst, axis1=-2, axis2=-1)), -1)
        half_logdet_q = jnp.sum(
            jnp.log(jnp.diagonal(qst, axis1=-2, axis2=-1)), -1)
        m = jax.scipy.linalg.solve_triangular(qst, pst, lower=True)
        tr = jnp.sum(m * m, axis=(-2, -1))
        diff = ql - pl
        sol = jax.scipy.linalg.solve_triangular(
            qst, diff[..., None], lower=True)[..., 0]
        maha = jnp.sum(sol ** 2, -1)
        return 0.5 * (2 * (half_logdet_q - half_logdet_p) - d + tr + maha)
    return apply(fn, p.loc, p.scale_tril, q.loc, q.scale_tril,
                 op_name="kl_mvn")


@register_kl(F.LogNormal, F.LogNormal)
def _kl_lognormal(p, q):
    # KL is invariant under the shared exp transform -> Normal KL
    def fn(pl, ps, ql, qs):
        var_ratio = (ps / qs) ** 2
        t1 = ((pl - ql) / qs) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
    return apply(fn, p.loc, p.scale, q.loc, q.scale, op_name="kl_lognormal")


@register_kl(F.Poisson, F.Poisson)
def _kl_poisson(p, q):
    def fn(pr, qr):
        return pr * (jnp.log(pr) - jnp.log(qr)) - pr + qr
    return apply(fn, p.rate, q.rate, op_name="kl_poisson")
