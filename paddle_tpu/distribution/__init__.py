"""paddle.distribution — probability distributions, transforms, and KL
(reference: ``python/paddle/distribution/`` — Distribution base +
``normal.py``/``uniform.py``/... families, ``transform.py``, ``kl.py``;
SURVEY.md citation convention: canonical upstream paths, unverified).

TPU-native design: parameters live as ``Tensor``s and all differentiable
math (``log_prob``, ``entropy``, ``rsample``) is written in paddle ops so
it records on the autograd tape and traces under ``jax.jit``; sampling
draws from the framework PRNG (``paddle.seed``-derived counter keys,
``framework/random.py``) via ``jax.random`` so it is deterministic and
TPU-resident.
"""
from __future__ import annotations

from .distribution import Distribution, ExponentialFamily, Independent
from .families import (
    Bernoulli, Beta, Binomial, Categorical, Cauchy, ContinuousBernoulli,
    Dirichlet, Exponential,
    Gamma, Geometric, Gumbel, Laplace, LogNormal, Multinomial,
    MultivariateNormal, Normal, Poisson, StudentT, Uniform,
)
from .transform import (
    AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    IndependentTransform, PowerTransform, ReshapeTransform, SigmoidTransform,
    SoftmaxTransform, StackTransform, StickBreakingTransform, TanhTransform,
    Transform, TransformedDistribution,
)
from .kl import kl_divergence, register_kl

__all__ = [
    "Distribution", "ExponentialFamily", "Independent",
    "Bernoulli", "Beta", "Binomial", "Categorical", "Cauchy",
    "ContinuousBernoulli", "Dirichlet",
    "Exponential", "Gamma", "Geometric", "Gumbel", "Laplace", "LogNormal",
    "Multinomial", "MultivariateNormal", "Normal", "Poisson", "StudentT",
    "Uniform",
    "Transform", "TransformedDistribution", "AbsTransform", "AffineTransform",
    "ChainTransform", "ExpTransform", "IndependentTransform",
    "PowerTransform", "ReshapeTransform", "SigmoidTransform",
    "SoftmaxTransform", "StackTransform", "StickBreakingTransform",
    "TanhTransform",
    "kl_divergence", "register_kl",
]
