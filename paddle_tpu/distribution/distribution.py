"""Distribution base classes (reference:
``python/paddle/distribution/distribution.py``,
``exponential_family.py``, ``independent.py``)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..framework import random as prandom
from ..autograd.tape import apply


def _arr(x, dtype=None):
    """Tensor/array/scalar -> jnp array (keeps Tensors' underlying array)."""
    if isinstance(x, Tensor):
        a = x._data
    else:
        a = jnp.asarray(x, jnp.float32 if isinstance(x, (int, float)) else None)
    if dtype is not None and a.dtype != dtype:
        a = a.astype(dtype)
    return a


def _wrap(a):
    return a if isinstance(a, Tensor) else Tensor(a)


def _shape_tuple(shape):
    if shape is None:
        return ()
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


class Distribution:
    """Base of all distributions (reference Distribution ABC: sample /
    rsample / log_prob / probs / entropy / kl_divergence, with
    ``batch_shape`` + ``event_shape``)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = _shape_tuple(batch_shape)
        self._event_shape = _shape_tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    @property
    def stddev(self):
        return _wrap(jnp.sqrt(_arr(self.variance)))

    def sample(self, shape=()):
        """Draw (non-reparameterized); default falls back to rsample with
        gradients cut, matching the reference's sample/rsample split."""
        out = self.rsample(shape).detach()
        out.stop_gradient = True
        return out

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return _wrap(jnp.exp(_arr(self.log_prob(value))))

    # reference spells it ``probs``
    def probs(self, value):
        return self.prob(value)

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        from .kl import kl_divergence
        return kl_divergence(self, other)

    def _extend_shape(self, sample_shape):
        return (_shape_tuple(sample_shape) + self.batch_shape
                + self.event_shape)

    def _key(self):
        return prandom.next_key()


class ExponentialFamily(Distribution):
    """Exponential-family marker (reference ``exponential_family.py`` —
    enables the Bregman-divergence generic entropy; subclasses here
    provide closed forms so this stays a marker/base)."""


class Independent(Distribution):
    """Reinterpret the rightmost ``reinterpreted_batch_ndims`` batch dims as
    event dims (reference ``python/paddle/distribution/independent.py``)."""

    def __init__(self, base, reinterpreted_batch_ndims):
        self.base = base
        self.reinterpreted_batch_ndims = int(reinterpreted_batch_ndims)
        shape = base.batch_shape + base.event_shape
        split = len(base.batch_shape) - self.reinterpreted_batch_ndims
        if split < 0:
            raise ValueError(
                "reinterpreted_batch_ndims exceeds batch rank "
                f"({self.reinterpreted_batch_ndims} > {len(base.batch_shape)})")
        super().__init__(shape[:split], shape[split:])

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)

        def fn(a):
            n = self.reinterpreted_batch_ndims
            return jnp.sum(a, axis=tuple(range(a.ndim - n, a.ndim))) if n else a
        return apply(fn, lp, op_name="independent_log_prob")

    def entropy(self):
        ent = self.base.entropy()

        def fn(a):
            n = self.reinterpreted_batch_ndims
            return jnp.sum(a, axis=tuple(range(a.ndim - n, a.ndim))) if n else a
        return apply(fn, ent, op_name="independent_entropy")


_HALF_LOG_2PI = 0.5 * math.log(2.0 * math.pi)
