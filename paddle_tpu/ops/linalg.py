"""paddle.linalg (reference: ``python/paddle/tensor/linalg.py`` — SURVEY.md §2.2)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..autograd.tape import apply, defop


@defop
def norm(x, p=None, axis=None, keepdim=False):
    if p is None:
        p = "fro" if axis is None or isinstance(axis, (list, tuple)) else 2
    if axis is None:
        x = x.reshape(-1)
        return jnp.linalg.norm(x, ord=2 if p == "fro" else p)
    if isinstance(axis, (list, tuple)):
        return jnp.linalg.norm(x, ord="fro" if p == "fro" else p,
                               axis=tuple(axis), keepdims=keepdim)
    return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)


vector_norm = norm


@defop
def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False):
    return jnp.linalg.norm(x, ord=p, axis=tuple(axis), keepdims=keepdim)


@defop
def dist(x, y, p=2.0):
    return jnp.linalg.norm((x - y).reshape(-1), ord=p)


@defop
def inv(x):
    return jnp.linalg.inv(x)


@defop
def pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rcond=rcond, hermitian=hermitian)


@defop
def det(x):
    return jnp.linalg.det(x)


@defop
def slogdet(x):
    s, l = jnp.linalg.slogdet(x)
    return jnp.stack([s, l])


@defop
def cholesky(x, upper=False):
    c = jnp.linalg.cholesky(x)
    return jnp.swapaxes(c, -1, -2).conj() if upper else c


@defop
def cholesky_solve(x, y, upper=False):
    c = y if not upper else jnp.swapaxes(y, -1, -2)
    return jax.scipy.linalg.cho_solve((c, True), x)


def qr(x, mode="reduced"):
    return apply(lambda a: tuple(jnp.linalg.qr(a, mode=mode)), x, op_name="qr")


def svd(x, full_matrices=False):
    return apply(lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)),
                 x, op_name="svd")


def eig(x):
    arr = x.numpy() if isinstance(x, Tensor) else x
    import numpy as np
    w, v = np.linalg.eig(arr)
    return Tensor(w), Tensor(v)


def eigh(x, UPLO="L"):
    return apply(lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), x, op_name="eigh")


@defop
def eigvals(x):
    return jnp.linalg.eigvals(x)


@defop
def eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


@defop
def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


@defop
def matrix_rank(x, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(x, rtol=tol)


@defop
def solve(x, y):
    return jnp.linalg.solve(x, y)


@defop
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


def lstsq(x, y, rcond=None, driver=None):
    out = apply(lambda a, b: jnp.linalg.lstsq(a, b, rcond=rcond)[0], x, y,
                op_name="lstsq")
    return (out,)


def lu(x, pivot=True):
    def fn(a):
        lu_, piv = jax.scipy.linalg.lu_factor(a)
        return lu_, piv.astype(jnp.int32)
    return apply(fn, x, op_name="lu")


@defop
def multi_dot(tensors):
    return jnp.linalg.multi_dot(tensors)


@defop
def cond(x, p=None):
    return jnp.linalg.cond(x, p=p)


@defop
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


@defop
def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


@defop
def householder_product(x, tau):
    m, n = x.shape[-2], x.shape[-1]
    eye = jnp.eye(m, dtype=x.dtype)

    def body(i, q):
        v = jnp.where(jnp.arange(m) < i, 0.0, x[..., :, i]).at[i].set(1.0)
        h = eye - tau[..., i] * jnp.outer(v, v)
        return q @ h

    q = eye
    for i in range(n):
        q = body(i, q)
    return q[..., :, :n]


@defop
def pca_lowrank(x, q=None, center=True, niter=2):
    if center:
        x = x - jnp.mean(x, axis=-2, keepdims=True)
    u, s, v = jnp.linalg.svd(x, full_matrices=False)
    k = q or min(6, *x.shape[-2:])
    return u[..., :k], s[..., :k], jnp.swapaxes(v, -1, -2)[..., :k]


@defop
def matrix_exp(x):
    import jax.scipy.linalg as jsl
    return jsl.expm(x)


@defop
def ormqr(x, tau, y, left=True, transpose=False):
    """Apply Q (implicit in geqrf's packed reflectors ``x`` + ``tau``) to
    ``y`` without forming it (LAPACK ormqr semantics): each Householder
    H_i = I - tau_i v_i v_i^T is applied in the order the side/transpose
    combination requires."""
    m = x.shape[-2]
    k = tau.shape[-1]
    rows = jnp.arange(m)

    def reflector(i):
        col = x[:, i]
        return jnp.where(rows == i, 1.0, jnp.where(rows > i, col, 0.0))

    # Q = H_0 H_1 ... H_{k-1}
    # left:  Q y   -> apply H_{k-1} first;  Q^T y -> H_0 first
    # right: y Q   -> apply H_0 first;      y Q^T -> H_{k-1} first
    ascending = (left and transpose) or (not left and not transpose)

    def body(j, acc):
        i = j if ascending else k - 1 - j
        v = reflector(i)
        t = tau[i]
        if left:
            return acc - t * jnp.outer(v, v @ acc)
        return acc - t * jnp.outer(acc @ v, v)

    return jax.lax.fori_loop(0, k, body, y.astype(jnp.promote_types(x.dtype,
                                                                    y.dtype)))


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True):
    """paddle.linalg.lu_unpack — (P, L, U) from lu()'s packed output.

    ``x`` is the packed LU factor, ``y`` the pivot vector from
    :func:`lu` (0-based jax ``lu_factor`` convention: row i swapped
    with y[i], indices starting at 0 — NOT LAPACK getrf's 1-based
    pivots; convert with ``piv - 1`` before calling if you have
    those)."""
    def fn(lu_, piv):
        m, n = lu_.shape[-2], lu_.shape[-1]
        k = min(m, n)
        L = jnp.tril(lu_[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_.dtype)
        U = jnp.triu(lu_[..., :k, :])
        # pivots -> permutation matrix: apply row swaps to identity
        def perm_of(pv):
            perm = jnp.arange(m)
            def body(i, p):
                j = pv[i].astype(jnp.int32)
                pi, pj = p[i], p[j]
                return p.at[i].set(pj).at[j].set(pi)
            return jax.lax.fori_loop(0, pv.shape[0], body, perm)
        if piv.ndim == 1:
            perm = perm_of(piv)
        else:
            perm = jax.vmap(perm_of)(piv.reshape(-1, piv.shape[-1])
                                     ).reshape(*piv.shape[:-1], m)
        P = jax.nn.one_hot(perm, m, dtype=lu_.dtype)
        P = jnp.swapaxes(P, -1, -2)
        return P, L, U
    return apply(fn, x, y, op_name="lu_unpack")


def matrix_transpose(x, name=None):
    """paddle.linalg.matrix_transpose — swap the last two dims."""
    return apply(lambda a: jnp.swapaxes(a, -1, -2),
                 x, op_name="matrix_transpose")


def cholesky_inverse(x, upper=False, name=None):
    """paddle.linalg.cholesky_inverse — inverse of A from its Cholesky
    factor (A = LL^T or U^T U)."""
    def fn(f):
        eye = jnp.eye(f.shape[-1], dtype=f.dtype)
        inv_f = jax.scipy.linalg.solve_triangular(f, eye, lower=not upper)
        inv_ft = jnp.swapaxes(inv_f, -1, -2)    # batched-safe transpose
        return (inv_f @ inv_ft) if upper else (inv_ft @ inv_f)
    return apply(fn, x, op_name="cholesky_inverse")


def lu_solve(b, lu_data, lu_pivots, trans="N", name=None):
    """paddle.linalg.lu_solve — solve A x = b from lu()'s packed factor.

    ``lu_pivots`` must follow the 0-based jax ``lu_factor`` convention
    (as returned by :func:`lu`), not LAPACK getrf's 1-based pivots."""
    if trans not in ("N", "T", "C"):
        raise ValueError(f"lu_solve: trans must be 'N', 'T' or 'C', "
                         f"got {trans!r}")

    def fn(bb, lu_, piv):
        t = {"N": 0, "T": 1, "C": 2}[trans]
        return jax.scipy.linalg.lu_solve((lu_, piv.astype(jnp.int32)),
                                         bb, trans=t)
    return apply(fn, b, lu_data, lu_pivots, op_name="lu_solve")


def vecdot(x, y, axis=-1, name=None):
    """paddle.linalg.vecdot — vector dot product along ``axis`` with
    broadcasting over the remaining dims (first argument conjugated for
    complex inputs, the Array-API contract)."""
    def fn(a, b):
        return (jnp.conj(a) * b).sum(axis=axis)
    return apply(fn, x, y, op_name="vecdot")


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """paddle.linalg.svd_lowrank — randomized low-rank SVD via ``niter``
    subspace (power) iterations (Halko et al., the reference algorithm).
    Returns (U [m, q], S [q], V [n, q])."""
    from ..framework import random as prandom

    def fn(a, *rest):
        b = a - rest[0] if rest else a
        m, n = b.shape[-2], b.shape[-1]
        k = min(int(q), m, n)
        bt = jnp.swapaxes(b, -1, -2)      # batched-safe transpose
        omega = jax.random.normal(prandom.next_key(),
                                  b.shape[:-2] + (n, k), b.dtype)
        y = b @ omega
        for _ in range(int(niter)):
            # re-orthonormalize each subspace iteration: raw power
            # iterations collapse the basis in float32
            q_i, _ = jnp.linalg.qr(y)
            y = b @ (bt @ q_i)
        Q, _ = jnp.linalg.qr(y)
        ub, s, vt = jnp.linalg.svd(jnp.swapaxes(Q, -1, -2) @ b,
                                   full_matrices=False)
        return Q @ ub, s, jnp.swapaxes(vt, -1, -2)
    args = (x,) + ((M,) if M is not None else ())
    return apply(fn, *args, op_name="svd_lowrank")
