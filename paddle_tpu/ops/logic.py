"""Comparison / logical / bitwise ops + search & sort (reference:
``python/paddle/tensor/logic.py``, ``search.py`` — SURVEY.md §2.2)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..autograd.tape import apply, defop
from ..framework.dtype import INT_DTYPE


def _binop(name, fn):
    @defop
    def op(x, y):
        return fn(x, y)
    op.__name__ = op.__qualname__ = name
    return op


equal = _binop("equal", jnp.equal)
not_equal = _binop("not_equal", jnp.not_equal)
greater_than = _binop("greater_than", jnp.greater)
greater_equal = _binop("greater_equal", jnp.greater_equal)
less_than = _binop("less_than", jnp.less)
less_equal = _binop("less_equal", jnp.less_equal)
logical_and = _binop("logical_and", jnp.logical_and)
logical_or = _binop("logical_or", jnp.logical_or)
logical_xor = _binop("logical_xor", jnp.logical_xor)
bitwise_and = _binop("bitwise_and", jnp.bitwise_and)
bitwise_or = _binop("bitwise_or", jnp.bitwise_or)
bitwise_xor = _binop("bitwise_xor", jnp.bitwise_xor)


@defop
def logical_not(x):
    return jnp.logical_not(x)


@defop
def bitwise_not(x):
    return jnp.bitwise_not(x)


@defop
def is_empty(x):
    return jnp.asarray(x.size == 0)


def in_dynamic_mode():
    from ..jit.api import in_to_static_mode
    return not in_to_static_mode()


# -- search / sort ----------------------------------------------------------

@defop
def argmax(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmax(x, axis=axis, keepdims=keepdim if axis is not None else False)
    return out.astype(INT_DTYPE)


@defop
def argmin(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmin(x, axis=axis, keepdims=keepdim if axis is not None else False)
    return out.astype(INT_DTYPE)


@defop
def argsort(x, axis=-1, descending=False, stable=True):
    out = jnp.argsort(x, axis=axis, stable=stable, descending=descending)
    return out.astype(INT_DTYPE)


@defop
def sort(x, axis=-1, descending=False, stable=True):
    out = jnp.sort(x, axis=axis, stable=stable, descending=descending)
    return out


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())

    def fn(a):
        ax = (a.ndim - 1) if axis is None else axis % a.ndim
        moved = jnp.moveaxis(a, ax, -1)
        if largest:
            v, i = jax.lax.top_k(moved, k)
        else:
            v, i = jax.lax.top_k(-moved, k)
            v = -v
        return jnp.moveaxis(v, -1, ax), jnp.moveaxis(i, -1, ax).astype(INT_DTYPE)

    return apply(fn, x, op_name="topk")


@defop
def kthvalue(x, k, axis=-1, keepdim=False):
    v = jnp.sort(x, axis=axis)
    i = jnp.argsort(x, axis=axis).astype(INT_DTYPE)
    taken_v = jnp.take(v, k - 1, axis=axis)
    taken_i = jnp.take(i, k - 1, axis=axis)
    if keepdim:
        taken_v = jnp.expand_dims(taken_v, axis)
        taken_i = jnp.expand_dims(taken_i, axis)
    return taken_v, taken_i


@defop
def mode(x, axis=-1, keepdim=False):
    ax = axis % x.ndim
    moved = jnp.moveaxis(x, ax, -1)  # [..., n]
    eq = jnp.equal(moved[..., :, None], moved[..., None, :])
    counts = jnp.sum(eq, axis=-1)  # [..., n] occurrences of each element
    idx = jnp.argmax(counts, axis=-1).astype(INT_DTYPE)
    vals = jnp.take_along_axis(moved, idx[..., None], axis=-1)[..., 0]
    if keepdim:
        vals = jnp.expand_dims(vals, ax)
        idx = jnp.expand_dims(idx, ax)
    return vals, idx


@defop
def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    side = "right" if right else "left"
    if sorted_sequence.ndim == 1:
        out = jnp.searchsorted(sorted_sequence, values, side=side)
    else:
        out = jax.vmap(lambda s, v: jnp.searchsorted(s, v, side=side))(
            sorted_sequence.reshape(-1, sorted_sequence.shape[-1]),
            values.reshape(-1, values.shape[-1]))
        out = out.reshape(values.shape)
    return out.astype(jnp.int32 if out_int32 else INT_DTYPE)


@defop
def bucketize(x, sorted_sequence, out_int32=False, right=False):
    out = jnp.searchsorted(sorted_sequence, x, side="right" if right else "left")
    return out.astype(jnp.int32 if out_int32 else INT_DTYPE)
