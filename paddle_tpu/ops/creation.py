"""Tensor creation ops (reference: ``python/paddle/tensor/creation.py`` and
``python/paddle/tensor/random.py`` — SURVEY.md §2.2; canonical paths, unverified)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, to_tensor  # noqa: F401  (re-exported)
from ..framework import dtype as dtypes
from ..framework import random as prandom
from ..autograd.tape import apply, defop
from ..framework.dtype import INT_DTYPE


def _dt(dtype, default=None):
    if dtype is None:
        return dtypes.convert_dtype(default) if default else None
    return dtypes.convert_dtype(dtype)


def _shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        shape = [int(shape)]
    return tuple(int(s) for s in shape)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype, dtypes.get_default_dtype())))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype, dtypes.get_default_dtype())))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        dtype = dtypes.get_default_dtype() if isinstance(fill_value, float) else None
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x
    return Tensor(jnp.zeros(x._data.shape, _dt(dtype) or x.dtype))


def ones_like(x, dtype=None, name=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x
    return Tensor(jnp.ones(x._data.shape, _dt(dtype) or x.dtype))


def full_like(x, fill_value, dtype=None, name=None):
    return Tensor(jnp.full(x._data.shape, fill_value, _dt(dtype) or x.dtype))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start = start.item() if isinstance(start, Tensor) else start
    end = end.item() if isinstance(end, Tensor) else end
    step = step.item() if isinstance(step, Tensor) else step
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = (dtypes.get_default_dtype()
                 if any(isinstance(v, float) for v in (start, end, step)) else "int64")
    return Tensor(jnp.arange(start, end, step, _dt(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    start = start.item() if isinstance(start, Tensor) else start
    stop = stop.item() if isinstance(stop, Tensor) else stop
    return Tensor(jnp.linspace(start, stop, int(num), dtype=_dt(dtype, dtypes.get_default_dtype())))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(start, stop, int(num), base=base,
                               dtype=_dt(dtype, dtypes.get_default_dtype())))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype, dtypes.get_default_dtype())))


@defop
def tril(x, diagonal=0):
    return jnp.tril(x, diagonal)


@defop
def triu(x, diagonal=0):
    return jnp.triu(x, diagonal)


@defop
def diag(x, offset=0, padding_value=0):
    if x.ndim == 1 and padding_value != 0:
        d = jnp.diag(x, offset)
        mask = jnp.eye(d.shape[0], dtype=bool) if offset == 0 else \
            jnp.diag(jnp.ones(x.shape[0], dtype=bool), offset)
        return jnp.where(mask, d, jnp.asarray(padding_value, d.dtype))
    return jnp.diag(x, offset)


@defop
def diagflat(x, offset=0):
    return jnp.diagflat(x, offset)


@defop
def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    n = x.shape[-1] + abs(offset)
    out = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    idx = jnp.arange(x.shape[-1])
    r = idx + max(-offset, 0)
    c = idx + max(offset, 0)
    out = out.at[..., r, c].set(x)
    if (dim1, dim2) != (-2, -1):
        out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
    return out


@defop
def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset, axis1, axis2)


def meshgrid(*args, **kwargs):
    arrs = [a._data if isinstance(a, Tensor) else jnp.asarray(a) for a in
            (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    return [Tensor(m) for m in jnp.meshgrid(*arrs, indexing="ij")]


def assign(x, output=None):
    val = x._data if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))
    if output is not None:
        output.set_value(val)
        return output
    return Tensor(val)


def clone(x):
    return x.clone()


# -- random -----------------------------------------------------------------


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype=dtype, min=0.0, max=1.0)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    dt = _dt(dtype, dtypes.get_default_dtype())
    key = prandom.next_key() if not seed else jax.random.key(seed)
    return Tensor(jax.random.uniform(key, _shape(shape), dt, minval=min, maxval=max))


def randn(shape, dtype=None, name=None):
    dt = _dt(dtype, dtypes.get_default_dtype())
    return Tensor(jax.random.normal(prandom.next_key(), _shape(shape), dt))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        sh = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(jax.random.normal(prandom.next_key(), sh) * s + m)
    dt = dtypes.convert_dtype(dtypes.get_default_dtype())
    return Tensor(jax.random.normal(prandom.next_key(), _shape(shape), dt) * std + mean)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(prandom.next_key(), _shape(shape), low, high,
                                     _dt(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    # reference allows float x: integers are sampled, then cast to x.dtype
    dt = dtype or dtypes.dtype_name(x.dtype)
    if dtypes.is_floating(_dt(dt)):   # incl. bfloat16 (np.issubdtype misses it)
        return randint(low, high, x.shape, "int64").astype(dt)
    return randint(low, high, x.shape, dt)


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(prandom.next_key(), n).astype(_dt(dtype)))


def bernoulli(x, name=None):
    p = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.bernoulli(prandom.next_key(), p).astype(p.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    p = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    logits = jnp.log(jnp.maximum(p, 1e-30))
    if replacement:
        out = jax.random.categorical(prandom.next_key(), logits,
                                     shape=p.shape[:-1] + (num_samples,), axis=-1)
    else:
        # Gumbel top-k without replacement
        g = jax.random.gumbel(prandom.next_key(), p.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(INT_DTYPE))


def poisson(x, name=None):
    lam = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.poisson(prandom.next_key(), lam).astype(lam.dtype))


def exponential_(x, lam=1.0, name=None):
    val = jax.random.exponential(prandom.next_key(), x._data.shape).astype(x.dtype) / lam
    return x._replace_(val)


def binomial(count, prob, name=None):
    """paddle.binomial — samples from Binomial(count, prob) per element
    (reference kernel: ``paddle/phi/kernels/cpu/binomial_kernel``)."""
    n = count._data if isinstance(count, Tensor) else jnp.asarray(count)
    p = prob._data if isinstance(prob, Tensor) else jnp.asarray(prob)
    n, p = jnp.broadcast_arrays(n, p)
    return Tensor(jax.random.binomial(
        prandom.next_key(), n.astype(jnp.float32),
        p.astype(jnp.float32)).astype(INT_DTYPE))


def standard_gamma(x, name=None):
    """paddle.standard_gamma — Gamma(alpha=x, scale=1) samples."""
    a = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.gamma(prandom.next_key(), a).astype(a.dtype))


def log_normal(mean=1.0, std=2.0, shape=None, dtype=None, name=None):
    """paddle.log_normal — exp(Normal(mean, std)) of the given shape."""
    shape = [1] if shape is None else list(shape)
    dt = _dt(dtype, "float32")
    z = jax.random.normal(prandom.next_key(), tuple(int(s) for s in shape))
    return Tensor(jnp.exp(mean + std * z).astype(dt))


def polar(abs, angle, name=None):
    """paddle.polar — complex tensor from magnitude + phase."""
    return apply(lambda r, t: jax.lax.complex(r * jnp.cos(t),
                                              r * jnp.sin(t)),
                 abs, angle, op_name="polar")


def vander(x, n=None, increasing=False, name=None):
    def fn(a):
        cols = n if n is not None else a.shape[0]
        out = jnp.vander(a, cols, increasing=increasing)
        return out
    return apply(fn, x, op_name="vander")


def complex(real, imag, name=None):
    """paddle.complex — build a complex tensor from real/imag parts."""
    return apply(lambda r, i: jax.lax.complex(r, i), real, imag,
                 op_name="complex")


def tril_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = jnp.tril_indices(int(row), k=int(offset), m=int(col))
    return Tensor(jnp.stack([r, c]).astype(_dt(dtype, "int64")))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = jnp.triu_indices(int(row), k=int(offset), m=int(col))
    return Tensor(jnp.stack([r, c]).astype(_dt(dtype, "int64")))
