"""Shape/layout manipulation ops (reference: ``python/paddle/tensor/
manipulation.py`` — SURVEY.md §2.2; canonical paths, unverified)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..framework import dtype as dtypes
from ..autograd.tape import apply, defop
from ..framework.dtype import INT_DTYPE


def _static_shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)


def reshape(x, shape, name=None):
    sh = _static_shape(shape)
    return apply(lambda a: jnp.reshape(a, sh), x, op_name="reshape")


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    return x._replace_(out._data, out._grad_node, out._out_idx)


def view(x, shape_or_dtype):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return x.astype(shape_or_dtype)


@defop
def flatten(x, start_axis=0, stop_axis=-1):
    nd = x.ndim
    start = start_axis % nd if nd else 0
    stop = stop_axis % nd if nd else 0
    new_shape = x.shape[:start] + (-1,) + x.shape[stop + 1:]
    return jnp.reshape(x, new_shape)


def squeeze(x, axis=None, name=None):
    ax = None
    if axis is not None:
        axis = axis if isinstance(axis, (list, tuple)) else [axis]
        ax = tuple(a % x.ndim for a in axis if x.shape[a % x.ndim] == 1)
    return apply(lambda a: jnp.squeeze(a, ax), x, op_name="squeeze")


def squeeze_(x, axis=None, name=None):
    out = squeeze(x, axis)
    return x._replace_(out._data, out._grad_node, out._out_idx)


def unsqueeze(x, axis, name=None):
    axis = axis if isinstance(axis, (list, tuple)) else [axis]
    axis = tuple(int(a.item()) if isinstance(a, Tensor) else int(a) for a in axis)
    return apply(lambda a: jnp.expand_dims(a, axis), x, op_name="unsqueeze")


def unsqueeze_(x, axis, name=None):
    out = unsqueeze(x, axis)
    return x._replace_(out._data, out._grad_node, out._out_idx)


def transpose(x, perm, name=None):
    perm = tuple(int(p) for p in perm)
    return apply(lambda a: jnp.transpose(a, perm), x, op_name="transpose")


@defop
def moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


@defop
def swapaxes(x, axis0, axis1):
    return jnp.swapaxes(x, axis0, axis1)


def concat(x, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    tensors = list(x)
    return apply(lambda *ts: jnp.concatenate(ts, axis=axis), *tensors, op_name="concat")


def stack(x, axis=0, name=None):
    tensors = list(x)
    return apply(lambda *ts: jnp.stack(ts, axis=axis), *tensors, op_name="stack")


def hstack(x):
    return apply(lambda *ts: jnp.hstack(ts), *list(x), op_name="hstack")


def vstack(x):
    return apply(lambda *ts: jnp.vstack(ts), *list(x), op_name="vstack")


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    dim = x.shape[axis % x.ndim] if hasattr(x, "ndim") else None
    if isinstance(num_or_sections, int):
        n = num_or_sections
        if dim % n != 0:
            raise ValueError(
                f"split: axis dim {dim} is not divisible by num {n}")
        sizes = [dim // n] * n
    else:
        sizes = [int(s) for s in num_or_sections]
        if any(s == -1 for s in sizes):
            rest = dim - builtins_sum(s for s in sizes if s != -1)
            sizes = [rest if s == -1 else s for s in sizes]
    offsets = np.cumsum([0] + sizes[:-1]).tolist()

    def fn(a):
        return tuple(jax.lax.slice_in_dim(a, o, o + s, axis=axis % a.ndim)
                     for o, s in zip(offsets, sizes))

    return list(apply(fn, x, op_name="split"))


def builtins_sum(it):
    import builtins
    return builtins.sum(it)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0):
    n = x.shape[axis % x.ndim]

    def fn(a):
        return tuple(jnp.squeeze(jax.lax.slice_in_dim(a, i, i + 1, axis=axis % a.ndim),
                                 axis % a.ndim) for i in range(n))

    return list(apply(fn, x, op_name="unbind"))


def unstack(x, axis=0, num=None):
    return unbind(x, axis)


@defop
def tile(x, repeat_times):
    rt = tuple(int(r) for r in repeat_times)
    if len(rt) > x.ndim:
        x = jnp.reshape(x, (1,) * (len(rt) - x.ndim) + x.shape)
    return jnp.tile(x, rt)


def expand(x, shape, name=None):
    sh = _static_shape(shape)
    sh = tuple(x.shape[i - (len(sh) - x.ndim)] if s == -1 else s for i, s in enumerate(sh))
    return apply(lambda a: jnp.broadcast_to(a, sh), x, op_name="expand")


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs):
    arrs = [t._data for t in inputs]
    sh = jnp.broadcast_shapes(*[a.shape for a in arrs])
    return [expand(t, sh) for t in inputs]


@defop
def flip(x, axis):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return jnp.flip(x, ax)


def rot90(x, k=1, axes=(0, 1)):
    return apply(lambda a: jnp.rot90(a, k, axes), x, op_name="rot90")


@defop
def roll(x, shifts, axis=None):
    sh = tuple(shifts) if isinstance(shifts, (list, tuple)) else shifts
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.roll(x, sh, ax)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        repeats = repeats._data
        total = int(repeats.sum())
        return apply(lambda a: jnp.repeat(a, repeats, axis=axis, total_repeat_length=total),
                     x, op_name="repeat_interleave")
    return apply(lambda a: jnp.repeat(a, repeats, axis=axis), x, op_name="repeat_interleave")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]
    nd = x.ndim
    if len(pad) == 2 * nd:
        width = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle convention: pad applies to the last len(pad)//2 spatial dims,
        # ordered from the last dim backwards: [left, right, top, bottom, ...]
        width = [(0, 0)] * nd
        np_ = len(pad) // 2
        for i in range(np_):
            width[nd - 1 - i] = (pad[2 * i], pad[2 * i + 1])
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]
    kw = {"constant_values": value} if jmode == "constant" else {}
    return apply(lambda a: jnp.pad(a, width, mode=jmode, **kw), x, op_name="pad")


def cast(x, dtype):
    return x.astype(dtype)


def numel(x):
    return Tensor(jnp.asarray(x.size, INT_DTYPE))


@defop
def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@defop
def as_complex(x):
    return jax.lax.complex(x[..., 0], x[..., 1])


def tolist(x):
    return x.tolist()


def tensordot(x, y, axes=2):
    return apply(lambda a, b: jnp.tensordot(a, b, axes=axes), x, y, op_name="tensordot")


@defop
def take_along_axis(arr, indices, axis, broadcast=True):
    idx = indices
    if broadcast:
        dst = list(arr.shape)
        dst[axis] = idx.shape[axis]
        idx = jnp.broadcast_to(idx, tuple(dst))
    return jnp.take_along_axis(arr, idx, axis=axis)


@defop
def put_along_axis(arr, indices, values, axis, reduce="assign", include_self=True,
                   broadcast=True):
    vals = jnp.broadcast_to(jnp.asarray(values, arr.dtype), indices.shape) \
        if not hasattr(values, "shape") or values.shape != indices.shape else values
    if reduce == "assign":
        return jnp.put_along_axis(arr, indices, vals, axis=axis, inplace=False)
    idx = [jnp.arange(s).reshape([-1 if i == d else 1 for i in range(arr.ndim)])
           for d, s in enumerate(indices.shape)]
    idx[axis] = indices
    if reduce in ("add", "sum"):
        return arr.at[tuple(idx)].add(vals)
    if reduce in ("mul", "multiply"):
        return arr.at[tuple(idx)].multiply(vals)
    if reduce == "amax":
        return arr.at[tuple(idx)].max(vals)
    if reduce == "amin":
        return arr.at[tuple(idx)].min(vals)
    raise ValueError(f"unknown reduce {reduce}")


@defop
def index_select(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


@defop
def index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


@defop
def gather(x, index, axis=0):
    return jnp.take(x, index.reshape(-1) if index.ndim > 1 else index, axis=axis)


@defop
def gather_nd(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


@defop
def scatter(x, index, updates, overwrite=True):
    if overwrite:
        return x.at[index].set(updates)
    # paddle: overwrite=False means accumulate — but zero out first occurrence sems:
    zeroed = x.at[index].set(jnp.zeros_like(updates))
    return zeroed.at[index].add(updates)


@defop
def scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


def scatter_nd(index, updates, shape):
    z = Tensor(jnp.zeros(_static_shape(shape), updates.dtype))
    return scatter_nd_add(z, index, updates)


@defop
def index_add(x, index, axis, value):
    # NB: module-level ``slice`` op shadows the builtin here
    idx = [builtins_slice(None)] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].add(value)


@defop
def index_put(x, indices, value, accumulate=False):
    idx = tuple(i for i in indices)
    return x.at[idx].add(value) if accumulate else x.at[idx].set(value)


@defop
def masked_select(x, mask):
    # dynamic-shaped output: eager-only op (cannot jit); fine for API parity
    return x[mask]


@defop
def masked_fill(x, mask, value):
    v = value if not hasattr(value, "shape") else value
    return jnp.where(mask, v, x)


@defop
def masked_scatter(x, mask, value):
    flat_val = value.reshape(-1)
    cnt = jnp.cumsum(mask.reshape(-1).astype(jnp.int32)) - 1
    gathered = flat_val[jnp.clip(cnt, 0, flat_val.shape[0] - 1)].reshape(x.shape)
    return jnp.where(mask, gathered, x)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply(lambda c, a, b: jnp.where(c, a, b), condition, x, y, op_name="where")


def nonzero(x, as_tuple=False):
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    nz = jnp.nonzero(arr)  # eager-only (dynamic shape)
    if as_tuple:
        return tuple(Tensor(n.reshape(-1, 1).astype(INT_DTYPE)) for n in nz)
    return Tensor(jnp.stack(nz, axis=1).astype(INT_DTYPE))


def slice(input, axes, starts, ends):
    idx = [builtins_slice(None)] * input.ndim
    for ax, st, en in zip(axes, starts, ends):
        st = int(st.item()) if isinstance(st, Tensor) else int(st)
        en = int(en.item()) if isinstance(en, Tensor) else int(en)
        idx[ax] = builtins_slice(st, en)
    return apply(lambda a: a[tuple(idx)], input, op_name="slice")


def builtins_slice(*args):
    import builtins
    return builtins.slice(*args)


def strided_slice(x, axes, starts, ends, strides):
    idx = [builtins_slice(None)] * x.ndim
    for ax, st, en, sr in zip(axes, starts, ends, strides):
        idx[ax] = builtins_slice(int(st), int(en), int(sr))
    return apply(lambda a: a[tuple(idx)], x, op_name="strided_slice")


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def fn(a):
        size = (index_num + nshards - 1) // nshards
        lo = shard_id * size
        in_shard = (a >= lo) & (a < lo + size)
        return jnp.where(in_shard, a - lo, ignore_value)
    return apply(fn, input, op_name="shard_index")


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None):
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    res = jnp.unique(arr, return_index=return_index, return_inverse=return_inverse,
                     return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(res)
    return tuple(Tensor(r) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None):
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    if axis is not None:
        raise NotImplementedError
    flat = arr.reshape(-1)
    keep = np.ones(flat.shape[0], dtype=bool)
    keep[1:] = flat[1:] != flat[:-1]
    out = [Tensor(flat[keep])]
    if return_inverse:
        out.append(Tensor(np.cumsum(keep) - 1))
    if return_counts:
        idx = np.flatnonzero(keep)
        out.append(Tensor(np.diff(np.append(idx, flat.shape[0]))))
    return out[0] if len(out) == 1 else tuple(out)


def one_hot(x, num_classes, name=None):
    return apply(lambda a: jax.nn.one_hot(a, num_classes, dtype=jnp.float32),
                 x, op_name="one_hot")


def permute(x, *perm, name=None):
    """torch-style alias of transpose(perm)."""
    if len(perm) == 1 and isinstance(perm[0], (list, tuple)):
        perm = tuple(perm[0])
    return transpose(x, list(perm))


# ---------------------------------------------------------------------------
# breadth batch (round 2): reference python/paddle/tensor/manipulation.py
# ---------------------------------------------------------------------------

def _atleast(nd):
    def go(*inputs, name=None):
        fns = {1: jnp.atleast_1d, 2: jnp.atleast_2d, 3: jnp.atleast_3d}
        outs = [apply(fns[nd], t, op_name=f"atleast_{nd}d") for t in inputs]
        return outs[0] if len(outs) == 1 else outs
    go.__name__ = f"atleast_{nd}d"
    return go


atleast_1d = _atleast(1)
atleast_2d = _atleast(2)
atleast_3d = _atleast(3)


def column_stack(x, name=None):
    return apply(lambda *ts: jnp.column_stack(ts), *x, op_name="column_stack")


def row_stack(x, name=None):
    return apply(lambda *ts: jnp.vstack(ts), *x, op_name="row_stack")


def dstack(x, name=None):
    return apply(lambda *ts: jnp.dstack(ts), *x, op_name="dstack")


def hsplit(x, num_or_indices, name=None):
    return apply(lambda a: tuple(jnp.hsplit(a, num_or_indices)), x,
                 op_name="hsplit")


def vsplit(x, num_or_indices, name=None):
    return apply(lambda a: tuple(jnp.vsplit(a, num_or_indices)), x,
                 op_name="vsplit")


def dsplit(x, num_or_indices, name=None):
    return apply(lambda a: tuple(jnp.dsplit(a, num_or_indices)), x,
                 op_name="dsplit")


def tensor_split(x, num_or_indices, axis=0, name=None):
    return apply(lambda a: tuple(jnp.array_split(a, num_or_indices,
                                                 axis=axis)), x,
                 op_name="tensor_split")


def unflatten(x, axis, shape, name=None):
    def fn(a):
        ax = axis % a.ndim
        sh = list(a.shape[:ax]) + [int(s) for s in shape] + list(a.shape[ax + 1:])
        return a.reshape(sh)
    return apply(fn, x, op_name="unflatten")


def block_diag(inputs, name=None):
    def fn(*ts):
        import jax.scipy.linalg as jsl
        return jsl.block_diag(*[jnp.atleast_2d(t) for t in ts])
    return apply(fn, *inputs, op_name="block_diag")


@defop
def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1):
    # normalize the diagonal plane to the LAST two dims so the advanced
    # indices stay adjacent (arbitrary axis pairs, ndim >= 2)
    a1, a2 = axis1 % x.ndim, axis2 % x.ndim
    xm = jnp.moveaxis(x, (a1, a2), (-2, -1))
    idx = jnp.arange(y.shape[-1])
    i1 = idx + (-offset if offset < 0 else 0)
    i2 = idx + (offset if offset > 0 else 0)
    xm = xm.at[..., i1, i2].set(y)
    return jnp.moveaxis(xm, (-2, -1), (a1, a2))


@defop
def select_scatter(x, values, axis, index):
    indexer = [builtins_slice(None)] * x.ndim
    indexer[axis % x.ndim] = index
    return x.at[tuple(indexer)].set(values)


@defop
def slice_scatter(x, value, axes, starts, ends, strides=None):
    strides = strides or [1] * len(axes)
    indexer = [builtins_slice(None)] * x.ndim
    for ax, st, en, sr in zip(axes, starts, ends, strides):
        indexer[ax] = builtins_slice(int(st), int(en), int(sr))
    return x.at[tuple(indexer)].set(value)


@defop
def index_fill(x, index, axis, value):
    indexer = [builtins_slice(None)] * x.ndim
    indexer[axis % x.ndim] = index
    v = value._data if hasattr(value, "_data") else value
    return x.at[tuple(indexer)].set(jnp.asarray(v, x.dtype))


@defop
def unfold(x, axis, size, step):
    """Tensor.unfold — sliding windows of ``size`` every ``step`` along
    ``axis``; window becomes a trailing dim (reference
    ``python/paddle/tensor/manipulation.py`` unfold)."""
    ax = int(axis) % x.ndim
    n = (x.shape[ax] - size) // step + 1
    starts = jnp.arange(n) * step
    win = jnp.arange(size)
    idx = starts[:, None] + win[None, :]          # [n, size]
    out = jnp.take(x, idx.reshape(-1), axis=ax)
    shp = list(x.shape[:ax]) + [n, size] + list(x.shape[ax + 1:])
    out = out.reshape(shp)
    # move the window dim to the end
    return jnp.moveaxis(out, ax + 1, -1)


def rank(x):
    """paddle.rank — 0-D int32 tensor holding ndim."""
    from ..framework.core import Tensor
    nd = x.ndim if hasattr(x, "ndim") else jnp.asarray(x).ndim
    return Tensor(jnp.asarray(nd, jnp.int32))


def shape(x):
    """paddle.shape — 1-D int32 tensor of the (static) shape."""
    from ..framework.core import Tensor
    shp = x.shape if hasattr(x, "shape") else jnp.asarray(x).shape
    return Tensor(jnp.asarray(shp, jnp.int32))


def crop(x, shape=None, offsets=None, name=None):
    """paddle.crop — slice a region of ``shape`` at ``offsets`` (negative
    shape entries keep the remaining extent, like the reference)."""
    xs = list(x.shape)
    if shape is None:
        shape = xs
    if hasattr(shape, "tolist"):
        shape = shape.tolist()
    if offsets is None:
        offsets = [0] * len(xs)
    if hasattr(offsets, "tolist"):
        offsets = offsets.tolist()
    if len(shape) != len(xs) or len(offsets) != len(xs):
        raise ValueError(
            f"crop: shape/offsets rank {len(shape)}/{len(offsets)} must "
            f"equal input rank {len(xs)}")
    starts = [int(o) for o in offsets]
    sizes = [int(xs[i] - starts[i]) if int(s) == -1 else int(s)
             for i, s in enumerate(shape)]
    for i, (st, sz) in enumerate(zip(starts, sizes)):
        if st < 0 or sz < 0 or st + sz > xs[i]:
            raise ValueError(
                f"crop: dim {i} region [{st}, {st + sz}) out of bounds "
                f"for extent {xs[i]}")

    def fn(a):
        idx = tuple(builtins_slice(st, st + sz)
                    for st, sz in zip(starts, sizes))
        return a[idx]

    return apply(fn, x, op_name="crop")


@defop
def fliplr(x):
    return jnp.fliplr(x)


@defop
def flipud(x):
    return jnp.flipud(x)


@defop
def index_copy(x, index, axis, value):
    idx = [builtins_slice(None)] * x.ndim
    idx[axis % x.ndim] = index
    return x.at[tuple(idx)].set(value)


def view(x, shape_or_dtype, name=None):
    """paddle.view — reshape (list/tuple) or dtype reinterpretation.

    Dtype views follow the reference shape rule: the LAST dim rescales by
    the byte-width ratio (f32 (2,6) viewed as f16 -> (2,12); f16 (2,6)
    viewed as f32 -> (2,3)), unlike raw lax.bitcast_convert_type which
    appends/consumes a trailing ratio dim."""
    from ..framework import dtype as dtypes
    import numpy as np
    if isinstance(shape_or_dtype, (list, tuple)):
        return apply(lambda a: a.reshape(tuple(int(s)
                                               for s in shape_or_dtype)),
                     x, op_name="view")
    dt = dtypes.convert_dtype(shape_or_dtype)

    def fn(a):
        src = np.dtype(a.dtype).itemsize
        dst = np.dtype(dt).itemsize
        if src == dst:
            return jax.lax.bitcast_convert_type(a, dt)
        if src > dst:                      # narrowing: split last dim
            out = jax.lax.bitcast_convert_type(a, dt)   # (..., n, r)
            return out.reshape(a.shape[:-1] + (a.shape[-1] * (src // dst),))
        r = dst // src                     # widening: fold last dim
        if a.shape[-1] % r:
            raise ValueError(
                f"view: last dim {a.shape[-1]} not divisible by the "
                f"byte-width ratio {r}")
        packed = a.reshape(a.shape[:-1] + (a.shape[-1] // r, r))
        return jax.lax.bitcast_convert_type(packed, dt)
    return apply(fn, x, op_name="view")


def view_as(x, other, name=None):
    return view(x, list(other.shape))


def as_strided(x, shape, stride, offset=0, name=None):
    """paddle.as_strided — strided view over the flattened buffer
    (gather-based: XLA has no aliasing views, so this materializes)."""
    def fn(a):
        flat = a.reshape(-1)
        idx = jnp.asarray(int(offset))
        for s, st in zip(shape, stride):
            idx = idx[..., None] + jnp.arange(int(s)) * int(st)
        return flat[idx.reshape(-1)].reshape(tuple(int(s) for s in shape))
    return apply(fn, x, op_name="as_strided")


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    """paddle.fill_diagonal_tensor — write ``y`` along the (dim1, dim2)
    diagonal of ``x`` (out-of-place; ``fill_diagonal_tensor_`` mutates)."""
    def fn(a, b):
        n = min(a.shape[dim1], a.shape[dim2] - offset) if offset >= 0 \
            else min(a.shape[dim1] + offset, a.shape[dim2])
        i = jnp.arange(n) + max(-offset, 0)
        j = jnp.arange(n) + max(offset, 0)
        # move the diagonal dims to the front for a single scatter
        moved = jnp.moveaxis(a, (dim1, dim2), (0, 1))
        bm = jnp.moveaxis(b, -1, 0) if b.ndim else b
        upd = moved.at[i, j].set(bm)
        return jnp.moveaxis(upd, (0, 1), (dim1, dim2))
    return apply(fn, x, y, op_name="fill_diagonal_tensor")


def fill_diagonal_tensor_(x, y, offset=0, dim1=0, dim2=1, name=None):
    out = fill_diagonal_tensor(x, y, offset=offset, dim1=dim1, dim2=dim2)
    return x._replace_(out._data if isinstance(out, Tensor) else out)


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    """paddle.Tensor.fill_diagonal_ — fill the (offset) diagonal in
    place. For ndim > 2 the torch/paddle contract fills the
    (i, i, ..., i) hyper-diagonal of an all-equal-dims tensor (offset
    must be 0 there)."""
    a = x._data
    if a.ndim > 2:
        if offset != 0:
            raise ValueError("fill_diagonal_: offset is only supported "
                             "for 2-D tensors")
        if len(set(a.shape)) != 1:
            raise ValueError("fill_diagonal_: ndim>2 needs all dims equal")
        i = jnp.arange(a.shape[0])
        new = a.at[tuple([i] * a.ndim)].set(value)
        return x._replace_(new)
    if a.ndim == 2 and wrap and a.shape[0] > a.shape[1]:
        # torch/paddle wrap semantics: repeat the diagonal every n+1 rows
        rows = jnp.arange(a.shape[0])
        cols = (rows + offset) % (a.shape[1] + 1)
        hit = cols < a.shape[1]
        new = a.at[rows[hit], cols[hit]].set(value)
    else:
        n = min(a.shape[-2] - max(-offset, 0), a.shape[-1] - max(offset, 0))
        i = jnp.arange(n) + max(-offset, 0)
        j = jnp.arange(n) + max(offset, 0)
        new = a.at[..., i, j].set(value)
    return x._replace_(new)
