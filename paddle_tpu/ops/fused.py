"""Fused transformer ops (reference: ``paddle/phi/kernels/fusion/`` —
``fused_rope``, ``fused_rms_norm``, ``fused_swiglu``; Python surface
``paddle.incubate.nn.functional``, SURVEY.md §2.1/§2.2 "Incubate").

TPU-native: each "fused" op is expressed as plain jax.numpy — XLA fuses the
elementwise chains into the surrounding matmuls (SURVEY.md §7.0: the CUDA
fusion tier maps to XLA fusion + Pallas for the rest), so there is nothing to
hand-fuse here except keeping the ops in one traced region.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..autograd.tape import apply


def rope_freqs(head_dim, max_position, base=10000.0, dtype=jnp.float32):
    """Precompute RoPE cos/sin tables of shape [max_position, head_dim]."""
    inv = 1.0 / (base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_position, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)                      # [S, D/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)  # [S, D] (neox layout)
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def _rotate_half(x):
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True):
    """paddle.incubate.nn.functional.fused_rotary_position_embedding.

    q/k/v layout [batch, seq, heads, head_dim]; cos/sin [max_pos, head_dim]
    (or broadcastable). Returns rotated (q, k, v) — entries None where the
    input was None.
    """
    def rot(x, cs, sn, pos):
        if x is None:
            return None
        s = x.shape[1]
        if pos is not None:
            cs = jnp.take(cs, pos, axis=0)      # [b, s, d] or [s, d]
            sn = jnp.take(sn, pos, axis=0)
        else:
            cs, sn = cs[:s], sn[:s]
        cs = jnp.expand_dims(cs, -2)             # [.., s, 1, d]
        sn = jnp.expand_dims(sn, -2)
        while cs.ndim < x.ndim:                  # prepend batch dims
            cs, sn = cs[None], sn[None]
        if use_neox_rotary_style:
            return x * cs + _rotate_half(x) * sn
        # GPT-J interleaved style
        x1, x2 = x[..., ::2], x[..., 1::2]
        c2, s2 = cs[..., ::2], sn[..., ::2]
        o1 = x1 * c2 - x2 * s2
        o2 = x2 * c2 + x1 * s2
        return jnp.stack([o1, o2], axis=-1).reshape(x.shape)

    def fn(*ts):
        it = iter(ts)
        qq = next(it)
        kk = next(it) if k is not None else None
        vv = next(it) if v is not None else None
        return tuple(x for x in (
            rot(qq, cos, sin, position_ids),
            rot(kk, cos, sin, position_ids),
            vv) if x is not None)

    args = [t for t in (q, k, v) if t is not None]
    out = apply(fn, *args, op_name="fused_rope")
    out = list(out) if isinstance(out, (tuple, list)) else [out]
    res = []
    for t in (q, k, v):
        res.append(out.pop(0) if t is not None else None)
    return tuple(res)


# -- fused_swiglu: the worked example for the custom-op extension API
# (utils.register_op — the TPU-native PD_BUILD_OP). fwd returns
# (out, residuals); the hand-written VJP recomputes nothing but the cheap
# sigmoid products (reference: fused_bias_act swiglu backward kernel).

def _swiglu_fwd(a, g):
    s = 1.0 / (1.0 + jnp.exp(-a))
    return jnp.asarray(a * s * g, a.dtype), (a, s, g)


def _swiglu_vjp(res, cot):
    a, s, g = res
    d_silu = s * (1.0 + a * (1.0 - s))        # d/da [a*sigmoid(a)]
    return (jnp.asarray(cot * g * d_silu, a.dtype),
            jnp.asarray(cot * a * s, g.dtype))


_fused_swiglu_op = None


def _swiglu_registered():
    global _fused_swiglu_op
    if _fused_swiglu_op is None:
        from ..utils.custom_op import register_op
        _fused_swiglu_op = register_op(_swiglu_fwd, name="fused_swiglu",
                                       vjp=_swiglu_vjp, override=True)
    return _fused_swiglu_op


def fused_swiglu(x, gate=None):
    """swiglu(x, gate) = silu(x) * gate (paddle.incubate fused_swiglu)."""
    if gate is None:
        x, gate = apply(lambda a: tuple(jnp.split(a, 2, axis=-1)), x,
                        op_name="swiglu_split")
    return _swiglu_registered()(x, gate)


def jax_silu(a):
    return a * (1.0 / (1.0 + jnp.exp(-a)))
