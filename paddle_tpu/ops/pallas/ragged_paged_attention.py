"""Ragged paged attention — ONE kernel for mixed prefill + decode over the
shared paged KV pool (reference: "Ragged Paged Attention", arxiv
2604.15464; ROADMAP item 1 after PR 4's two-program serving tick).

The serving scheduler packs a tick's work into ONE flat token batch:
every decoding slot contributes its single current token, every
mid-prefill slot contributes a span of prompt tokens, and the whole
batch is padded to a bounded bucket size. Each sequence is described by
``(slot, q_start, q_len, context_len)``:

* ``slot``         — row of ``block_tables`` (the sequence's page map);
* ``q_start``      — offset of the sequence's first token in the flat
                     ``q`` batch (``q_starts`` must be non-decreasing);
* ``q_len``        — number of NEW tokens this step (1 for decode);
* ``context_len``  — total context INCLUDING the new tokens, so query
                     ``j`` of the span attends positions
                     ``[0, context_len - q_len + j]`` — causal masking
                     inside the ragged span falls out of the same
                     per-token context bound the decode kernel uses.

Tokens outside every span (bucket padding) attend one garbage key
(page 0 slot 0, the pool's scratch page) and their output is discarded
by the caller — identical to the decode kernel's inactive-slot story.

Three tiers, mirroring ``ops/pallas/paged_attention.py``:

* on real TPU the in-repo kernel is the default once its canary has
  been proven in a disposable subprocess (``utils.guarded_compile``);
* ``PADDLE_TPU_RAGGED_IMPL=xla`` (or an unproven kernel) delegates to a
  plain-XLA gather+softmax fallback — zero Mosaic, wedge-free;
* CPU tests / ``interpret=True`` run the in-repo kernel in interpret
  mode: grid ``(tokens, kv_head, pages)``, block-table-steered dynamic
  BlockSpec index maps (scalar prefetch in SMEM), online-softmax
  scratch accumulation — the decode kernel's streaming recurrence with
  per-TOKEN (not per-row) context bounds and table rows.

Unused block-table entries MUST be 0 (a valid page): their scores are
masked by the per-token context bound but the DMA address must be in
range.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .paged_attention import _CompilerParams, NEG_INF


def _token_descriptors(num_tokens, seq_slots, q_starts, q_lens,
                       context_lens):
    """Expand per-sequence ``(slot, q_start, q_len, context_len)``
    descriptors into the per-token arrays the kernel grid consumes:
    ``tok_slot[t]`` (block-table row) and ``tok_ctx[t]`` (key positions
    visible to token ``t``). Padding tokens — outside every span — get
    ``(slot 0, ctx 1)``: one finite, discarded garbage score instead of
    an all-masked NaN softmax. Pure jnp, so it traces under jit."""
    seq_slots = jnp.asarray(seq_slots, jnp.int32)
    q_starts = jnp.asarray(q_starts, jnp.int32)
    q_lens = jnp.asarray(q_lens, jnp.int32)
    context_lens = jnp.asarray(context_lens, jnp.int32)
    tok = jnp.arange(num_tokens, dtype=jnp.int32)
    nseq = q_starts.shape[0]
    seq_of = jnp.clip(
        jnp.searchsorted(q_starts, tok, side="right").astype(jnp.int32) - 1,
        0, nseq - 1)
    off = tok - q_starts[seq_of]
    valid = (off >= 0) & (off < q_lens[seq_of])
    tok_slot = jnp.where(valid, seq_slots[seq_of], 0)
    tok_ctx = jnp.where(
        valid, context_lens[seq_of] - q_lens[seq_of] + off + 1, 1)
    return tok_slot, tok_ctx


def _ragged_kernel(slots_ref, ctx_ref, tables_ref, q_ref, k_ref, v_ref,
                   o_ref, m_ref, l_ref, acc_ref, *, sm_scale, page_size,
                   pages_per_seq, group):
    t = pl.program_id(0)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    ctx = ctx_ref[t]
    q = q_ref[0, 0].astype(jnp.float32)            # [group, d]
    k = k_ref[0, 0].astype(jnp.float32)            # [page_size, d]
    v = v_ref[0, 0].astype(jnp.float32)
    # s[g, ps] — one plain 2-D MXU dot per (token, head, page)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale
    pos = p * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(pos < ctx, s, NEG_INF)

    m_prev = m_ref[...][:, :1]                     # [g, 1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    w = jnp.exp(s - m_new)                         # masked -> 0
    corr = jnp.exp(m_prev - m_new)
    l_new = l_ref[...][:, :1] * corr + jnp.sum(w, -1, keepdims=True)
    pv = jax.lax.dot_general(                      # [g, d]
        w, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    acc_ref[...] = acc_ref[...] * corr + pv
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(p == pages_per_seq - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...][:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _ragged_kernel_quant(slots_ref, ctx_ref, tables_ref, q_ref, k_ref,
                         v_ref, ks_ref, vs_ref, o_ref, m_ref, l_ref,
                         acc_ref, *, sm_scale, page_size, pages_per_seq,
                         group):
    """int8-KV variant of :func:`_ragged_kernel`: page blocks arrive as
    int8 rows plus one fp32 scale per (page, slot) row, dequantized in
    VMEM right before the MXU dots — fp32 pages never exist in HBM."""
    t = pl.program_id(0)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    ctx = ctx_ref[t]
    q = q_ref[0, 0].astype(jnp.float32)            # [group, d]
    k = k_ref[0, 0].astype(jnp.float32) * ks_ref[0, 0][:, None]
    v = v_ref[0, 0].astype(jnp.float32) * vs_ref[0, 0][:, None]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale
    pos = p * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(pos < ctx, s, NEG_INF)

    m_prev = m_ref[...][:, :1]                     # [g, 1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    w = jnp.exp(s - m_new)                         # masked -> 0
    corr = jnp.exp(m_prev - m_new)
    l_new = l_ref[...][:, :1] * corr + jnp.sum(w, -1, keepdims=True)
    pv = jax.lax.dot_general(                      # [g, d]
        w, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    acc_ref[...] = acc_ref[...] * corr + pv
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(p == pages_per_seq - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...][:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _ragged_paged_attention_pallas_quant(q, k_pages, v_pages, k_scales,
                                         v_scales, block_tables, tok_slot,
                                         tok_ctx, *, sm_scale, interpret):
    tokens, heads, d = q.shape
    kv_heads, _, page_size, _ = k_pages.shape
    pages_per_seq = block_tables.shape[1]
    group = heads // kv_heads
    qg = q.reshape(tokens, kv_heads, group, d)

    kernel = functools.partial(
        _ragged_kernel_quant, sm_scale=sm_scale, page_size=page_size,
        pages_per_seq=pages_per_seq, group=group)
    page_spec = pl.BlockSpec((1, 1, page_size, d),
                             lambda t, h, p, slot, ctx, tbl:
                             (h, tbl[slot[t], p], 0, 0))
    scale_spec = pl.BlockSpec((1, 1, page_size),
                              lambda t, h, p, slot, ctx, tbl:
                              (h, tbl[slot[t], p], 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(tokens, kv_heads, pages_per_seq),
        in_specs=[
            pl.BlockSpec((1, 1, group, d),
                         lambda t, h, p, slot, ctx, tbl: (t, h, 0, 0)),
            page_spec, page_spec, scale_spec, scale_spec,
        ],
        out_specs=pl.BlockSpec((1, 1, group, d),
                               lambda t, h, p, slot, ctx, tbl: (t, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((tokens, kv_heads, group, d),
                                       q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(tok_slot, jnp.int32), jnp.asarray(tok_ctx, jnp.int32),
      jnp.asarray(block_tables, jnp.int32), qg, k_pages, v_pages,
      jnp.asarray(k_scales, jnp.float32), jnp.asarray(v_scales, jnp.float32))
    return out.reshape(tokens, heads, d)


def _ragged_paged_attention_pallas(q, k_pages, v_pages, block_tables,
                                   tok_slot, tok_ctx, *, sm_scale,
                                   interpret):
    tokens, heads, d = q.shape
    kv_heads, _, page_size, _ = k_pages.shape
    pages_per_seq = block_tables.shape[1]
    group = heads // kv_heads
    qg = q.reshape(tokens, kv_heads, group, d)

    kernel = functools.partial(
        _ragged_kernel, sm_scale=sm_scale, page_size=page_size,
        pages_per_seq=pages_per_seq, group=group)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(tokens, kv_heads, pages_per_seq),
        in_specs=[
            pl.BlockSpec((1, 1, group, d),
                         lambda t, h, p, slot, ctx, tbl: (t, h, 0, 0)),
            pl.BlockSpec((1, 1, page_size, d),
                         lambda t, h, p, slot, ctx, tbl:
                         (h, tbl[slot[t], p], 0, 0)),
            pl.BlockSpec((1, 1, page_size, d),
                         lambda t, h, p, slot, ctx, tbl:
                         (h, tbl[slot[t], p], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, group, d),
                               lambda t, h, p, slot, ctx, tbl: (t, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((tokens, kv_heads, group, d),
                                       q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(tok_slot, jnp.int32), jnp.asarray(tok_ctx, jnp.int32),
      jnp.asarray(block_tables, jnp.int32), qg, k_pages, v_pages)
    return out.reshape(tokens, heads, d)


def _ragged_paged_attention_xla(q, k_pages, v_pages, block_tables,
                                tok_slot, tok_ctx, *, sm_scale,
                                k_scales=None, v_scales=None):
    """Vectorized jittable XLA tier: gather each token's sequence pages
    as dense KV (dequantized when int8 row scales are given), then
    masked softmax-attention. O(tokens * S_max) HBM — trades the
    kernel's memory win for wedge-free compiles."""
    kv_heads, _, page_size, d = k_pages.shape
    tokens, heads, _ = q.shape
    group = heads // kv_heads
    tbl = jnp.asarray(block_tables, jnp.int32)[jnp.asarray(tok_slot,
                                                           jnp.int32)]
    kg, vg = k_pages[:, tbl], v_pages[:, tbl]
    if k_scales is not None:
        kg = kg.astype(jnp.float32) * k_scales[:, tbl][..., None]
        vg = vg.astype(jnp.float32) * v_scales[:, tbl][..., None]
    # [kv, tokens, pages, slot, d] -> [tokens, kv, S, d]
    ks = jnp.moveaxis(kg, 1, 0).reshape(tokens, kv_heads, -1, d)
    vs = jnp.moveaxis(vg, 1, 0).reshape(tokens, kv_heads, -1, d)
    qb = (q * sm_scale).reshape(tokens, kv_heads, group, d)
    s = jnp.einsum("tkgd,tksd->tkgs", qb.astype(jnp.float32),
                   ks.astype(jnp.float32))
    valid = (jnp.arange(ks.shape[2])[None, :]
             < jnp.asarray(tok_ctx, jnp.int32)[:, None])
    s = jnp.where(valid[:, None, None, :], s, -jnp.inf)
    w = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("tkgs,tksd->tkgd", w, vs.astype(jnp.float32))
    return o.reshape(tokens, heads, d).astype(q.dtype)


def ragged_paged_attention(q, k_pages, v_pages, block_tables, seq_slots,
                           q_starts, q_lens, context_lens, *,
                           sm_scale=None, k_scales=None, v_scales=None,
                           interpret=False):
    """Mixed prefill+decode attention over a shared paged KV cache.

    q               [tokens, heads, head_dim] — the flat packed batch
    k_pages/v_pages [kv_heads, num_pages, page_size, head_dim]
    block_tables    [slots, pages_per_seq] int32 (unused entries = 0)
    seq_slots       [nseq] int32 — block-table row per sequence
    q_starts        [nseq] int32 — NON-DECREASING span offsets into q
    q_lens          [nseq] int32 — span length (1 = decode; a
                    speculative verify span is the current token plus k
                    drafted tokens, q_len = k+1)
    context_lens    [nseq] int32 — total context incl. this span
    k_scales/v_scales [kv_heads, num_pages, page_size] f32 — per-row
                    dequant scales for int8 pages (None = native pages)
    -> [tokens, heads, head_dim]; rows outside every span are garbage.
    """
    tokens, heads, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    tok_slot, tok_ctx = _token_descriptors(tokens, seq_slots, q_starts,
                                           q_lens, context_lens)
    if k_scales is not None:
        # int8 KV pages: same wedge-proof ladder, own canary — the quant
        # kernel's Mosaic lowering (int8 loads + row-scale multiplies)
        # is distinct from the native kernel's proven one.
        if not interpret and jax.default_backend() == "tpu":
            import os
            impl = os.environ.get("PADDLE_TPU_RAGGED_IMPL", "auto").lower()
            if impl != "xla":
                from ...utils.guarded_compile import kernel_allowed
                if impl == "inrepo" or kernel_allowed(
                        "ragged_paged_attention_int8",
                        "int8-KV ragged paged attention kernel",
                        fallback="the XLA dequant-gather tier"):
                    return _ragged_paged_attention_pallas_quant(
                        q, k_pages, v_pages, k_scales, v_scales,
                        block_tables, tok_slot, tok_ctx,
                        sm_scale=sm_scale, interpret=False)
            return _ragged_paged_attention_xla(
                q, k_pages, v_pages, block_tables, tok_slot, tok_ctx,
                sm_scale=sm_scale, k_scales=k_scales, v_scales=v_scales)
        return _ragged_paged_attention_pallas_quant(
            q, k_pages, v_pages, k_scales, v_scales, block_tables,
            tok_slot, tok_ctx, sm_scale=sm_scale, interpret=interpret)
    if not interpret and jax.default_backend() == "tpu":
        # Impl choice on real TPU: same wedge-proof ladder as
        # paged_attention — the in-repo kernel only after its canary is
        # proven in a disposable subprocess; otherwise zero-Mosaic XLA.
        import os
        impl = os.environ.get("PADDLE_TPU_RAGGED_IMPL", "auto").lower()
        if impl != "xla":
            from ...utils.guarded_compile import kernel_allowed
            if impl == "inrepo" or kernel_allowed(
                    "ragged_paged_attention", "ragged paged attention kernel",
                    fallback="the XLA gather-attention tier"):
                return _ragged_paged_attention_pallas(
                    q, k_pages, v_pages, block_tables, tok_slot, tok_ctx,
                    sm_scale=sm_scale, interpret=False)
        return _ragged_paged_attention_xla(
            q, k_pages, v_pages, block_tables, tok_slot, tok_ctx,
            sm_scale=sm_scale)
    return _ragged_paged_attention_pallas(
        q, k_pages, v_pages, block_tables, tok_slot, tok_ctx,
        sm_scale=sm_scale, interpret=interpret)


def ragged_paged_attention_reference(q, k_pages, v_pages, block_tables,
                                     seq_slots, q_starts, q_lens,
                                     context_lens):
    """Dense numpy-style oracle: per sequence, gather its context from
    the pages and run plain causal softmax attention for its span. Rows
    outside every span are zero."""
    import numpy as np

    tokens, heads, d = q.shape
    kv_heads, _, page_size, _ = k_pages.shape
    group = heads // kv_heads
    out = np.zeros((tokens, heads, d), np.float32)
    tbl = np.asarray(block_tables)
    for i in range(len(np.asarray(seq_slots))):
        slot = int(np.asarray(seq_slots)[i])
        qs = int(np.asarray(q_starts)[i])
        ql = int(np.asarray(q_lens)[i])
        ctx = int(np.asarray(context_lens)[i])
        n_pages = -(-ctx // page_size)
        ks = jnp.concatenate([k_pages[:, int(tbl[slot, p])]
                              for p in range(n_pages)], axis=1)[:, :ctx]
        vs = jnp.concatenate([v_pages[:, int(tbl[slot, p])]
                              for p in range(n_pages)], axis=1)[:, :ctx]
        for j in range(ql):
            vis = ctx - ql + j + 1                 # causal inside the span
            qb = q[qs + j].reshape(kv_heads, group, d).astype(jnp.float32)
            s = jnp.einsum("kgd,ksd->kgs", qb,
                           ks[:, :vis].astype(jnp.float32)) / math.sqrt(d)
            w = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("kgs,ksd->kgd", w,
                           vs[:, :vis].astype(jnp.float32))
            out[qs + j] = np.asarray(o.reshape(heads, d))
    return jnp.asarray(out).astype(q.dtype)
