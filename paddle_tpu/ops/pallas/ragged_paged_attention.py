"""Ragged paged attention — ONE kernel for mixed prefill + decode over the
shared paged KV pool (reference: "Ragged Paged Attention", arxiv
2604.15464; ROADMAP item 1 after PR 4's two-program serving tick).

The serving scheduler packs a tick's work into ONE flat token batch:
every decoding slot contributes its single current token, every
mid-prefill slot contributes a span of prompt tokens, and the whole
batch is padded to a bounded bucket size. Each sequence is described by
``(slot, q_start, q_len, context_len)``:

* ``slot``         — row of ``block_tables`` (the sequence's page map);
* ``q_start``      — offset of the sequence's first token in the flat
                     ``q`` batch (``q_starts`` must be non-decreasing);
* ``q_len``        — number of NEW tokens this step (1 for decode);
* ``context_len``  — total context INCLUDING the new tokens, so query
                     ``j`` of the span attends positions
                     ``[0, context_len - q_len + j]`` — causal masking
                     inside the ragged span falls out of the same
                     per-token context bound the decode kernel uses.

Tokens outside every span (bucket padding) attend one garbage key
(page 0 slot 0, the pool's scratch page) and their output is discarded
by the caller — identical to the decode kernel's inactive-slot story.

Three tiers, mirroring ``ops/pallas/paged_attention.py``:

* on real TPU an in-repo kernel is the default once its canary has
  been proven in a disposable subprocess (``utils.guarded_compile``);
* ``PADDLE_TPU_RAGGED_IMPL=xla`` (or an unproven kernel) delegates to a
  plain-XLA gather+softmax fallback — zero Mosaic, wedge-free;
* CPU tests / ``interpret=True`` run the in-repo kernels in interpret
  mode: block-table-steered dynamic BlockSpec index maps (scalar
  prefetch in SMEM), online-softmax scratch accumulation — the decode
  kernel's streaming recurrence with per-TOKEN (not per-row) context
  bounds and table rows.

Two in-repo grids. The default **q-block** grid ``(q_blocks, kv_head,
jobs)`` tiles the flat batch into fixed ``PADDLE_TPU_RAGGED_QBLOCK``-row
blocks over the cumulative span offsets and walks a host-built job list
(one (page, owner-slot, kv-offset) per KV page any sequence in the
block needs) — one grid step covers a whole block of tokens against one
page, so a mixed tick runs far fewer, fatter MXU steps. A block may
straddle span boundaries: rows past a span's causal bound mask with
-inf exactly like the per-token kernel, and cross-span keys are steered
out with a finite ``BIG_NEG`` so alien jobs are bitwise no-ops (see
``BIG_NEG``). The historical **per-token** grid ``(tokens, kv_head,
pages)`` remains as the escape hatch (``PADDLE_TPU_RAGGED_IMPL=token``)
and is used automatically under jit tracing, where the q-block
schedule's host-side job build cannot run. The two grids run the SAME
online-softmax recurrence in the same per-row page order — the masking
is an exact no-op on alien jobs, so outputs agree to ~1 ulp (the only
reorder is the dot shape itself: ``[q_block*group, d]`` vs
``[group, d]`` MXU tiles accumulate in different orders) and greedy
token streams through the serving engine are bit-identical.

Unused block-table entries MUST be 0 (a valid page): their scores are
masked by the per-token context bound but the DMA address must be in
range.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .paged_attention import _CompilerParams, NEG_INF

#: finite cross-span mask for the q-block kernel. The causal bound keeps
#: NEG_INF (= -inf, matching the per-token kernel bit for bit on a row's
#: own pages); keys belonging to ANOTHER sequence's job must stay finite:
#: a row whose first visited job is alien would otherwise accumulate
#: m = -inf and hit exp(-inf - -inf) = NaN, which no later correction
#: can wash out. With -1e30, the first own-slot job's rescale factor
#: exp(-1e30 - m_real) underflows to exactly 0.0, erasing the alien
#: garbage bitwise; alien jobs after it are exact no-ops (weights
#: exp(-1e30 - m_real) = 0.0, correction exp(0) = 1.0).
BIG_NEG = -1e30

#: default q-block rows (tokens per grid step); PADDLE_TPU_RAGGED_QBLOCK
DEFAULT_QBLOCK = 8


def _qblock_rows():
    import os
    try:
        qb = int(os.environ.get("PADDLE_TPU_RAGGED_QBLOCK",
                                str(DEFAULT_QBLOCK)))
    except ValueError:
        qb = DEFAULT_QBLOCK
    return max(qb, 1)


def _token_descriptors(num_tokens, seq_slots, q_starts, q_lens,
                       context_lens):
    """Expand per-sequence ``(slot, q_start, q_len, context_len)``
    descriptors into the per-token arrays the kernel grid consumes:
    ``tok_slot[t]`` (block-table row) and ``tok_ctx[t]`` (key positions
    visible to token ``t``). Padding tokens — outside every span — get
    ``(slot 0, ctx 1)``: one finite, discarded garbage score instead of
    an all-masked NaN softmax. Pure jnp, so it traces under jit."""
    seq_slots = jnp.asarray(seq_slots, jnp.int32)
    q_starts = jnp.asarray(q_starts, jnp.int32)
    q_lens = jnp.asarray(q_lens, jnp.int32)
    context_lens = jnp.asarray(context_lens, jnp.int32)
    tok = jnp.arange(num_tokens, dtype=jnp.int32)
    nseq = q_starts.shape[0]
    seq_of = jnp.clip(
        jnp.searchsorted(q_starts, tok, side="right").astype(jnp.int32) - 1,
        0, nseq - 1)
    off = tok - q_starts[seq_of]
    valid = (off >= 0) & (off < q_lens[seq_of])
    tok_slot = jnp.where(valid, seq_slots[seq_of], 0)
    tok_ctx = jnp.where(
        valid, context_lens[seq_of] - q_lens[seq_of] + off + 1, 1)
    return tok_slot, tok_ctx


def qblock_schedule(num_tokens, seq_slots, q_starts, q_lens, context_lens,
                    block_tables, q_block, page_size):
    """Host-side (numpy, concrete-value) schedule for the q-block grid.

    Tiles the flat packed batch into fixed ``q_block``-row blocks over
    the cumulative span offsets and enumerates, per block, the "jobs"
    its grid steps execute: one (physical page, owner slot, kv offset)
    triple per KV page any sequence appearing in the block still needs.
    Pages of one slot are listed ascending, slots in first-appearance
    order, so each row sees its own pages in exactly the per-token
    kernel's order. The job count is padded to a power of two so the
    compiled-program family stays bounded (grid = (blocks, kv_heads, J)
    with J from a small bucket set, vs (tokens, kv_heads, pages)).

    Sentinels: rows past ``num_tokens`` (block padding) get slot -1 /
    ctx 0; padding jobs get slot -2 / page 0. They can never match each
    other, so every row's score matrix keeps at least one finite entry
    (BIG_NEG) and the online softmax never sees an all--inf row.

    Returns ``(row_slot [B*q_block], row_ctx [B*q_block],
    job_page [B, J], job_slot [B, J], job_kv [B, J])`` int32 numpy.
    """
    import numpy as np

    ss = np.asarray(seq_slots, np.int32).reshape(-1)
    qs = np.asarray(q_starts, np.int32).reshape(-1)
    ql = np.asarray(q_lens, np.int32).reshape(-1)
    cl = np.asarray(context_lens, np.int32).reshape(-1)
    tbl = np.asarray(block_tables, np.int32)
    pages_per_seq = tbl.shape[1]
    T = int(num_tokens)
    q_block = max(int(q_block), 1)

    tok = np.arange(T, dtype=np.int32)
    nseq = qs.shape[0]
    seq_of = np.clip(
        np.searchsorted(qs, tok, side="right").astype(np.int32) - 1,
        0, max(nseq - 1, 0))
    off = tok - qs[seq_of]
    valid = (off >= 0) & (off < ql[seq_of])
    ts = np.where(valid, ss[seq_of], 0).astype(np.int32)
    tc = np.where(valid, cl[seq_of] - ql[seq_of] + off + 1, 1).astype(
        np.int32)

    nblocks = -(-T // q_block)
    t_pad = nblocks * q_block
    row_slot = np.full(t_pad, -1, np.int32)
    row_ctx = np.zeros(t_pad, np.int32)
    row_slot[:T] = ts
    row_ctx[:T] = tc
    bs = row_slot.reshape(nblocks, q_block)
    bc = row_ctx.reshape(nblocks, q_block)

    jobs = []
    max_jobs = 1
    for b in range(nblocks):
        block_jobs = []
        seen = []
        for r in range(q_block):
            slot = int(bs[b, r])
            if slot < 0 or slot in seen:
                continue
            seen.append(slot)
            cmax = int(bc[b][bs[b] == slot].max())
            n_pages = min(max(-(-cmax // page_size), 1), pages_per_seq)
            for p in range(n_pages):
                block_jobs.append((int(tbl[slot, p]), slot, p * page_size))
        if not block_jobs:
            block_jobs.append((0, -2, 0))
        jobs.append(block_jobs)
        max_jobs = max(max_jobs, len(block_jobs))

    num_jobs = 1 << (max_jobs - 1).bit_length()
    job_page = np.zeros((nblocks, num_jobs), np.int32)
    job_slot = np.full((nblocks, num_jobs), -2, np.int32)
    job_kv = np.zeros((nblocks, num_jobs), np.int32)
    for b, block_jobs in enumerate(jobs):
        for j, (page, slot, kv) in enumerate(block_jobs):
            job_page[b, j] = page
            job_slot[b, j] = slot
            job_kv[b, j] = kv
    return row_slot, row_ctx, job_page, job_slot, job_kv


def _qblock_masked_scores(s, kv_start, jslot, row_slot, row_ctx):
    """Causal bound with NEG_INF (bitwise the per-token kernel's mask on
    a row's own pages), then the whole row to finite BIG_NEG wherever
    the row's sequence does not own this job's page."""
    pos = kv_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(pos < row_ctx, s, NEG_INF)
    return jnp.where(row_slot == jslot, s, BIG_NEG)


def _qblock_kernel(jp_ref, js_ref, jk_ref, rs_ref, rc_ref, q_ref, k_ref,
                   v_ref, o_ref, m_ref, l_ref, acc_ref, *, sm_scale,
                   num_jobs):
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    jslot = js_ref[b, j]
    jkv = jk_ref[b, j]
    row_slot = rs_ref[0][:, :1]                    # [Qg, 1]
    row_ctx = rc_ref[0][:, :1]
    q = q_ref[0, 0].astype(jnp.float32)            # [Qg, d]
    k = k_ref[0, 0].astype(jnp.float32)            # [page_size, d]
    v = v_ref[0, 0].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale
    s = _qblock_masked_scores(s, jkv, jslot, row_slot, row_ctx)

    m_prev = m_ref[...][:, :1]                     # [Qg, 1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    w = jnp.exp(s - m_new)
    corr = jnp.exp(m_prev - m_new)
    l_new = l_ref[...][:, :1] * corr + jnp.sum(w, -1, keepdims=True)
    pv = jax.lax.dot_general(                      # [Qg, d]
        w, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    acc_ref[...] = acc_ref[...] * corr + pv
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == num_jobs - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...][:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _qblock_kernel_quant(jp_ref, js_ref, jk_ref, rs_ref, rc_ref, q_ref,
                         k_ref, v_ref, ks_ref, vs_ref, o_ref, m_ref,
                         l_ref, acc_ref, *, sm_scale, num_jobs):
    """int8-KV q-block variant: same job walk, pages dequantized from
    int8 rows + per-row fp32 scales right before the MXU dots."""
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    jslot = js_ref[b, j]
    jkv = jk_ref[b, j]
    row_slot = rs_ref[0][:, :1]
    row_ctx = rc_ref[0][:, :1]
    q = q_ref[0, 0].astype(jnp.float32)
    k = k_ref[0, 0].astype(jnp.float32) * ks_ref[0, 0][:, None]
    v = v_ref[0, 0].astype(jnp.float32) * vs_ref[0, 0][:, None]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale
    s = _qblock_masked_scores(s, jkv, jslot, row_slot, row_ctx)

    m_prev = m_ref[...][:, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    w = jnp.exp(s - m_new)
    corr = jnp.exp(m_prev - m_new)
    l_new = l_ref[...][:, :1] * corr + jnp.sum(w, -1, keepdims=True)
    pv = jax.lax.dot_general(
        w, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    acc_ref[...] = acc_ref[...] * corr + pv
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == num_jobs - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...][:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _ragged_paged_attention_pallas_qblock(q, k_pages, v_pages,
                                          block_tables, seq_slots,
                                          q_starts, q_lens, context_lens,
                                          *, sm_scale, interpret,
                                          k_scales=None, v_scales=None,
                                          q_block=None):
    """Q-block tier: grid ``(q_blocks, kv_heads, jobs)`` over the flat
    packed batch — one grid step covers ``q_block`` tokens against one
    KV page, so a mixed prefill+decode tick runs far fewer (and fatter)
    MXU steps than the per-token grid. Requires concrete descriptors
    (the job schedule is built host-side)."""
    import numpy as np

    tokens, heads, d = q.shape
    kv_heads, _, page_size, _ = k_pages.shape
    group = heads // kv_heads
    qb = q_block or _qblock_rows()
    row_slot, row_ctx, job_page, job_slot, job_kv = qblock_schedule(
        tokens, seq_slots, q_starts, q_lens, context_lens, block_tables,
        qb, page_size)
    nblocks, num_jobs = job_page.shape
    t_pad = nblocks * qb
    qg_rows = qb * group

    qp = jnp.pad(q, ((0, t_pad - tokens), (0, 0), (0, 0)))
    qg = qp.reshape(nblocks, qb, kv_heads, group, d).transpose(
        0, 2, 1, 3, 4).reshape(nblocks, kv_heads, qg_rows, d)
    # per-ROW metadata rides as [B, Qg, 128] VMEM lanes so the kernel
    # can slice [:, :1] — the same layout trick the softmax scratch uses
    rows = np.repeat(row_slot.reshape(nblocks, qb), group, axis=1)
    rowc = np.repeat(row_ctx.reshape(nblocks, qb), group, axis=1)
    rs = jnp.asarray(np.broadcast_to(rows[:, :, None],
                                     (nblocks, qg_rows, 128)))
    rc = jnp.asarray(np.broadcast_to(rowc[:, :, None],
                                     (nblocks, qg_rows, 128)))

    quant = k_scales is not None
    kernel = functools.partial(
        _qblock_kernel_quant if quant else _qblock_kernel,
        sm_scale=sm_scale, num_jobs=num_jobs)
    page_spec = pl.BlockSpec((1, 1, page_size, d),
                             lambda b, h, j, jp, js, jk:
                             (h, jp[b, j], 0, 0))
    scale_spec = pl.BlockSpec((1, 1, page_size),
                              lambda b, h, j, jp, js, jk:
                              (h, jp[b, j], 0))
    row_spec = pl.BlockSpec((1, qg_rows, 128),
                            lambda b, h, j, jp, js, jk: (b, 0, 0))
    in_specs = [
        row_spec, row_spec,
        pl.BlockSpec((1, 1, qg_rows, d),
                     lambda b, h, j, jp, js, jk: (b, h, 0, 0)),
        page_spec, page_spec,
    ]
    operands = [rs, rc, qg, k_pages, v_pages]
    if quant:
        in_specs += [scale_spec, scale_spec]
        operands += [jnp.asarray(k_scales, jnp.float32),
                     jnp.asarray(v_scales, jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(nblocks, kv_heads, num_jobs),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, qg_rows, d),
                               lambda b, h, j, jp, js, jk: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((qg_rows, 128), jnp.float32),
            pltpu.VMEM((qg_rows, 128), jnp.float32),
            pltpu.VMEM((qg_rows, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((nblocks, kv_heads, qg_rows, d),
                                       q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(job_page), jnp.asarray(job_slot), jnp.asarray(job_kv),
      *operands)
    out = out.reshape(nblocks, kv_heads, qb, group, d).transpose(
        0, 2, 1, 3, 4).reshape(t_pad, heads, d)
    return out[:tokens]


def _ragged_kernel(slots_ref, ctx_ref, tables_ref, q_ref, k_ref, v_ref,
                   o_ref, m_ref, l_ref, acc_ref, *, sm_scale, page_size,
                   pages_per_seq, group):
    t = pl.program_id(0)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    ctx = ctx_ref[t]
    q = q_ref[0, 0].astype(jnp.float32)            # [group, d]
    k = k_ref[0, 0].astype(jnp.float32)            # [page_size, d]
    v = v_ref[0, 0].astype(jnp.float32)
    # s[g, ps] — one plain 2-D MXU dot per (token, head, page)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale
    pos = p * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(pos < ctx, s, NEG_INF)

    m_prev = m_ref[...][:, :1]                     # [g, 1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    w = jnp.exp(s - m_new)                         # masked -> 0
    corr = jnp.exp(m_prev - m_new)
    l_new = l_ref[...][:, :1] * corr + jnp.sum(w, -1, keepdims=True)
    pv = jax.lax.dot_general(                      # [g, d]
        w, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    acc_ref[...] = acc_ref[...] * corr + pv
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(p == pages_per_seq - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...][:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _ragged_kernel_quant(slots_ref, ctx_ref, tables_ref, q_ref, k_ref,
                         v_ref, ks_ref, vs_ref, o_ref, m_ref, l_ref,
                         acc_ref, *, sm_scale, page_size, pages_per_seq,
                         group):
    """int8-KV variant of :func:`_ragged_kernel`: page blocks arrive as
    int8 rows plus one fp32 scale per (page, slot) row, dequantized in
    VMEM right before the MXU dots — fp32 pages never exist in HBM."""
    t = pl.program_id(0)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    ctx = ctx_ref[t]
    q = q_ref[0, 0].astype(jnp.float32)            # [group, d]
    k = k_ref[0, 0].astype(jnp.float32) * ks_ref[0, 0][:, None]
    v = v_ref[0, 0].astype(jnp.float32) * vs_ref[0, 0][:, None]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale
    pos = p * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(pos < ctx, s, NEG_INF)

    m_prev = m_ref[...][:, :1]                     # [g, 1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    w = jnp.exp(s - m_new)                         # masked -> 0
    corr = jnp.exp(m_prev - m_new)
    l_new = l_ref[...][:, :1] * corr + jnp.sum(w, -1, keepdims=True)
    pv = jax.lax.dot_general(                      # [g, d]
        w, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    acc_ref[...] = acc_ref[...] * corr + pv
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(p == pages_per_seq - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...][:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _ragged_paged_attention_pallas_quant(q, k_pages, v_pages, k_scales,
                                         v_scales, block_tables, tok_slot,
                                         tok_ctx, *, sm_scale, interpret):
    tokens, heads, d = q.shape
    kv_heads, _, page_size, _ = k_pages.shape
    pages_per_seq = block_tables.shape[1]
    group = heads // kv_heads
    qg = q.reshape(tokens, kv_heads, group, d)

    kernel = functools.partial(
        _ragged_kernel_quant, sm_scale=sm_scale, page_size=page_size,
        pages_per_seq=pages_per_seq, group=group)
    page_spec = pl.BlockSpec((1, 1, page_size, d),
                             lambda t, h, p, slot, ctx, tbl:
                             (h, tbl[slot[t], p], 0, 0))
    scale_spec = pl.BlockSpec((1, 1, page_size),
                              lambda t, h, p, slot, ctx, tbl:
                              (h, tbl[slot[t], p], 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(tokens, kv_heads, pages_per_seq),
        in_specs=[
            pl.BlockSpec((1, 1, group, d),
                         lambda t, h, p, slot, ctx, tbl: (t, h, 0, 0)),
            page_spec, page_spec, scale_spec, scale_spec,
        ],
        out_specs=pl.BlockSpec((1, 1, group, d),
                               lambda t, h, p, slot, ctx, tbl: (t, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((tokens, kv_heads, group, d),
                                       q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(tok_slot, jnp.int32), jnp.asarray(tok_ctx, jnp.int32),
      jnp.asarray(block_tables, jnp.int32), qg, k_pages, v_pages,
      jnp.asarray(k_scales, jnp.float32), jnp.asarray(v_scales, jnp.float32))
    return out.reshape(tokens, heads, d)


def _ragged_paged_attention_pallas(q, k_pages, v_pages, block_tables,
                                   tok_slot, tok_ctx, *, sm_scale,
                                   interpret):
    tokens, heads, d = q.shape
    kv_heads, _, page_size, _ = k_pages.shape
    pages_per_seq = block_tables.shape[1]
    group = heads // kv_heads
    qg = q.reshape(tokens, kv_heads, group, d)

    kernel = functools.partial(
        _ragged_kernel, sm_scale=sm_scale, page_size=page_size,
        pages_per_seq=pages_per_seq, group=group)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(tokens, kv_heads, pages_per_seq),
        in_specs=[
            pl.BlockSpec((1, 1, group, d),
                         lambda t, h, p, slot, ctx, tbl: (t, h, 0, 0)),
            pl.BlockSpec((1, 1, page_size, d),
                         lambda t, h, p, slot, ctx, tbl:
                         (h, tbl[slot[t], p], 0, 0)),
            pl.BlockSpec((1, 1, page_size, d),
                         lambda t, h, p, slot, ctx, tbl:
                         (h, tbl[slot[t], p], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, group, d),
                               lambda t, h, p, slot, ctx, tbl: (t, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((tokens, kv_heads, group, d),
                                       q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(tok_slot, jnp.int32), jnp.asarray(tok_ctx, jnp.int32),
      jnp.asarray(block_tables, jnp.int32), qg, k_pages, v_pages)
    return out.reshape(tokens, heads, d)


def _ragged_impl():
    import os
    return os.environ.get("PADDLE_TPU_RAGGED_IMPL", "auto").lower()


def _qblock_eligible(impl, *values):
    """The q-block schedule is built host-side, so it needs concrete
    descriptor/block-table values — under jit tracing the per-token grid
    (whose index maps trace fine) is the escape hatch."""
    if impl in ("token", "pertoken", "xla"):
        return False
    return not any(isinstance(v, jax.core.Tracer) for v in values)


def _ragged_paged_attention_xla(q, k_pages, v_pages, block_tables,
                                tok_slot, tok_ctx, *, sm_scale,
                                k_scales=None, v_scales=None):
    """Vectorized jittable XLA tier: gather each token's sequence pages
    as dense KV (dequantized when int8 row scales are given), then
    masked softmax-attention. O(tokens * S_max) HBM — trades the
    kernel's memory win for wedge-free compiles."""
    kv_heads, _, page_size, d = k_pages.shape
    tokens, heads, _ = q.shape
    group = heads // kv_heads
    tbl = jnp.asarray(block_tables, jnp.int32)[jnp.asarray(tok_slot,
                                                           jnp.int32)]
    kg, vg = k_pages[:, tbl], v_pages[:, tbl]
    if k_scales is not None:
        kg = kg.astype(jnp.float32) * k_scales[:, tbl][..., None]
        vg = vg.astype(jnp.float32) * v_scales[:, tbl][..., None]
    # [kv, tokens, pages, slot, d] -> [tokens, kv, S, d]
    ks = jnp.moveaxis(kg, 1, 0).reshape(tokens, kv_heads, -1, d)
    vs = jnp.moveaxis(vg, 1, 0).reshape(tokens, kv_heads, -1, d)
    qb = (q * sm_scale).reshape(tokens, kv_heads, group, d)
    s = jnp.einsum("tkgd,tksd->tkgs", qb.astype(jnp.float32),
                   ks.astype(jnp.float32))
    valid = (jnp.arange(ks.shape[2])[None, :]
             < jnp.asarray(tok_ctx, jnp.int32)[:, None])
    s = jnp.where(valid[:, None, None, :], s, -jnp.inf)
    w = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("tkgs,tksd->tkgd", w, vs.astype(jnp.float32))
    return o.reshape(tokens, heads, d).astype(q.dtype)


def ragged_paged_attention(q, k_pages, v_pages, block_tables, seq_slots,
                           q_starts, q_lens, context_lens, *,
                           sm_scale=None, k_scales=None, v_scales=None,
                           interpret=False):
    """Mixed prefill+decode attention over a shared paged KV cache.

    q               [tokens, heads, head_dim] — the flat packed batch
    k_pages/v_pages [kv_heads, num_pages, page_size, head_dim]
    block_tables    [slots, pages_per_seq] int32 (unused entries = 0)
    seq_slots       [nseq] int32 — block-table row per sequence
    q_starts        [nseq] int32 — NON-DECREASING span offsets into q
    q_lens          [nseq] int32 — span length (1 = decode; a
                    speculative verify span is the current token plus k
                    drafted tokens, q_len = k+1)
    context_lens    [nseq] int32 — total context incl. this span
    k_scales/v_scales [kv_heads, num_pages, page_size] f32 — per-row
                    dequant scales for int8 pages (None = native pages)
    -> [tokens, heads, head_dim]; rows outside every span are garbage.
    """
    tokens, heads, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    impl = _ragged_impl()
    qblock_ok = _qblock_eligible(impl, seq_slots, q_starts, q_lens,
                                 context_lens, block_tables)
    if k_scales is not None:
        # int8 KV pages: same wedge-proof ladder, own canaries — the
        # quant kernels' Mosaic lowerings (int8 loads + row-scale
        # multiplies) are distinct from the native kernels' proven ones.
        if not interpret and jax.default_backend() == "tpu":
            if impl != "xla":
                from ...utils.guarded_compile import kernel_allowed
                if qblock_ok and (impl == "inrepo" or kernel_allowed(
                        "ragged_paged_attention_qblock_int8",
                        "int8-KV q-block ragged attention kernel",
                        fallback="the per-token ragged kernel")):
                    return _ragged_paged_attention_pallas_qblock(
                        q, k_pages, v_pages, block_tables, seq_slots,
                        q_starts, q_lens, context_lens,
                        sm_scale=sm_scale, interpret=False,
                        k_scales=k_scales, v_scales=v_scales)
                if impl == "inrepo" or kernel_allowed(
                        "ragged_paged_attention_int8",
                        "int8-KV ragged paged attention kernel",
                        fallback="the XLA dequant-gather tier"):
                    tok_slot, tok_ctx = _token_descriptors(
                        tokens, seq_slots, q_starts, q_lens, context_lens)
                    return _ragged_paged_attention_pallas_quant(
                        q, k_pages, v_pages, k_scales, v_scales,
                        block_tables, tok_slot, tok_ctx,
                        sm_scale=sm_scale, interpret=False)
            tok_slot, tok_ctx = _token_descriptors(
                tokens, seq_slots, q_starts, q_lens, context_lens)
            return _ragged_paged_attention_xla(
                q, k_pages, v_pages, block_tables, tok_slot, tok_ctx,
                sm_scale=sm_scale, k_scales=k_scales, v_scales=v_scales)
        if qblock_ok:
            return _ragged_paged_attention_pallas_qblock(
                q, k_pages, v_pages, block_tables, seq_slots, q_starts,
                q_lens, context_lens, sm_scale=sm_scale,
                interpret=interpret, k_scales=k_scales, v_scales=v_scales)
        tok_slot, tok_ctx = _token_descriptors(tokens, seq_slots,
                                               q_starts, q_lens,
                                               context_lens)
        if impl == "xla":
            return _ragged_paged_attention_xla(
                q, k_pages, v_pages, block_tables, tok_slot, tok_ctx,
                sm_scale=sm_scale, k_scales=k_scales, v_scales=v_scales)
        return _ragged_paged_attention_pallas_quant(
            q, k_pages, v_pages, k_scales, v_scales, block_tables,
            tok_slot, tok_ctx, sm_scale=sm_scale, interpret=interpret)
    if not interpret and jax.default_backend() == "tpu":
        # Impl choice on real TPU: same wedge-proof ladder as
        # paged_attention — an in-repo kernel only after its canary is
        # proven in a disposable subprocess; the q-block grid first
        # (fewer, fatter steps), the per-token grid as escape hatch
        # (PADDLE_TPU_RAGGED_IMPL=token), zero-Mosaic XLA at the bottom.
        if impl != "xla":
            from ...utils.guarded_compile import kernel_allowed
            if qblock_ok and (impl == "inrepo" or kernel_allowed(
                    "ragged_paged_attention_qblock",
                    "q-block ragged paged attention kernel",
                    fallback="the per-token ragged kernel")):
                return _ragged_paged_attention_pallas_qblock(
                    q, k_pages, v_pages, block_tables, seq_slots,
                    q_starts, q_lens, context_lens, sm_scale=sm_scale,
                    interpret=False)
            if impl == "inrepo" or kernel_allowed(
                    "ragged_paged_attention", "ragged paged attention kernel",
                    fallback="the XLA gather-attention tier"):
                tok_slot, tok_ctx = _token_descriptors(
                    tokens, seq_slots, q_starts, q_lens, context_lens)
                return _ragged_paged_attention_pallas(
                    q, k_pages, v_pages, block_tables, tok_slot, tok_ctx,
                    sm_scale=sm_scale, interpret=False)
        tok_slot, tok_ctx = _token_descriptors(tokens, seq_slots,
                                               q_starts, q_lens,
                                               context_lens)
        return _ragged_paged_attention_xla(
            q, k_pages, v_pages, block_tables, tok_slot, tok_ctx,
            sm_scale=sm_scale)
    if qblock_ok:
        return _ragged_paged_attention_pallas_qblock(
            q, k_pages, v_pages, block_tables, seq_slots, q_starts,
            q_lens, context_lens, sm_scale=sm_scale, interpret=interpret)
    tok_slot, tok_ctx = _token_descriptors(tokens, seq_slots, q_starts,
                                           q_lens, context_lens)
    if impl == "xla":
        return _ragged_paged_attention_xla(
            q, k_pages, v_pages, block_tables, tok_slot, tok_ctx,
            sm_scale=sm_scale)
    return _ragged_paged_attention_pallas(
        q, k_pages, v_pages, block_tables, tok_slot, tok_ctx,
        sm_scale=sm_scale, interpret=interpret)


def ragged_paged_attention_reference(q, k_pages, v_pages, block_tables,
                                     seq_slots, q_starts, q_lens,
                                     context_lens):
    """Dense numpy-style oracle: per sequence, gather its context from
    the pages and run plain causal softmax attention for its span. Rows
    outside every span are zero."""
    import numpy as np

    tokens, heads, d = q.shape
    kv_heads, _, page_size, _ = k_pages.shape
    group = heads // kv_heads
    out = np.zeros((tokens, heads, d), np.float32)
    tbl = np.asarray(block_tables)
    for i in range(len(np.asarray(seq_slots))):
        slot = int(np.asarray(seq_slots)[i])
        qs = int(np.asarray(q_starts)[i])
        ql = int(np.asarray(q_lens)[i])
        ctx = int(np.asarray(context_lens)[i])
        n_pages = -(-ctx // page_size)
        ks = jnp.concatenate([k_pages[:, int(tbl[slot, p])]
                              for p in range(n_pages)], axis=1)[:, :ctx]
        vs = jnp.concatenate([v_pages[:, int(tbl[slot, p])]
                              for p in range(n_pages)], axis=1)[:, :ctx]
        for j in range(ql):
            vis = ctx - ql + j + 1                 # causal inside the span
            qb = q[qs + j].reshape(kv_heads, group, d).astype(jnp.float32)
            s = jnp.einsum("kgd,ksd->kgs", qb,
                           ks[:, :vis].astype(jnp.float32)) / math.sqrt(d)
            w = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("kgs,ksd->kgd", w,
                           vs[:, :vis].astype(jnp.float32))
            out[qs + j] = np.asarray(o.reshape(heads, d))
    return jnp.asarray(out).astype(q.dtype)
