"""Weight-only int8 matmul Pallas kernel (reference analogue: the int8
inference path of ``paddle/fluid/inference`` + phi int8 GEMM kernels /
weight-only-quant GEMM in the fusion tier; SURVEY.md §2.1, §7.0 "Pallas
(Mosaic) kernels ... quantized" tier).

TPU rationale: weight-only int8 halves (vs bf16) or quarters (vs f32) the
HBM traffic of the GEMM's weight stream — the bound resource for small-batch
decode. The kernel streams int8 weight tiles into VMEM and dequantizes
per-tile (per-output-channel scales) right before the MXU dot, so the full
f32 weight matrix never exists in HBM.

Grid (m, n, k) with k innermost (sequential): f32 accumulator scratch
persists across k steps, output written at the last k step.
"""
from __future__ import annotations

import functools
import weakref

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams; accept either name so
# the kernels (and their CPU interpret-mode tests) work across versions
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))


def _cdiv(a, b):
    return (a + b - 1) // b


#: (id(w), id(scale)) -> (weakref(w), weakref(scale), dequant array).
#: The guarded-off fallback below used to dequantize the FULL weight on
#: every call — per decode step, per layer — which regressed eager
#: serving whenever the canary said no. Weights are long-lived (a model
#:  holds them for the process lifetime), so one dequant per weight
#: identity amortizes to zero; the weakrefs guard against id() reuse
#: after garbage collection.
_DEQUANT_CACHE: dict = {}
_DEQUANT_CACHE_MAX = 64


def _dequant_weight(w_int8, scale):
    key = (id(w_int8), id(scale))
    hit = _DEQUANT_CACHE.get(key)
    if hit is not None:
        w_ref, s_ref, dq = hit
        if w_ref() is w_int8 and s_ref() is scale:
            return dq
        del _DEQUANT_CACHE[key]
    dq = w_int8.astype(jnp.float32) * scale[None, :]
    try:
        entry = (weakref.ref(w_int8), weakref.ref(scale), dq)
    except TypeError:                       # non-weakrefable operands
        entry = ((lambda o=w_int8: o), (lambda o=scale: o), dq)
    if len(_DEQUANT_CACHE) >= _DEQUANT_CACHE_MAX:
        _DEQUANT_CACHE.clear()
    _DEQUANT_CACHE[key] = entry
    return dq


def _kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, k_steps):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)          # dequant: int8 -> f32 tile
    acc_ref[...] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(k == k_steps - 1)
    def _done():
        scale = s_ref[...][0]                    # [bn] per-channel scales
        o_ref[...] = (acc_ref[...] * scale[None, :]).astype(o_ref.dtype)


def int8_matmul(x, w_int8, scale, block_m=128, block_n=128, block_k=128,
                out_dtype=None, interpret=None):
    """x [M, K] float; w_int8 [K, N] int8; scale [N] f32 (per output channel,
    dequant = int8 * scale). Returns x @ (w_int8 * scale) [M, N]."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not interpret and jax.default_backend() == "tpu":
        from ...utils.guarded_compile import kernel_allowed
        if not kernel_allowed("quant_matmul", "int8 matmul kernel"):
            # XLA fallback: dequantize + plain matmul (safe, more HBM);
            # dequant cached per weight identity — see _dequant_weight
            w = _dequant_weight(w_int8, scale)
            return (x.astype(jnp.float32) @ w).astype(out_dtype or x.dtype)
    m, kdim = x.shape
    _, n = w_int8.shape
    out_dtype = out_dtype or x.dtype
    block_m = min(block_m, max(m, 8))
    block_n = min(block_n, max(n, 128))
    block_k = min(block_k, max(kdim, 128))
    mp, np_, kp = (_cdiv(m, block_m) * block_m, _cdiv(n, block_n) * block_n,
                   _cdiv(kdim, block_k) * block_k)
    if (mp, kp) != (m, kdim):
        x = jnp.pad(x, ((0, mp - m), (0, kp - kdim)))
    if (kp, np_) != (kdim, n):
        w_int8 = jnp.pad(w_int8, ((0, kp - kdim), (0, np_ - n)))
    if np_ != n:
        scale = jnp.pad(scale, (0, np_ - n))
    k_steps = kp // block_k

    out = pl.pallas_call(
        functools.partial(_kernel, k_steps=k_steps),
        grid=(mp // block_m, np_ // block_n, k_steps),
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda i, j, k: (i, k)),
            pl.BlockSpec((block_k, block_n), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, block_n), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), out_dtype),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(x, w_int8, scale[None, :].astype(jnp.float32))
    return out[:m, :n]


def quantize_weight(w):
    """f32 [K, N] -> (int8 [K, N], scale [N]) symmetric per-output-channel
    (abs-max over the reduction axis K)."""
    amax = jnp.max(jnp.abs(w), axis=0)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w / scale[None, :]), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)
