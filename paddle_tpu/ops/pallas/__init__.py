"""Pallas (Mosaic) TPU kernels — the TPU-native analogue of the reference's
CUDA fusion tier (`paddle/phi/kernels/gpu/flash_attn_*`, `fusion/`;
SURVEY.md §7.0: "CUDA-kernel components map to Pallas").
"""
from .flash_attention import (  # noqa: F401
    flash_attention, flash_attention_with_lse, mha_reference,
)
from .ring_attention import ring_flash_attention  # noqa: F401
from .quant_matmul import int8_matmul, quantize_weight  # noqa: F401
from .ragged_paged_attention import (  # noqa: F401
    ragged_paged_attention, ragged_paged_attention_reference,
)
