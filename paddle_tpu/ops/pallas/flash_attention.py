"""Flash attention as Pallas TPU kernels (fwd + bwd).

Reference analogue: the FA2 CUDA kernels Paddle vendors and wires as phi
kernels (``paddle/phi/kernels/gpu/flash_attn_kernel``, ``third_party/flashattn``
— SURVEY.md §2.1), surfaced through
``paddle.nn.functional.scaled_dot_product_attention``. On TPU the same tiling
idea maps onto Pallas/Mosaic: the grid iterates KV blocks sequentially per
(batch, head, Q-block) with online-softmax state (m, l, acc) carried in VMEM
scratch, so logits are never materialized in HBM — O(seq) memory like FA2.

Extras beyond a plain FA port, needed by the ring-attention (context-parallel)
layer (SURVEY.md §5.7):

* ``q_offset`` / ``kv_offset`` runtime scalars (SMEM) give each block's global
  position, so causal masking stays exact when Q and KV are shards of a longer
  sequence rotating around the 'sep'/cp mesh axis.
* the forward also returns the per-row logsumexp (``lse``) so partial results
  from different KV shards merge with the standard online-softmax combine —
  the same contract FA2 exposes via ``softmax_lse`` for PaddleNLP's
  ``RingFlashAttention``.

Layouts: public API is Paddle's flash-attn layout ``[batch, seq, heads, dim]``;
kernels run in ``[batch, heads, seq, dim]``. GQA is supported by mapping each
query head to its KV group in the BlockSpec index map (no materialized
repeats).
"""
from __future__ import annotations

import functools
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams; accept either name so
# the kernels (and their CPU interpret-mode tests) work across versions
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

NEG_INF = float(-1e30)   # large-negative instead of -inf: keeps exp()/where() NaN-free

# Tunable via env for the MFU sweep (BASELINE.md): block sizes set the
# VMEM working set vs grid-parallelism trade on the MXU — 128 is the safe
# default; 256/512 on Q can lift arithmetic intensity at long seq.
import os as _os

DEFAULT_BLOCK_Q = int(_os.environ.get("PADDLE_TPU_FA_BLOCK_Q", "128"))
DEFAULT_BLOCK_K = int(_os.environ.get("PADDLE_TPU_FA_BLOCK_K", "128"))


def _cdiv(a, b):
    return (a + b - 1) // b


# ---------------------------------------------------------------------------
# Reference (pure XLA) — also the numerical oracle for tests
# ---------------------------------------------------------------------------

def mha_reference(q, k, v, causal=True, sm_scale=None, q_offset=0,
                  kv_offset=0, with_lse=False):
    """Plain-XLA attention in kernel layout [b, h, s, d] (GQA-aware).

    Returns ``out`` or ``(out, lse)``; lse is fp32 [b, h, sq].
    """
    b, hq, sq, d = q.shape
    hk, sk = k.shape[1], k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if hk != hq:
        # GQA via grouped einsum — no materialized K/V head repeats
        g = hq // hk
        qg = q.reshape(b, hk, g, sq, d).astype(jnp.float32)
        logits = jnp.einsum("bhgqd,bhkd->bhgqk", qg,
                            k.astype(jnp.float32)).reshape(b, hq, sq, sk)
        logits = logits * sm_scale
    else:
        logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                            k.astype(jnp.float32)) * sm_scale
    if causal:
        qi = jnp.arange(sq)[:, None] + q_offset
        ki = jnp.arange(k.shape[2])[None, :] + kv_offset
        logits = jnp.where(qi >= ki, logits, NEG_INF)
    m = jnp.max(logits, axis=-1, keepdims=True)
    dead = m <= NEG_INF          # fully-masked row: zero output (kernel contract)
    p = jnp.where(dead, 0.0, jnp.exp(logits - m))
    l = jnp.sum(p, axis=-1, keepdims=True)
    if hk != hq:
        pg = p.reshape(b, hk, hq // hk, sq, sk)
        out = jnp.einsum("bhgqk,bhkd->bhgqd", pg,
                         v.astype(jnp.float32)).reshape(b, hq, sq, d)
        out = out / jnp.maximum(l, 1e-30)
    else:
        out = jnp.einsum("bhqk,bhkd->bhqd", p,
                         v.astype(jnp.float32)) / jnp.maximum(l, 1e-30)
    out = out.astype(q.dtype)
    if not with_lse:
        return out
    lse = (m + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]
    lse = jnp.where(l[..., 0] <= 1e-30, NEG_INF, lse)
    return out, lse


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(off_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, sm_scale, causal, block_q, block_k,
                kv_blocks, kv_len):
    i = pl.program_id(2)          # q block
    j = pl.program_id(3)          # kv block (sequential)
    q_off = off_ref[0]
    kv_off = off_ref[1]

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # global positions of this tile's rows/cols
    q_ids = q_off + i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_local = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    k_ids = kv_off + k_local

    # skip tiles that are entirely in the causal future
    run = True
    if causal:
        first_q = q_off + i * block_q
        last_q = first_q + block_q - 1
        first_k = kv_off + j * block_k
        run = last_q >= first_k

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        mask = k_local < kv_len
        if causal:
            mask = jnp.logical_and(mask, q_ids >= k_ids)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:, :1]                       # (bq, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # (bq, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                      # fully-masked rows -> 0
        corr = jnp.exp(m_prev - m_new)
        l_new = l_ref[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == kv_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        o_ref[0, 0] = (acc_ref[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        lse = m_ref[:, :1] + jnp.log(jnp.maximum(l, 1e-30))
        lse = jnp.where(l <= 1e-30, NEG_INF, lse)
        # lane-replicated (block_q, 128) store: Mosaic needs >=(8,128) tiles
        lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref.shape[2:])


def _fwd(q, k, v, causal, sm_scale, q_offset, kv_offset, block_q, block_k,
         interpret):
    b, hq, sq, d = q.shape
    hk, sk = k.shape[1], k.shape[2]
    group = hq // hk
    block_q = min(block_q, max(sq, 8))
    block_k = min(block_k, max(sk, 8))
    sq_pad = _cdiv(sq, block_q) * block_q
    sk_pad = _cdiv(sk, block_k) * block_k
    if sq_pad != sq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, sq_pad - sq), (0, 0)))
    if sk_pad != sk:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, sk_pad - sk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, sk_pad - sk), (0, 0)))
    q_blocks = sq_pad // block_q
    kv_blocks = sk_pad // block_k
    offs = jnp.asarray(
        jnp.stack([jnp.asarray(q_offset, jnp.int32),
                   jnp.asarray(kv_offset, jnp.int32)]), jnp.int32)

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, kv_blocks=kv_blocks, kv_len=sk)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, hq, q_blocks, kv_blocks),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i, j: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h, i, j: (b_, h // group, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h, i, j: (b_, h // group, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i, j: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 128),
                         lambda b_, h, i, j: (b_, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sq_pad, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, sq_pad, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(offs, q, k, v)
    return out[:, :, :sq], lse[:, :, :sq, 0]


# ---------------------------------------------------------------------------
# Backward kernels (FA2-style recompute; dq pass + dk/dv pass)
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, acc_ref, *, sm_scale, causal, block_q, block_k,
                   kv_blocks, kv_len):
    i = pl.program_id(2)
    j = pl.program_id(3)
    q_off = off_ref[0]
    kv_off = off_ref[1]

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    run = True
    if causal:
        run = (q_off + i * block_q + block_q - 1) >= (kv_off + j * block_k)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        q_ids = q_off + i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_local = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_local < kv_len
        if causal:
            mask = jnp.logical_and(mask, q_ids >= (kv_off + k_local))
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        acc_ref[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(j == kv_blocks - 1)
    def _finalize():
        dq_ref[0, 0] = acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, sm_scale, causal,
                    block_q, block_k, q_blocks, kv_len):
    j = pl.program_id(2)          # kv block
    i = pl.program_id(3)          # q block (sequential)
    q_off = off_ref[0]
    kv_off = off_ref[1]

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        run = (q_off + i * block_q + block_q - 1) >= (kv_off + j * block_k)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        q_ids = q_off + i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_local = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_local < kv_len
        if causal:
            mask = jnp.logical_and(mask, q_ids >= (kv_off + k_local))
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)         # (bq, bk)
        dv_acc[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(i == q_blocks - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd(causal, sm_scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse, offs = res
    do, g_lse = g
    b, hq, sq, d = q.shape
    hk, sk = k.shape[1], k.shape[2]
    group = hq // hk
    block_q = min(block_q, max(sq, 8))
    block_k = min(block_k, max(sk, 8))
    sq_pad = _cdiv(sq, block_q) * block_q
    sk_pad = _cdiv(sk, block_k) * block_k

    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    # lse is a differentiable output (ring merge uses it): dlse/ds_j = p_j, so
    # its cotangent folds into the delta term of ds = p*(dp - delta)
    if g_lse is not None and getattr(g_lse, "dtype", None) != jax.dtypes.float0:
        delta = delta - g_lse.astype(jnp.float32)

    def padq(x):
        return jnp.pad(x, ((0, 0), (0, 0), (0, sq_pad - sq)) +
                       (((0, 0),) if x.ndim == 4 else ())) if sq_pad != sq else x

    def padk(x):
        return jnp.pad(x, ((0, 0), (0, 0), (0, sk_pad - sk), (0, 0))) \
            if sk_pad != sk else x

    qp, dop = padq(q), padq(do)
    # padded q rows: lse = +inf so p = exp(s - inf) = 0 (NEG_INF would explode)
    lsep = jnp.pad(lse, ((0, 0), (0, 0), (0, sq_pad - sq)),
                   constant_values=jnp.inf) if sq_pad != sq else lse
    deltap = padq(delta)
    # lane-replicated (…, 128) layout for per-row scalars (Mosaic tiling)
    lsep = jnp.broadcast_to(lsep[..., None], (*lsep.shape, 128))
    deltap = jnp.broadcast_to(deltap[..., None], (*deltap.shape, 128))
    kp, vp = padk(k), padk(v)
    q_blocks = sq_pad // block_q
    kv_blocks = sk_pad // block_k

    q_spec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i, j: (b_, h, i, 0))
    kv_spec = pl.BlockSpec((1, 1, block_k, d),
                           lambda b_, h, i, j: (b_, h // group, j, 0))
    row_spec = pl.BlockSpec((1, 1, block_q, 128),
                            lambda b_, h, i, j: (b_, h, i, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          kv_blocks=kv_blocks, kv_len=sk),
        grid=(b, hq, q_blocks, kv_blocks),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((b, hq, sq_pad, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(offs, qp, kp, vp, dop, lsep, deltap)[0][:, :, :sq]

    # dk/dv per *query* head (grid over full hq), then reduce over the GQA group
    kv_q_spec = pl.BlockSpec((1, 1, block_k, d),
                             lambda b_, h, j, i: (b_, h // group, j, 0))
    q_spec2 = pl.BlockSpec((1, 1, block_q, d), lambda b_, h, j, i: (b_, h, i, 0))
    row_spec2 = pl.BlockSpec((1, 1, block_q, 128),
                             lambda b_, h, j, i: (b_, h, i, 0))
    dkv_out_spec = pl.BlockSpec((1, 1, block_k, d),
                                lambda b_, h, j, i: (b_, h, j, 0))
    dk_full, dv_full = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          q_blocks=q_blocks, kv_len=sk),
        grid=(b, hq, kv_blocks, q_blocks),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  q_spec2, kv_q_spec, kv_q_spec, q_spec2, row_spec2, row_spec2],
        out_specs=[dkv_out_spec, dkv_out_spec],
        out_shape=[jax.ShapeDtypeStruct((b, hq, sk_pad, d), jnp.float32),
                   jax.ShapeDtypeStruct((b, hq, sk_pad, d), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(offs, qp, kp, vp, dop, lsep, deltap)
    dk_full = dk_full[:, :, :sk]
    dv_full = dv_full[:, :, :sk]
    if group > 1:
        dk = dk_full.reshape(b, hk, group, sk, d).sum(axis=2)
        dv = dv_full.reshape(b, hk, group, sk, d).sum(axis=2)
    else:
        dk, dv = dk_full, dv_full
    d_offs = np.zeros(offs.shape, dtype=jax.dtypes.float0)  # int input: float0 cotangent
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), d_offs)


# ---------------------------------------------------------------------------
# custom_vjp wrapper (kernel layout [b, h, s, d])
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, offs, causal, sm_scale, block_q, block_k, interpret):
    out, _ = _fwd(q, k, v, causal, sm_scale, offs[0], offs[1],
                  block_q, block_k, interpret)
    return out


def _flash_fwd_rule(q, k, v, offs, causal, sm_scale, block_q, block_k,
                    interpret):
    out, lse = _fwd(q, k, v, causal, sm_scale, offs[0], offs[1],
                    block_q, block_k, interpret)
    return out, (q, k, v, out, lse, offs)


def _flash_bwd_rule(causal, sm_scale, block_q, block_k, interpret, res, g):
    return _bwd(causal, sm_scale, block_q, block_k, interpret, res, (g, None))


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_with_lse(q, k, v, offs, causal, sm_scale, block_q, block_k,
                    interpret):
    return _fwd(q, k, v, causal, sm_scale, offs[0], offs[1], block_q, block_k,
                interpret)


def _flash_lse_fwd_rule(q, k, v, offs, causal, sm_scale, block_q, block_k,
                        interpret):
    out, lse = _fwd(q, k, v, causal, sm_scale, offs[0], offs[1],
                    block_q, block_k, interpret)
    return (out, lse), (q, k, v, out, lse, offs)


_flash_with_lse.defvjp(_flash_lse_fwd_rule, _bwd)


def _default_interpret():
    return jax.default_backend() != "tpu"


def _xla_fallback(q, k, v, causal, sm_scale, q_offset, kv_offset,
                  with_lse=False, chunk=1024):
    """Safe non-Mosaic path (kernel layout). Chunks the query axis so the
    fp32 logits temporary is O(chunk*sk), not O(sq*sk) — an unproven
    kernel at long sequence lengths must degrade to slow, not to OOM.
    Each chunk is wrapped in ``jax.checkpoint`` so the backward also
    recomputes its logits/probabilities per chunk: without it jax AD
    saves every chunk's O(chunk*sk) softmax residuals, which together
    re-materialize the full S×S memory this tier exists to avoid."""
    sq, sk = q.shape[2], k.shape[2]
    if sq <= chunk:
        return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale,
                             q_offset=q_offset, kv_offset=kv_offset,
                             with_lse=with_lse)

    @functools.partial(jax.checkpoint, static_argnums=(3, 4))
    def one_chunk(qc, k, v, start, hi):
        # the kv trim happens INSIDE the checkpoint boundary: the saved
        # residual stays the one shared full k/v buffer, the sliced
        # copies are recomputed in backward (slicing outside would pin
        # every chunk's kv prefix live simultaneously — O(sq²·d/chunk))
        return mha_reference(qc, k[:, :, :hi], v[:, :, :hi], causal=causal,
                             sm_scale=sm_scale, q_offset=q_offset + start,
                             kv_offset=kv_offset, with_lse=with_lse)

    # causal + static offsets: chunk [start, start+chunk) can only attend
    # to kv positions <= q_offset+start+chunk-1, so trim the kv suffix —
    # the triangle costs half the FLOPs of the full rectangle
    trim = causal and isinstance(q_offset, int) and isinstance(kv_offset, int)
    outs, lses = [], []
    for start in range(0, sq, chunk):
        hi = sk
        if trim:
            hi = max(min(sk, q_offset + start + chunk - kv_offset), 1)
        res = one_chunk(q[:, :, start:start + chunk], k, v, start, hi)
        if with_lse:
            outs.append(res[0])
            lses.append(res[1])
        else:
            outs.append(res)
    if with_lse:
        return jnp.concatenate(outs, axis=2), jnp.concatenate(lses, axis=2)
    return jnp.concatenate(outs, axis=2)


# ---------------------------------------------------------------------------
# Pure-XLA flash attention (no Mosaic): lax.scan online-softmax forward +
# custom_vjp blockwise-recompute backward. This is the training-path tier
# for sessions where Mosaic compiles are off-limits (the round-2/3/4 tunnel
# wedge) — flash MEMORY behavior (O(block²) logits temporaries, O(S)
# residuals) from plain XLA ops the TPU compiler handles natively.
# ---------------------------------------------------------------------------

def _xfa_blocks(sq, sk):
    bq = min(int(_os.environ.get("PADDLE_TPU_XFA_BLOCK_Q", "512")), sq)
    bk = min(int(_os.environ.get("PADDLE_TPU_XFA_BLOCK_K", "1024")), sk)
    return bq, bk


def _xflash_fwd_impl(q, k, v, offs, causal, sm_scale):
    """Grouped-GQA online-softmax forward. q [b,hq,sq,d]; k/v [b,hk,sk,d];
    returns (out [b,hq,sq,d], lse fp32 [b,hq,sq]) with mha_reference's
    conventions (natural-log lse; fully-masked rows -> out 0, lse NEG_INF)."""
    b, hq, sq, d = q.shape
    hk, sk = k.shape[1], k.shape[2]
    g = hq // hk
    bq, bk = _xfa_blocks(sq, sk)
    nq, nk = sq // bq, sk // bk
    q_off = jnp.asarray(offs[0], jnp.int32)
    kv_off = jnp.asarray(offs[1], jnp.int32)
    qg = q.reshape(b, hk, g, sq, d)

    def one_q_block(qi, qblk):                     # qblk [b,hk,g,bq,d]
        m0 = jnp.full((b, hk, g, bq), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, hk, g, bq), jnp.float32)
        a0 = jnp.zeros((b, hk, g, bq, d), jnp.float32)

        def step(carry, kj):
            m, l, acc = carry
            kblk = jax.lax.dynamic_slice_in_dim(k, kj * bk, bk, axis=2)
            vblk = jax.lax.dynamic_slice_in_dim(v, kj * bk, bk, axis=2)
            s = jnp.einsum("bhgqd,bhkd->bhgqk", qblk, kblk,
                           preferred_element_type=jnp.float32) * sm_scale
            if causal:
                qpos = q_off + qi * bq + jnp.arange(bq, dtype=jnp.int32)
                kpos = kv_off + kj * bk + jnp.arange(bk, dtype=jnp.int32)
                s = jnp.where(qpos[:, None] >= kpos[None, :], s, NEG_INF)
            m_new = jnp.maximum(m, s.max(-1))
            # dead rows (everything masked): exponents of NEG_INF-vs-NEG_INF
            # must not become exp(0)=1 — shift by 0 instead
            m_eff = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
            p = jnp.exp(s - m_eff[..., None])
            alpha = jnp.exp(m - m_eff)
            l_new = l * alpha + p.sum(-1)
            pv = jnp.einsum("bhgqk,bhkd->bhgqd", p.astype(v.dtype), vblk,
                            preferred_element_type=jnp.float32)
            return (m_new, l_new, acc * alpha[..., None] + pv), None

        (m, l, acc), _ = jax.lax.scan(step, (m0, l0, a0),
                                      jnp.arange(nk, dtype=jnp.int32))
        l_safe = jnp.maximum(l, 1e-30)
        out = (acc / l_safe[..., None]).astype(q.dtype)
        m_eff = jnp.where(m <= NEG_INF / 2, 0.0, m)
        lse = jnp.where(l <= 1e-30, NEG_INF, m_eff + jnp.log(l_safe))
        return out, lse

    qblocks = jnp.moveaxis(qg.reshape(b, hk, g, nq, bq, d), 3, 0)

    def scan_q(_, xs):
        qi, qblk = xs
        return None, one_q_block(qi, qblk)

    _, (outs, lses) = jax.lax.scan(
        scan_q, None, (jnp.arange(nq, dtype=jnp.int32), qblocks))
    out = jnp.moveaxis(outs, 0, 3).reshape(b, hq, sq, d)
    lse = jnp.moveaxis(lses, 0, 3).reshape(b, hq, sq)
    return out, lse


def _xflash_bwd_impl(q, k, v, offs, out, lse, dout, causal, sm_scale,
                     g_lse=None):
    """Blockwise-recompute backward (FA2 structure in plain XLA): one scan
    over q blocks carrying fp32 dk/dv accumulators, inner scan over kv
    blocks; p is recomputed from lse so no S×S residual exists."""
    b, hq, sq, d = q.shape
    hk, sk = k.shape[1], k.shape[2]
    g = hq // hk
    bq, bk = _xfa_blocks(sq, sk)
    nq, nk = sq // bq, sk // bk
    q_off = jnp.asarray(offs[0], jnp.int32)
    kv_off = jnp.asarray(offs[1], jnp.int32)

    delta = (dout.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)
    # lse is a differentiable output (ring merge uses it): dlse/ds_j = p_j,
    # so its cotangent folds into the delta term of ds = p*(dp - delta) —
    # same handling as the Mosaic path's _bwd
    if g_lse is not None and getattr(g_lse, "dtype", None) != \
            jax.dtypes.float0:
        delta = delta - g_lse.astype(jnp.float32)
    shp5 = (b, hk, g, nq, bq)
    qb = jnp.moveaxis(q.reshape(b, hk, g, nq, bq, d), 3, 0)
    dob = jnp.moveaxis(dout.reshape(b, hk, g, nq, bq, d), 3, 0)
    lseb = jnp.moveaxis(lse.reshape(*shp5), 3, 0)
    deltab = jnp.moveaxis(delta.reshape(*shp5), 3, 0)

    def per_q(carry, xs):
        dk, dv = carry
        qi, qblk, doblk, lseblk, dblk = xs
        live = (lseblk > NEG_INF / 2).astype(jnp.float32)

        def step(inner, kj):
            dq_acc, dk, dv = inner
            kblk = jax.lax.dynamic_slice_in_dim(k, kj * bk, bk, axis=2)
            vblk = jax.lax.dynamic_slice_in_dim(v, kj * bk, bk, axis=2)
            s = jnp.einsum("bhgqd,bhkd->bhgqk", qblk, kblk,
                           preferred_element_type=jnp.float32) * sm_scale
            if causal:
                qpos = q_off + qi * bq + jnp.arange(bq, dtype=jnp.int32)
                kpos = kv_off + kj * bk + jnp.arange(bk, dtype=jnp.int32)
                s = jnp.where(qpos[:, None] >= kpos[None, :], s, NEG_INF)
            p = jnp.exp(s - lseblk[..., None]) * live[..., None]
            dp = jnp.einsum("bhgqd,bhkd->bhgqk", doblk, vblk,
                            preferred_element_type=jnp.float32)
            ds = p * (dp - dblk[..., None]) * sm_scale
            pc, dsc = p.astype(v.dtype), ds.astype(q.dtype)
            dq_blk = jnp.einsum("bhgqk,bhkd->bhgqd", dsc, kblk,
                                preferred_element_type=jnp.float32)
            dk_blk = jnp.einsum("bhgqk,bhgqd->bhkd", dsc, qblk,
                                preferred_element_type=jnp.float32)
            dv_blk = jnp.einsum("bhgqk,bhgqd->bhkd", pc, doblk,
                                preferred_element_type=jnp.float32)
            dk = jax.lax.dynamic_update_slice_in_dim(
                dk, jax.lax.dynamic_slice_in_dim(dk, kj * bk, bk, 2)
                + dk_blk, kj * bk, 2)
            dv = jax.lax.dynamic_update_slice_in_dim(
                dv, jax.lax.dynamic_slice_in_dim(dv, kj * bk, bk, 2)
                + dv_blk, kj * bk, 2)
            return (dq_acc + dq_blk, dk, dv), None

        dq0 = jnp.zeros((b, hk, g, bq, d), jnp.float32)
        (dq_blk, dk, dv), _ = jax.lax.scan(
            step, (dq0, dk, dv), jnp.arange(nk, dtype=jnp.int32))
        return (dk, dv), dq_blk

    dk0 = jnp.zeros((b, hk, sk, d), jnp.float32)
    dv0 = jnp.zeros((b, hk, sk, d), jnp.float32)
    (dk, dv), dqs = jax.lax.scan(
        per_q, (dk0, dv0),
        (jnp.arange(nq, dtype=jnp.int32), qb, dob, lseb, deltab))
    dq = jnp.moveaxis(dqs, 0, 3).reshape(b, hq, sq, d).astype(q.dtype)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _xflash(q, k, v, offs, causal, sm_scale):
    out, _ = _xflash_fwd_impl(q, k, v, offs, causal, sm_scale)
    return out


def _xflash_fwd_rule(q, k, v, offs, causal, sm_scale):
    out, lse = _xflash_fwd_impl(q, k, v, offs, causal, sm_scale)
    return out, (q, k, v, offs, out, lse)


def _xflash_bwd_rule(causal, sm_scale, res, g):
    q, k, v, offs, out, lse = res
    dq, dk, dv = _xflash_bwd_impl(q, k, v, offs, out, lse, g, causal,
                                  sm_scale)
    return dq, dk, dv, None


_xflash.defvjp(_xflash_fwd_rule, _xflash_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _xflash_with_lse(q, k, v, offs, causal, sm_scale):
    return _xflash_fwd_impl(q, k, v, offs, causal, sm_scale)


def _xflash_lse_fwd_rule(q, k, v, offs, causal, sm_scale):
    out, lse = _xflash_fwd_impl(q, k, v, offs, causal, sm_scale)
    return (out, lse), (q, k, v, offs, out, lse)


def _xflash_lse_bwd_rule(causal, sm_scale, res, g):
    q, k, v, offs, out, lse = res
    dout, g_lse = g
    dq, dk, dv = _xflash_bwd_impl(q, k, v, offs, out, lse, dout, causal,
                                  sm_scale, g_lse=g_lse)
    return dq, dk, dv, None


_xflash_with_lse.defvjp(_xflash_lse_fwd_rule, _xflash_lse_bwd_rule)


def _scanq(q, k, v, causal, sm_scale, q_offset, kv_offset,
           with_lse=False, chunk=1024):
    """Single-level scan tier: ``lax.scan`` over q-chunks, full-K plain
    attention per chunk, ``jax.checkpoint`` body. Compared to the other
    non-Mosaic tiers: graph size is CONSTANT in sequence length (the
    unrolled chunked tier emits one subgraph per chunk) and there is no
    scan-in-scan / custom_vjp structure (the _xflash formulation that
    hung the round-4 remote compile). Memory O(chunk·sk) fwd and bwd
    (remat body; k/v are closure constants whose cotangents the scan
    transpose accumulates). Requires sq % chunk == 0 (callers fall back
    to the chunked tier otherwise)."""
    b, h, sq, d = q.shape
    nq = sq // chunk
    qb = jnp.moveaxis(q.reshape(b, h, nq, chunk, d), 2, 0)
    q_off = jnp.asarray(q_offset, jnp.int32)

    @jax.checkpoint
    def body(qi, qc):
        return mha_reference(qc, k, v, causal=causal, sm_scale=sm_scale,
                             q_offset=q_off + qi * chunk,
                             kv_offset=kv_offset, with_lse=True)

    def step(carry, xs):
        qi, qc = xs
        return carry, body(qi, qc)

    _, (outs, lses) = jax.lax.scan(
        step, None, (jnp.arange(nq, dtype=jnp.int32), qb))
    out = jnp.moveaxis(outs, 0, 2).reshape(b, h, sq, d)
    if with_lse:
        return out, jnp.moveaxis(lses, 0, 2).reshape(b, h, sq)
    return out


def _xfa_mode():
    """PADDLE_TPU_XFA selects the non-Mosaic training tier:
    ``1`` (default) the scan-formulation online-softmax flash (_xflash);
    ``scanq`` the single-level scan-over-q-chunks tier; ``0`` the
    unrolled chunked-reference tier. The knob exists because the round-4
    on-chip session saw the scan formulation hang the remote XLA
    compile — the bench runner pins known-safe tiers without touching
    FLAGS."""
    mode = _os.environ.get("PADDLE_TPU_XFA", "1")
    if mode not in ("0", "1", "scanq"):
        raise ValueError(f"PADDLE_TPU_XFA={mode!r}: expected 0, 1 or scanq")
    return mode


def _xfa_chunk():
    return max(int(_os.environ.get("PADDLE_TPU_XFA_CHUNK", "1024")), 1)


def _xflash_ok(q, k):
    """The scan formulation needs block-divisible sequence axes; other
    shapes stay on the chunked-reference fallback."""
    if _xfa_mode() != "1":
        return False
    sq, sk = q.shape[2], k.shape[2]
    bq, bk = _xfa_blocks(sq, sk)
    return sq % bq == 0 and sk % bk == 0


def _scanq_ok(q):
    chunk = _xfa_chunk()
    return (_xfa_mode() == "scanq" and q.shape[2] % chunk == 0
            and q.shape[2] > chunk)


def xla_attention(q, k, v, causal=True, sm_scale=None, q_offset=0,
                  kv_offset=0, with_lse=False):
    """Non-Mosaic attention in kernel layout [b, h, s, d]: the single
    dispatch point for the pure-XLA tiers (``PADDLE_TPU_XFA`` selects
    _xflash / _scanq / the unrolled chunked tier). Used by
    ``flash_attention`` when the Mosaic kernel is quarantined and by the
    SDPA long-sequence memory-safety route — callers get tier
    improvements without re-implementing the selection."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if _xflash_ok(q, k):
        offs = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                          jnp.asarray(kv_offset, jnp.int32)])
        if with_lse:
            return _xflash_with_lse(q, k, v, offs, causal, sm_scale)
        return _xflash(q, k, v, offs, causal, sm_scale)
    if _scanq_ok(q):
        return _scanq(q, k, v, causal, sm_scale, q_offset, kv_offset,
                      with_lse=with_lse, chunk=_xfa_chunk())
    return _xla_fallback(q, k, v, causal, sm_scale, q_offset, kv_offset,
                         with_lse=with_lse)


def _mosaic_allowed():
    """First-compile guard (VERDICT.md round-2 weak #1): on a real TPU,
    dispatching this kernel from a long-lived process requires a prior
    subprocess proof (see utils.guarded_compile); otherwise fall back to
    the pure-XLA reference instead of risking a Mosaic remote-compile
    hang that would wedge the session's only chip."""
    if jax.default_backend() != "tpu":
        return True
    from ...utils.guarded_compile import kernel_allowed
    # non-default block sizes are a DIFFERENT Mosaic compile — key the
    # proof on them so a sweep config can't ride the 128x128 proof
    kid = "flash_attention"
    if (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K) != (128, 128):
        kid = f"flash_attention_q{DEFAULT_BLOCK_Q}k{DEFAULT_BLOCK_K}"
    return kernel_allowed(kid, "flash attention kernel")


def flash_attention(q, k, v, causal=True, sm_scale=None, q_offset=0,
                    kv_offset=0, block_q=DEFAULT_BLOCK_Q,
                    block_k=DEFAULT_BLOCK_K, interpret=None, kernel_layout=False):
    """Flash attention. Layout [b, s, h, d] (paddle flash-attn convention) or
    [b, h, s, d] with ``kernel_layout=True``. Differentiable (custom VJP with
    FA2-style blockwise recompute)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = _default_interpret()
    if not kernel_layout:
        q, k, v = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    if not interpret and not _mosaic_allowed():
        out = xla_attention(q, k, v, causal, sm_scale, q_offset, kv_offset)
    else:
        offs = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                          jnp.asarray(kv_offset, jnp.int32)])
        out = _flash(q, k, v, offs, causal, sm_scale, block_q, block_k,
                     interpret)
    if not kernel_layout:
        out = jnp.swapaxes(out, 1, 2)
    return out


def flash_attention_with_lse(q, k, v, causal=True, sm_scale=None, q_offset=0,
                             kv_offset=0, block_q=DEFAULT_BLOCK_Q,
                             block_k=DEFAULT_BLOCK_K, interpret=None):
    """Kernel-layout [b, h, s, d] flash attention returning (out, lse) for
    online-softmax merging across KV shards (ring attention)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = _default_interpret()
    if not interpret and not _mosaic_allowed():
        return xla_attention(q, k, v, causal, sm_scale, q_offset, kv_offset,
                             with_lse=True)
    offs = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                      jnp.asarray(kv_offset, jnp.int32)])
    return _flash_with_lse(q, k, v, offs, causal, sm_scale, block_q, block_k,
                           interpret)
