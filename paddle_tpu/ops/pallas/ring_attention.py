"""Ring flash attention — context parallelism over a mesh axis.

Reference analogue: PaddleNLP's ``RingFlashAttention`` built on core Paddle's
sep/cp comm group + ``batch_isend_irecv`` p2p KV rotation + the FA2 kernel's
``softmax_lse`` output (SURVEY.md §2.3 "CP / ring attention", §5.7 mechanism 3).

TPU-native design (SURVEY.md §5.7 "TPU-native plan"): runs inside
``shard_map`` over the 'sep' axis. Each device holds a sequence shard of
Q/K/V; KV shards rotate around the ring with ``lax.ppermute`` (lowered to ICI
neighbor exchanges) while each step's partial attention comes from the Pallas
flash kernel (``flash_attention_with_lse``) with *global* causal offsets, and
partials merge with the online-softmax combine. The whole loop is unrolled in
the trace (ring size is a static mesh-axis size) so XLA overlaps each
ppermute with the next step's compute.

Gradients: the flash kernel has a custom VJP and ppermute/merge are
differentiable, so ``jax.grad`` through this function yields the ring
backward (reverse rotation) automatically.

Note on load balance: with pure causal masking, later ring ranks do more
useful work per step (the classic ring-attention skew). The standard fix —
zigzag/striped sequence placement — is a data-layout choice left to the
caller; masking here stays exact for any offsets.
"""
from __future__ import annotations

import math
import os

import jax
import jax.numpy as jnp

from .flash_attention import flash_attention_with_lse, mha_reference, NEG_INF

#: PADDLE_SEP_RING_IMPL values (mirrors PADDLE_TPU_RAGGED_IMPL): "auto"
#: picks the kernel tier — interpret-pallas off-TPU, guarded Mosaic on a
#: real TPU (flash_attention_with_lse's canary falls back to XLA when the
#: subprocess proof is missing) — and "xla" forces the pure reference.
SEP_RING_IMPLS = ("auto", "kernel", "xla")


def sep_ring_impl():
    v = os.environ.get("PADDLE_SEP_RING_IMPL", "auto").lower()
    if v not in SEP_RING_IMPLS:
        raise ValueError(f"PADDLE_SEP_RING_IMPL {v!r} not in "
                         f"{SEP_RING_IMPLS}")
    return v


def _merge(out, lse, out_i, lse_i):
    """Online-softmax merge of two normalized partials (kernel layout)."""
    new_lse = jnp.logaddexp(lse, lse_i)
    w = jnp.exp(lse - new_lse)[..., None]
    w_i = jnp.exp(lse_i - new_lse)[..., None]
    return out * w + out_i * w_i, new_lse


def ring_partial(q, k, v, q_offset, kv_offset, sm_scale, impl=None,
                 interpret=None):
    """One ring step: normalized partial + lse for q (kernel layout
    [b, h, sq, d], global position ``q_offset``) against one KV block at
    global position ``kv_offset``, causal. Tiering matches
    ragged_paged_attention: ``auto``/``kernel`` route through
    ``flash_attention_with_lse`` (interpret-pallas off-TPU, Mosaic behind
    the guarded-compile canary with its own XLA fallback on TPU);
    ``xla`` is the zero-Pallas reference."""
    if impl is None:
        impl = sep_ring_impl()
    if impl == "xla":
        return mha_reference(q, k, v, causal=True, sm_scale=sm_scale,
                             q_offset=q_offset, kv_offset=kv_offset,
                             with_lse=True)
    return flash_attention_with_lse(q, k, v, causal=True,
                                    sm_scale=sm_scale, q_offset=q_offset,
                                    kv_offset=kv_offset,
                                    interpret=interpret)


def blockwise_causal_attention(q, q_offset, kv_blocks, sm_scale=None,
                               impl=None, interpret=None):
    """The ring-attention schedule run block-sequentially on one host:
    causal attention of ``q`` (kernel layout [b, h, sq, d] at global
    position ``q_offset``) over ``kv_blocks`` — a list of ``(k, v,
    kv_offset)`` tuples, each one ring step — merged with the
    online-softmax combine. Fully-masked blocks contribute lse=-inf and
    drop out of the merge exactly. This is the single-process stand-in
    for the sep-ring: block ``i`` is what replica ``i % sep_ways`` would
    compute, and because every block partial is a fixed-shape kernel
    call, the compiled-program set stays bounded by the stripe shape."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    out = jnp.zeros(q.shape, jnp.float32)
    lse = jnp.full(q.shape[:3], NEG_INF, jnp.float32)
    if impl is None:
        impl = sep_ring_impl()
    for k, v, kv_offset in kv_blocks:
        out_i, lse_i = ring_partial(q, k, v, q_offset, kv_offset,
                                    sm_scale, impl=impl,
                                    interpret=interpret)
        out, lse = _merge(out, lse, out_i.astype(jnp.float32), lse_i)
    return out.astype(q.dtype)


def ring_flash_attention(q, k, v, axis_name="sep", causal=True, sm_scale=None,
                         axis_size=None, interpret=None, use_kernel=True):
    """Blockwise ring attention over ``axis_name``; call inside shard_map/jit.

    q/k/v: local sequence shards, paddle layout [b, s_local, h, d].
    ``axis_size`` must be the static mesh-axis size (defaults to the global
    mesh's); ``use_kernel=False`` computes per-step partials with the pure-XLA
    reference instead of the Pallas kernel (debug/CPU path).
    """
    if axis_size is None:
        from ...distributed import mesh as mesh_mod
        axis_size = mesh_mod.axis_size(axis_name)
    n = int(axis_size)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])

    # -> kernel layout [b, h, s, d]
    q = jnp.swapaxes(q, 1, 2)
    k = jnp.swapaxes(k, 1, 2)
    v = jnp.swapaxes(v, 1, 2)
    s_local = q.shape[2]
    idx = jax.lax.axis_index(axis_name)
    q_off = idx * s_local

    out = jnp.zeros(q.shape, jnp.float32)
    lse = jnp.full(q.shape[:3], NEG_INF, jnp.float32)
    k_cur, v_cur = k, v
    perm = [(i, (i + 1) % n) for i in range(n)]

    for step in range(n):
        kv_idx = (idx - step) % n
        kv_off = kv_idx * s_local
        if use_kernel:
            out_i, lse_i = flash_attention_with_lse(
                q, k_cur, v_cur, causal=causal, sm_scale=sm_scale,
                q_offset=q_off, kv_offset=kv_off, interpret=interpret)
        else:
            out_i, lse_i = mha_reference(
                q, k_cur, v_cur, causal=causal, sm_scale=sm_scale,
                q_offset=q_off, kv_offset=kv_off, with_lse=True)
        out, lse = _merge(out, lse, out_i.astype(jnp.float32), lse_i)
        if step < n - 1:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)

    return jnp.swapaxes(out.astype(q.dtype), 1, 2)
