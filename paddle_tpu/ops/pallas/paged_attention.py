"""Paged-attention decode kernel (reference: the serving attention tier —
``paddle/phi/kernels/fusion/gpu/block_multihead_attention`` /
``fused_multi_transformer``'s paged KV cache; SURVEY.md §2.2 "Incubate"
serving block, VERDICT.md round-1 item 10).

TPU-native design: the KV cache lives in HBM as fixed-size pages
``[num_pages, page_size, kv_heads, head_dim]``; a per-sequence block table
maps logical context positions to pages (vLLM layout). One decode step
attends ONE query token per sequence over its paged context:

* grid ``(batch, pages_per_seq)`` — the page axis is the sequential minor
  dimension, accumulated with online softmax in VMEM scratch (the same
  streaming-softmax recurrence as the flash kernel);
* the page to fetch is data-dependent: ``block_tables`` rides in SMEM as a
  scalar-prefetch operand and the K/V BlockSpec ``index_map`` reads it to
  steer each page's HBM→VMEM DMA (Pallas' dynamic-block addressing — the
  TPU analogue of the CUDA kernel's pointer chasing);
* GQA: queries grouped ``[kv_heads, group, d]`` against the page's
  ``[page_size, kv_heads, d]`` — one MXU dot per page, no K/V repeats.

Unused block-table entries MUST be 0 (a valid page): their scores are
masked by ``context_lens`` but the DMA address must be in range.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float("-inf")


def _decode_kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
                   m_ref, l_ref, acc_ref, *, sm_scale, page_size,
                   pages_per_seq, group):
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    ctx = lens_ref[b]
    q = q_ref[0].astype(jnp.float32)               # [heads, d]
    k = k_ref[0].astype(jnp.float32)               # [page_size, kv, d]
    v = v_ref[0].astype(jnp.float32)
    kv_heads = k.shape[1]
    heads, d = q.shape
    qg = q.reshape(kv_heads, group, d)
    # s[kv, g, ps] = qg[kv, g, :] . k[ps, kv, :]
    s = jax.lax.dot_general(
        qg, k, (((2,), (2,)), ((0,), (1,))),
        preferred_element_type=jnp.float32) * sm_scale
    pos = p * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
    s = jnp.where(pos < ctx, s, NEG_INF)

    m_prev = m_ref[...][:, :, :1]                  # [kv, g, 1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    w = jnp.exp(s - m_new)                         # masked -> 0
    corr = jnp.exp(m_prev - m_new)
    l_new = l_ref[...][:, :, :1] * corr + jnp.sum(w, -1, keepdims=True)
    # acc[kv, g, d] += w[kv, g, ps] . v[ps, kv, d]
    pv = jax.lax.dot_general(
        w, v, (((2,), (0,)), ((0,), (1,))),
        preferred_element_type=jnp.float32)
    acc_ref[...] = acc_ref[...] * corr + pv
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(p == pages_per_seq - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...][:, :, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / l).reshape(heads, d).astype(o_ref.dtype)


def paged_attention(q, k_pages, v_pages, block_tables, context_lens, *,
                    sm_scale=None, interpret=False):
    """One-token decode attention over a paged KV cache.

    q              [batch, heads, head_dim]
    k_pages/v_pages [num_pages, page_size, kv_heads, head_dim]
    block_tables   [batch, pages_per_seq] int32 (unused entries = 0)
    context_lens   [batch] int32 — tokens already in context (incl. this one)
    -> [batch, heads, head_dim]
    """
    batch, heads, d = q.shape
    _, page_size, kv_heads, _ = k_pages.shape
    pages_per_seq = block_tables.shape[1]
    group = heads // kv_heads
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)

    kernel = functools.partial(
        _decode_kernel, sm_scale=sm_scale, page_size=page_size,
        pages_per_seq=pages_per_seq, group=group)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch, pages_per_seq),
        in_specs=[
            pl.BlockSpec((1, heads, d), lambda b, p, tbl, ln: (b, 0, 0)),
            pl.BlockSpec((1, page_size, kv_heads, d),
                         lambda b, p, tbl, ln: (tbl[b, p], 0, 0, 0)),
            pl.BlockSpec((1, page_size, kv_heads, d),
                         lambda b, p, tbl, ln: (tbl[b, p], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, heads, d), lambda b, p, tbl, ln: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((kv_heads, group, 128), jnp.float32),
            pltpu.VMEM((kv_heads, group, 128), jnp.float32),
            pltpu.VMEM((kv_heads, group, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((batch, heads, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(block_tables, jnp.int32),
      jnp.asarray(context_lens, jnp.int32), q, k_pages, v_pages)


def paged_attention_reference(q, k_pages, v_pages, block_tables,
                              context_lens):
    """Dense numpy-style oracle for tests."""
    batch, heads, d = q.shape
    _, page_size, kv_heads, _ = k_pages.shape
    group = heads // kv_heads
    outs = []
    for b in range(batch):
        ctx = int(context_lens[b])
        n_pages = -(-ctx // page_size)
        ks = jnp.concatenate([k_pages[int(block_tables[b, p])]
                              for p in range(n_pages)], axis=0)[:ctx]
        vs = jnp.concatenate([v_pages[int(block_tables[b, p])]
                              for p in range(n_pages)], axis=0)[:ctx]
        qb = q[b].reshape(kv_heads, group, d).astype(jnp.float32)
        s = jnp.einsum("kgd,skd->kgs", qb, ks.astype(jnp.float32))
        s = s / math.sqrt(d)
        w = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("kgs,skd->kgd", w, vs.astype(jnp.float32))
        outs.append(o.reshape(heads, d))
    return jnp.stack(outs).astype(q.dtype)
