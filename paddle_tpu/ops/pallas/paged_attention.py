"""Paged-attention decode kernel (reference: the serving attention tier —
``paddle/phi/kernels/fusion/gpu/block_multihead_attention`` /
``fused_multi_transformer``'s paged KV cache; SURVEY.md §2.2 "Incubate"
serving block, VERDICT.md round-1 item 10).

TPU-native design: the KV cache lives in HBM as fixed-size pages in
**kv-head-major** layout ``[kv_heads, num_pages, page_size, head_dim]`` —
each (head, page) block is a contiguous, tile-aligned ``[page_size, d]``
slab, so a page fetch is one aligned HBM→VMEM DMA and every in-kernel dot
is a plain 2-D MXU matmul (no batched dot_general, which Mosaic lowers
poorly). A per-sequence block table maps logical context positions to
pages (vLLM layout). One decode step attends ONE query token per sequence
over its paged context.

Three tiers, mirroring how the reference wires the vendored FA2 library
as a phi kernel (SURVEY.md §2.1 "Flash-attention integration"):

* on real TPU, the **in-repo kernel below is the default** once its
  canary has been proven in a disposable subprocess
  (``utils.guarded_compile`` — round 2 demonstrated a from-scratch
  Mosaic compile can hang the remote-compile tunnel, so first compiles
  only ever happen in a process that is safe to lose, and the proof
  includes a numeric parity check vs the dense reference);
* unproven/quarantined (or ``PADDLE_TPU_PAGED_IMPL=jax``): delegate to
  ``jax.experimental.pallas.ops.tpu.paged_attention`` — the
  production-hardened Mosaic kernel (manual double-buffered page DMA,
  megacore support). Note this still Mosaic-compiles, just a kernel
  that is known-good upstream;
* CPU tests / interpret mode run the in-repo kernel in interpret mode:
  grid ``(batch, kv_head, pages)``, block-table-steered dynamic
  BlockSpec index maps (scalar prefetch in SMEM), online-softmax scratch
  accumulation — the same streaming recurrence as the flash kernel.

Unused block-table entries MUST be 0 (a valid page): their scores are
masked by ``context_lens`` but the DMA address must be in range.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams; accept either name so
# the kernels (and their CPU interpret-mode tests) work across versions
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

NEG_INF = float("-inf")


def _decode_kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
                   m_ref, l_ref, acc_ref, *, sm_scale, page_size,
                   pages_per_seq, group):
    b = pl.program_id(0)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    ctx = lens_ref[b]
    q = q_ref[0, 0].astype(jnp.float32)            # [group, d]
    k = k_ref[0, 0].astype(jnp.float32)            # [page_size, d]
    v = v_ref[0, 0].astype(jnp.float32)
    # s[g, ps] — one plain 2-D MXU dot
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale
    pos = p * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(pos < ctx, s, NEG_INF)

    m_prev = m_ref[...][:, :1]                     # [g, 1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    w = jnp.exp(s - m_new)                         # masked -> 0
    corr = jnp.exp(m_prev - m_new)
    l_new = l_ref[...][:, :1] * corr + jnp.sum(w, -1, keepdims=True)
    pv = jax.lax.dot_general(                      # [g, d]
        w, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    acc_ref[...] = acc_ref[...] * corr + pv
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(p == pages_per_seq - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...][:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _decode_kernel_quant(tables_ref, lens_ref, q_ref, k_ref, v_ref, ks_ref,
                         vs_ref, o_ref, m_ref, l_ref, acc_ref, *, sm_scale,
                         page_size, pages_per_seq, group):
    """int8-KV variant of :func:`_decode_kernel`: the page blocks arrive
    as int8 rows plus one fp32 scale per (page, slot) row — dequantize
    in VMEM right before the MXU dots (the ``quant_matmul`` streaming
    discipline applied to the KV gather), so the fp32 pages never exist
    in HBM."""
    b = pl.program_id(0)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    ctx = lens_ref[b]
    q = q_ref[0, 0].astype(jnp.float32)            # [group, d]
    k = k_ref[0, 0].astype(jnp.float32) * ks_ref[0, 0][:, None]
    v = v_ref[0, 0].astype(jnp.float32) * vs_ref[0, 0][:, None]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale
    pos = p * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(pos < ctx, s, NEG_INF)

    m_prev = m_ref[...][:, :1]                     # [g, 1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    w = jnp.exp(s - m_new)                         # masked -> 0
    corr = jnp.exp(m_prev - m_new)
    l_new = l_ref[...][:, :1] * corr + jnp.sum(w, -1, keepdims=True)
    pv = jax.lax.dot_general(                      # [g, d]
        w, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    acc_ref[...] = acc_ref[...] * corr + pv
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(p == pages_per_seq - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...][:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _paged_attention_pallas_quant(q, k_pages, v_pages, k_scales, v_scales,
                                  block_tables, context_lens, *, sm_scale,
                                  interpret):
    batch, heads, d = q.shape
    kv_heads, _, page_size, _ = k_pages.shape
    pages_per_seq = block_tables.shape[1]
    group = heads // kv_heads
    qg = q.reshape(batch, kv_heads, group, d)

    kernel = functools.partial(
        _decode_kernel_quant, sm_scale=sm_scale, page_size=page_size,
        pages_per_seq=pages_per_seq, group=group)
    page_spec = pl.BlockSpec((1, 1, page_size, d),
                             lambda b, h, p, tbl, ln: (h, tbl[b, p], 0, 0))
    scale_spec = pl.BlockSpec((1, 1, page_size),
                              lambda b, h, p, tbl, ln: (h, tbl[b, p], 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch, kv_heads, pages_per_seq),
        in_specs=[
            pl.BlockSpec((1, 1, group, d),
                         lambda b, h, p, tbl, ln: (b, h, 0, 0)),
            page_spec, page_spec, scale_spec, scale_spec,
        ],
        out_specs=pl.BlockSpec((1, 1, group, d),
                               lambda b, h, p, tbl, ln: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((batch, kv_heads, group, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(block_tables, jnp.int32),
      jnp.asarray(context_lens, jnp.int32), qg, k_pages, v_pages,
      jnp.asarray(k_scales, jnp.float32), jnp.asarray(v_scales, jnp.float32))
    return out.reshape(batch, heads, d)


def _paged_attention_pallas(q, k_pages, v_pages, block_tables, context_lens,
                            *, sm_scale, interpret):
    batch, heads, d = q.shape
    kv_heads, _, page_size, _ = k_pages.shape
    pages_per_seq = block_tables.shape[1]
    group = heads // kv_heads
    qg = q.reshape(batch, kv_heads, group, d)

    kernel = functools.partial(
        _decode_kernel, sm_scale=sm_scale, page_size=page_size,
        pages_per_seq=pages_per_seq, group=group)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch, kv_heads, pages_per_seq),
        in_specs=[
            pl.BlockSpec((1, 1, group, d),
                         lambda b, h, p, tbl, ln: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, page_size, d),
                         lambda b, h, p, tbl, ln: (h, tbl[b, p], 0, 0)),
            pl.BlockSpec((1, 1, page_size, d),
                         lambda b, h, p, tbl, ln: (h, tbl[b, p], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, group, d),
                               lambda b, h, p, tbl, ln: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((batch, kv_heads, group, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(block_tables, jnp.int32),
      jnp.asarray(context_lens, jnp.int32), qg, k_pages, v_pages)
    return out.reshape(batch, heads, d)


def paged_attention(q, k_pages, v_pages, block_tables, context_lens, *,
                    sm_scale=None, k_scales=None, v_scales=None,
                    interpret=False):
    """One-token decode attention over a paged KV cache.

    q              [batch, heads, head_dim]
    k_pages/v_pages [kv_heads, num_pages, page_size, head_dim]
    block_tables   [batch, pages_per_seq] int32 (unused entries = 0)
    context_lens   [batch] int32 — tokens already in context (incl. this one)
    k_scales/v_scales [kv_heads, num_pages, page_size] f32 — per-row
                   dequant scales for int8 pages (None = native pages)
    -> [batch, heads, head_dim]
    """
    batch, heads, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if k_scales is not None:
        # int8 KV pages: dequantize in the gather tier. On real TPU the
        # quant kernel runs only once ITS canary is proven (the jax
        # production kernel has no dequant hook, so the XLA tier is the
        # fallback instead).
        if not interpret and jax.default_backend() == "tpu":
            import os
            impl = os.environ.get("PADDLE_TPU_PAGED_IMPL", "auto").lower()
            if impl != "xla":
                from ...utils.guarded_compile import kernel_allowed
                if impl == "inrepo" or kernel_allowed(
                        "paged_attention_int8",
                        "int8-KV paged attention kernel",
                        fallback="the XLA dequant-gather tier"):
                    return _paged_attention_pallas_quant(
                        q, k_pages, v_pages, k_scales, v_scales,
                        block_tables, context_lens, sm_scale=sm_scale,
                        interpret=False)
            return _paged_attention_xla(
                q, k_pages, v_pages, block_tables, context_lens,
                sm_scale=sm_scale, k_scales=k_scales, v_scales=v_scales)
        return _paged_attention_pallas_quant(
            q, k_pages, v_pages, k_scales, v_scales, block_tables,
            context_lens, sm_scale=sm_scale, interpret=interpret)
    if not interpret and jax.default_backend() == "tpu":
        # Impl choice on real TPU (VERDICT.md round-2 item 3): the
        # in-repo kernel is the default once its canary has been proven
        # in a disposable subprocess (utils.guarded_compile); the
        # production jax kernel remains as the fallback tier and can be
        # forced with PADDLE_TPU_PAGED_IMPL=jax.
        import os
        impl = os.environ.get("PADDLE_TPU_PAGED_IMPL", "auto").lower()
        if impl == "xla":
            # zero-Mosaic tier: sessions where the tunnel's Mosaic compile
            # service is wedged (rounds 2-4) can still decode on-chip —
            # every op here is plain XLA
            return _paged_attention_xla(q, k_pages, v_pages, block_tables,
                                        context_lens, sm_scale=sm_scale)
        if impl != "jax":
            from ...utils.guarded_compile import kernel_allowed
            if impl == "inrepo" or kernel_allowed(
                    "paged_attention", "paged attention kernel",
                    fallback="jax's production paged-attention kernel"):
                return _paged_attention_pallas(
                    q, k_pages, v_pages, block_tables, context_lens,
                    sm_scale=sm_scale, interpret=False)
        from jax.experimental.pallas.ops.tpu.paged_attention import (
            paged_attention as _jax_paged)
        pages_per_seq = block_tables.shape[1]
        ppcb = next(n for n in (8, 4, 2, 1) if pages_per_seq % n == 0)
        # the production kernel applies no softmax scale: fold into q
        return _jax_paged(
            (q * sm_scale).astype(q.dtype), k_pages, v_pages,
            jnp.asarray(context_lens, jnp.int32),
            jnp.asarray(block_tables, jnp.int32),
            pages_per_compute_block=ppcb)
    return _paged_attention_pallas(q, k_pages, v_pages, block_tables,
                                   context_lens, sm_scale=sm_scale,
                                   interpret=interpret)


def _paged_attention_xla(q, k_pages, v_pages, block_tables, context_lens,
                         *, sm_scale, k_scales=None, v_scales=None):
    """Vectorized jittable XLA decode attention over the paged cache: one
    gather materializes each sequence's pages as dense KV (dequantized
    when int8 row scales are given), then masked softmax-attention.
    O(batch·S_max) HBM for the gathered KV — the fallback trades the
    paged kernel's memory win for wedge-free compiles."""
    kv_heads, _, page_size, d = k_pages.shape
    batch, heads, _ = q.shape
    group = heads // kv_heads
    kg, vg = k_pages[:, block_tables], v_pages[:, block_tables]
    if k_scales is not None:
        kg = kg.astype(jnp.float32) * k_scales[:, block_tables][..., None]
        vg = vg.astype(jnp.float32) * v_scales[:, block_tables][..., None]
    # [kv_heads, batch, pages_per_seq, page_size, d] -> [b, kv, S, d]
    ks = jnp.moveaxis(kg, 1, 0).reshape(batch, kv_heads, -1, d)
    vs = jnp.moveaxis(vg, 1, 0).reshape(batch, kv_heads, -1, d)
    qb = (q * sm_scale).reshape(batch, kv_heads, group, d)
    s = jnp.einsum("bkgd,bksd->bkgs", qb.astype(jnp.float32),
                   ks.astype(jnp.float32))
    valid = (jnp.arange(ks.shape[2])[None, :]
             < jnp.asarray(context_lens, jnp.int32)[:, None])
    s = jnp.where(valid[:, None, None, :], s, -jnp.inf)
    w = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgs,bksd->bkgd", w, vs.astype(jnp.float32))
    return o.reshape(batch, heads, d).astype(q.dtype)


def paged_attention_reference(q, k_pages, v_pages, block_tables,
                              context_lens):
    """Dense numpy-style oracle for tests (kv-major page layout)."""
    batch, heads, d = q.shape
    kv_heads, _, page_size, _ = k_pages.shape
    group = heads // kv_heads
    outs = []
    for b in range(batch):
        ctx = int(context_lens[b])
        n_pages = -(-ctx // page_size)
        ks = jnp.concatenate([k_pages[:, int(block_tables[b, p])]
                              for p in range(n_pages)], axis=1)[:, :ctx]
        vs = jnp.concatenate([v_pages[:, int(block_tables[b, p])]
                              for p in range(n_pages)], axis=1)[:, :ctx]
        qb = q[b].reshape(kv_heads, group, d).astype(jnp.float32)
        s = jnp.einsum("kgd,ksd->kgs", qb, ks.astype(jnp.float32))
        s = s / math.sqrt(d)
        w = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("kgs,ksd->kgd", w, vs.astype(jnp.float32))
        outs.append(o.reshape(heads, d))
    return jnp.stack(outs).astype(q.dtype)
