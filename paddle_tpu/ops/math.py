"""Elementwise math + reductions (reference: ``python/paddle/tensor/math.py``,
``stat.py``, ``ops.py`` — SURVEY.md §2.2; canonical paths, unverified).

Every op is a thin pure-jnp function wrapped by the autograd dispatcher
(:func:`paddle_tpu.autograd.tape.defop`); XLA does the fusion."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..framework import dtype as dtypes
from ..autograd.tape import apply, defop
from ..framework.dtype import INT_DTYPE


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


# ---------------------------------------------------------------------------
# binary elementwise
# ---------------------------------------------------------------------------

@defop
def add(x, y):
    return jnp.add(x, y)


@defop
def subtract(x, y):
    return jnp.subtract(x, y)


@defop
def multiply(x, y):
    return jnp.multiply(x, y)


@defop
def divide(x, y):
    return jnp.true_divide(x, y)


@defop
def floor_divide(x, y):
    return jnp.floor_divide(x, y)


@defop
def mod(x, y):
    return jnp.mod(x, y)


remainder = mod
floor_mod = mod


@defop
def pow(x, y):
    return jnp.power(x, y)


@defop
def maximum(x, y):
    return jnp.maximum(x, y)


@defop
def minimum(x, y):
    return jnp.minimum(x, y)


@defop
def fmax(x, y):
    return jnp.fmax(x, y)


@defop
def fmin(x, y):
    return jnp.fmin(x, y)


@defop
def atan2(x, y):
    return jnp.arctan2(x, y)


@defop
def hypot(x, y):
    return jnp.hypot(x, y)


@defop
def heaviside(x, y):
    return jnp.heaviside(x, y)


@defop
def lerp(x, y, weight):
    return x + weight * (y - x)


@defop
def logaddexp(x, y):
    return jnp.logaddexp(x, y)


@defop
def nextafter(x, y):
    return jnp.nextafter(x, y)


@defop
def copysign(x, y):
    return jnp.copysign(x, y)


@defop
def gcd(x, y):
    return jnp.gcd(x, y)


@defop
def lcm(x, y):
    return jnp.lcm(x, y)


def divide_no_nan(x, y):
    return apply(lambda a, b: jnp.where(b == 0, 0.0, a / jnp.where(b == 0, 1.0, b)),
                 x, y, op_name="divide_no_nan")


# ---------------------------------------------------------------------------
# unary elementwise
# ---------------------------------------------------------------------------

def _unary(name, fn):
    def op(x):
        return fn(x)
    op.__name__ = op.__qualname__ = name   # before defop closes over it
    return defop(op)


exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", jax.lax.rsqrt)
square = _unary("square", jnp.square)
abs = _unary("abs", jnp.abs)
sign = _unary("sign", jnp.sign)
neg = _unary("neg", jnp.negative)
negative = neg
reciprocal = _unary("reciprocal", jnp.reciprocal)
floor = _unary("floor", jnp.floor)
ceil = _unary("ceil", jnp.ceil)
round = _unary("round", jnp.round)
trunc = _unary("trunc", jnp.trunc)
frac = _unary("frac", lambda x: x - jnp.trunc(x))
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
logit = _unary("logit", jax.scipy.special.logit)
digamma = _unary("digamma", jax.scipy.special.digamma)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)
i0 = _unary("i0", jax.scipy.special.i0)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
angle = _unary("angle", jnp.angle)
conj = _unary("conj", jnp.conj)
real = _unary("real", jnp.real)
imag = _unary("imag", jnp.imag)


@defop
def clip(x, min=None, max=None):
    mn = min.item() if isinstance(min, Tensor) else min
    mx = max.item() if isinstance(max, Tensor) else max
    return jnp.clip(x, mn, mx)


@defop
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None):
    out = x * scale + bias if bias_after_scale else (x + bias) * scale
    return out


@defop
def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


@defop
def multiplex(inputs, index):
    stacked = jnp.stack(inputs, axis=0)  # [n, batch, ...]
    idx = index.reshape(-1)
    return stacked[idx, jnp.arange(stacked.shape[1])]


@defop
def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


@defop
def trapezoid(y, x=None, dx=None, axis=-1):
    return jnp.trapezoid(y, x=x, dx=1.0 if dx is None and x is None else dx, axis=axis)


@defop
def cumulative_trapezoid(y, x=None, dx=None, axis=-1):
    ax = int(axis) % y.ndim
    n = y.shape[ax]
    lo = jax.lax.slice_in_dim(y, 0, n - 1, axis=ax)
    hi = jax.lax.slice_in_dim(y, 1, n, axis=ax)
    if x is not None:
        xa = jnp.asarray(x)
        if xa.ndim == 1:
            shape = [1] * y.ndim
            shape[ax] = n
            xa = xa.reshape(shape)
        d = (jax.lax.slice_in_dim(xa, 1, n, axis=ax)
             - jax.lax.slice_in_dim(xa, 0, n - 1, axis=ax))
    else:
        d = 1.0 if dx is None else dx
    return jnp.cumsum((lo + hi) * 0.5 * d, axis=ax)


@defop
def sgn(x):
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        mag = jnp.abs(x)
        return jnp.where(mag == 0, jnp.zeros_like(x), x / jnp.where(mag == 0, 1, mag))
    return jnp.sign(x)


@defop
def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary"):
    # x: [*, P, M], y: [*, R, M] -> [*, P, R]
    if p == 2.0 and compute_mode != "donot_use_mm_for_euclid_dist":
        # MXU-friendly: |x-y|^2 = |x|^2 + |y|^2 - 2 x.y; zero distances
        # are masked out of the sqrt so the gradient is a 0 subgradient
        # there (cdist(x, x) diagonal) instead of inf*0 = NaN
        x2 = jnp.sum(x * x, axis=-1)[..., :, None]
        y2 = jnp.sum(y * y, axis=-1)[..., None, :]
        xy = jnp.matmul(x, jnp.swapaxes(y, -1, -2))
        d2 = jnp.maximum(x2 + y2 - 2.0 * xy, 0.0)
        safe = jnp.where(d2 == 0.0, 1.0, d2)
        return jnp.where(d2 == 0.0, 0.0, jnp.sqrt(safe))
    diff_ = x[..., :, None, :] - y[..., None, :, :]
    if p == 0:
        return jnp.sum((diff_ != 0).astype(x.dtype), axis=-1)
    if jnp.isinf(p):
        return jnp.max(jnp.abs(diff_), axis=-1)
    return jnp.sum(jnp.abs(diff_) ** p, axis=-1) ** (1.0 / p)


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------

@defop
def sum(x, axis=None, dtype=None, keepdim=False):
    dt = dtypes.convert_dtype(dtype) if dtype else None
    return jnp.sum(x, axis=_axis(axis), dtype=dt, keepdims=keepdim)


@defop
def mean(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=_axis(axis), keepdims=keepdim)


@defop
def prod(x, axis=None, keepdim=False, dtype=None):
    dt = dtypes.convert_dtype(dtype) if dtype else None
    return jnp.prod(x, axis=_axis(axis), dtype=dt, keepdims=keepdim)


@defop
def max(x, axis=None, keepdim=False):
    return jnp.max(x, axis=_axis(axis), keepdims=keepdim)


@defop
def min(x, axis=None, keepdim=False):
    return jnp.min(x, axis=_axis(axis), keepdims=keepdim)


@defop
def amax(x, axis=None, keepdim=False):
    return jnp.max(x, axis=_axis(axis), keepdims=keepdim)


@defop
def amin(x, axis=None, keepdim=False):
    return jnp.min(x, axis=_axis(axis), keepdims=keepdim)


@defop
def logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=_axis(axis), keepdims=keepdim)


@defop
def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim)


@defop
def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim)


@defop
def median(x, axis=None, keepdim=False):
    return jnp.median(x, axis=_axis(axis), keepdims=keepdim)


@defop
def nanmedian(x, axis=None, keepdim=False):
    return jnp.nanmedian(x, axis=_axis(axis), keepdims=keepdim)


@defop
def quantile(x, q, axis=None, keepdim=False):
    return jnp.quantile(x, jnp.asarray(q), axis=_axis(axis), keepdims=keepdim)


@defop
def nansum(x, axis=None, dtype=None, keepdim=False):
    return jnp.nansum(x, axis=_axis(axis),
                      dtype=dtypes.convert_dtype(dtype) if dtype else None,
                      keepdims=keepdim)


@defop
def nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=_axis(axis), keepdims=keepdim)


@defop
def count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=_axis(axis), keepdims=keepdim).astype(INT_DTYPE)


@defop
def cumsum(x, axis=None, dtype=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jnp.cumsum(x, axis=int(axis),
                      dtype=dtypes.convert_dtype(dtype) if dtype else None)


@defop
def cumprod(x, dim=None, dtype=None):
    return jnp.cumprod(x, axis=int(dim),
                       dtype=dtypes.convert_dtype(dtype) if dtype else None)


def _cum_extreme(x, axis, is_max):
    """(values, indices) running max/min via pairwise associative scan;
    ties keep the earliest index (paddle/torch semantics)."""
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    idx_shape = [1] * x.ndim
    idx_shape[axis] = x.shape[axis]
    idx = jnp.broadcast_to(
        jnp.arange(x.shape[axis]).reshape(idx_shape), x.shape)

    def combine(a, b):
        av, ai = a
        bv, bi = b
        take_b = (bv > av) if is_max else (bv < av)
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

    vals, inds = jax.lax.associative_scan(combine, (x, idx), axis=axis)
    return vals, inds.astype(INT_DTYPE)


@defop
def cummax(x, axis=None):
    return _cum_extreme(x, axis, True)


@defop
def cummin(x, axis=None):
    return _cum_extreme(x, axis, False)


@defop
def logcumsumexp(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jnp.log(jnp.cumsum(jnp.exp(x - jax.lax.stop_gradient(jnp.max(x))), axis=axis)) \
        + jax.lax.stop_gradient(jnp.max(x))


# ---------------------------------------------------------------------------
# matrix
# ---------------------------------------------------------------------------

@defop
def matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim >= 2 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim >= 2 else y
    return jnp.matmul(x, y)


mm = matmul


@defop
def bmm(x, y):
    return jnp.matmul(x, y)


@defop
def dot(x, y):
    return jnp.sum(x * y, axis=-1)


@defop
def inner(x, y):
    return jnp.inner(x, y)


@defop
def outer(x, y):
    return jnp.outer(x, y)


@defop
def addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * jnp.matmul(x, y)


@defop
def kron(x, y):
    return jnp.kron(x, y)


@defop
def cross(x, y, axis=9):
    ax = axis if axis != 9 else (x.ndim - 1 if x.shape[-1] == 3 else
                                 next(i for i, s in enumerate(x.shape) if s == 3))
    return jnp.cross(x, y, axis=ax)


@defop
def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset, axis1, axis2)


@defop
def t(x):
    return x.T if x.ndim <= 2 else jnp.swapaxes(x, -1, -2)


def einsum(equation, *operands):
    return apply(lambda *ops: jnp.einsum(equation, *ops), *operands, op_name="einsum")


# ---------------------------------------------------------------------------
# checks
# ---------------------------------------------------------------------------

@defop
def isnan(x):
    return jnp.isnan(x)


@defop
def isinf(x):
    return jnp.isinf(x)


@defop
def isfinite(x):
    return jnp.isfinite(x)


def isclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return apply(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                 x, y, op_name="isclose")


def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return apply(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                 x, y, op_name="allclose")


def equal_all(x, y):
    return apply(lambda a, b: jnp.array_equal(a, b), x, y, op_name="equal_all")


@defop
def histogram(x, bins=100, min=0, max=0):
    rng = None if (min == 0 and max == 0) else (min, max)
    h, _ = jnp.histogram(x, bins=bins, range=rng)
    return h.astype(INT_DTYPE)


@defop
def bincount(x, weights=None, minlength=0):
    return jnp.bincount(x, weights=weights, minlength=minlength,
                        length=None)


@defop
def increment(x, value=1.0):
    return x + value


def all(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply(lambda a: jnp.all(a.astype(bool), axis=axis,
                                   keepdims=keepdim), x, op_name="all")


def any(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply(lambda a: jnp.any(a.astype(bool), axis=axis,
                                   keepdims=keepdim), x, op_name="any")


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    def fn(a, *extra):
        pre = extra[0] if prepend is not None else None
        app = extra[-1] if append is not None else None
        return jnp.diff(a, n=n, axis=axis, prepend=pre, append=app)

    args = [x] + [t for t in (prepend, append) if t is not None]
    return apply(fn, *args, op_name="diff")


def mv(x, vec, name=None):
    return apply(lambda a, b: a @ b, x, vec, op_name="mv")


def take(x, index, mode="raise", name=None):
    if mode == "raise":
        # jnp has no in-trace raise mode; match the reference's eager
        # behavior with a bounds check when the index is concrete (under
        # jit this degrades to clip, documented). The check reduces on
        # device and fetches ONE scalar — not the whole index array.
        from ..framework.core import Tensor as _T
        idx_val = index._data if isinstance(index, _T) else index
        if not isinstance(idx_val, jax.core.Tracer):
            n = 1
            for s in (x._data.shape if isinstance(x, _T) else x.shape):
                n *= s
            idx_arr = jnp.asarray(idx_val)
            if idx_arr.size and bool(jnp.any((idx_arr < -n) |
                                             (idx_arr >= n))):
                raise IndexError(
                    f"paddle.take: index out of range for input with "
                    f"{n} elements (mode='raise')")

    def fn(a, idx):
        flat = a.reshape(-1)
        if mode == "raise":
            # negatives are valid python-style indices in raise mode, but
            # jnp's clip mode would clamp them to 0 — normalize first
            idx = jnp.where(idx < 0, idx + flat.shape[0], idx)
        m = {"raise": "clip", "clip": "clip", "wrap": "wrap"}[mode]
        return jnp.take(flat, idx, mode=m)

    return apply(fn, x, index, op_name="take")


def broadcast_shape(x_shape, y_shape):
    import numpy as _np
    return list(_np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


# ---------------------------------------------------------------------------
# breadth batch (round 2): reference python/paddle/tensor/math.py additions
# ---------------------------------------------------------------------------

def add_n(inputs, name=None):
    """paddle.add_n — elementwise sum of a list of tensors."""
    import functools as _ft
    import operator as _op
    if isinstance(inputs, Tensor):
        return apply(lambda a: a, inputs, op_name="add_n")
    # NB: module-level ``sum`` is the paddle reduction op, not the builtin
    return apply(lambda *ts: _ft.reduce(_op.add, ts), *inputs,
                 op_name="add_n")


@defop
def clip_by_norm(x, max_norm):
    n = jnp.sqrt(jnp.sum(x.astype(jnp.float32) ** 2))
    scale = jnp.where(n > max_norm, max_norm / jnp.maximum(n, 1e-12), 1.0)
    return (x.astype(jnp.float32) * scale).astype(x.dtype)


@defop
def ldexp(x, y):
    # jnp.ldexp scales incrementally: no 2**y intermediate overflow
    return jnp.ldexp(x.astype(jnp.float32), y.astype(jnp.int32))


@defop
def frexp(x):
    m, e = jnp.frexp(x)
    return m, e.astype(jnp.int32)


sinc = _unary("sinc", jnp.sinc)
signbit = _unary("signbit", jnp.signbit)
isneginf = _unary("isneginf", jnp.isneginf)
isposinf = _unary("isposinf", jnp.isposinf)
isreal = _unary("isreal", jnp.isreal)
i0e = _unary("i0e", jax.scipy.special.i0e)
i1 = _unary("i1", jax.scipy.special.i1)
i1e = _unary("i1e", jax.scipy.special.i1e)


@defop
def polygamma(x, n=1):
    return jax.scipy.special.polygamma(n, x)


@defop
def gammainc(x, y):
    """Regularized lower incomplete gamma (paddle.gammainc(x, y) = P(x, y))."""
    return jax.scipy.special.gammainc(x, y)


@defop
def gammaincc(x, y):
    return jax.scipy.special.gammaincc(x, y)


igamma = gammainc
igammac = gammaincc


@defop
def multigammaln(x, p):
    return jax.scipy.special.multigammaln(x, p)


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    def fn(a):
        return jnp.nanquantile(a, q, axis=_axis(axis), keepdims=keepdim)
    return apply(fn, x, op_name="nanquantile")


def renorm(x, p, axis, max_norm, name=None):
    """Per-slice p-norm clamp along ``axis`` (reference paddle.renorm)."""
    def fn(a):
        red = tuple(d for d in range(a.ndim) if d != (axis % a.ndim))
        norms = jnp.sum(jnp.abs(a.astype(jnp.float32)) ** p, axis=red,
                        keepdims=True) ** (1.0 / p)
        scale = jnp.where(norms > max_norm,
                          max_norm / jnp.maximum(norms, 1e-12), 1.0)
        return (a * scale).astype(a.dtype)
    return apply(fn, x, op_name="renorm")


@defop
def bitwise_left_shift(x, y):
    return jnp.left_shift(x, y)


@defop
def bitwise_right_shift(x, y):
    return jnp.right_shift(x, y)


@defop
def cartesian_prod(x):
    """Cartesian product of a list of 1-D tensors (paddle.cartesian_prod);
    a single input returns it unchanged (reference shape semantics)."""
    if len(x) == 1:
        return jnp.asarray(x[0])
    grids = jnp.meshgrid(*x, indexing="ij")
    return jnp.stack([g.reshape(-1) for g in grids], axis=-1)


@defop
def combinations(x, r=2, with_replacement=False):
    import itertools
    n = x.shape[0]
    it = (itertools.combinations_with_replacement(range(n), r)
          if with_replacement else itertools.combinations(range(n), r))
    idx = np.array(list(it), np.int32).reshape(-1, r)
    return x[idx]


@defop
def float_power(x, y):
    return jnp.float_power(x, y)


@defop
def vdot(x, y):
    return jnp.vdot(x, y)


@defop
def nanargmax(x, axis=None, keepdim=False):
    return jnp.nanargmax(x, axis=_axis(axis), keepdims=keepdim)


@defop
def nanargmin(x, axis=None, keepdim=False):
    return jnp.nanargmin(x, axis=_axis(axis), keepdims=keepdim)


@defop
def positive(x):
    return +x


@defop
def isin(x, test_x, assume_unique=False, invert=False):
    return jnp.isin(x, test_x, assume_unique=assume_unique, invert=invert)


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    def fn(a, *w):
        return jnp.histogramdd(a, bins=bins, range=ranges,
                               density=density,
                               weights=w[0] if w else None)
    args = (x,) + ((weights,) if weights is not None else ())
    return apply(fn, *args, op_name="histogramdd")


@defop
def gammaln(x):
    """paddle.gammaln — log|Gamma(x)| (same kernel family as lgamma)."""
    return jax.lax.lgamma(x)


def histogram_bin_edges(x, bins=100, min=0, max=0, name=None):
    """paddle.histogram_bin_edges — the bin edges histogram() would use."""
    def fn(a):
        lo, hi = float(min), float(max)
        if lo == 0 and hi == 0:
            return jnp.histogram_bin_edges(a, bins=int(bins))
        return jnp.histogram_bin_edges(a, bins=int(bins), range=(lo, hi))
    return apply(fn, x, op_name="histogram_bin_edges")


def reduce_as(x, target, name=None):
    """paddle.reduce_as — sum x down to target's (broadcast-compatible)
    shape: the transpose of broadcasting, used by backward composition."""
    tgt = tuple(target.shape) if hasattr(target, "shape") else tuple(target)

    def fn(a):
        extra = a.ndim - len(tgt)
        out = a.sum(axis=tuple(range(extra))) if extra else a
        keep = tuple(i for i, (s, t) in enumerate(zip(out.shape, tgt))
                     if s != t and t == 1)
        return out.sum(axis=keep, keepdims=True) if keep else out
    return apply(fn, x, op_name="reduce_as")


def pdist(x, p=2.0, name=None):
    """paddle.pdist — condensed pairwise distances of the rows of a 2-D
    tensor (upper triangle of cdist(x, x), k=1)."""
    def fn(a):
        n = a.shape[0]
        diff = a[:, None, :] - a[None, :, :]
        if p == 2.0:
            d = jnp.sqrt(jnp.maximum((diff * diff).sum(-1), 0.0))
        elif p == 0:
            d = (diff != 0).sum(-1).astype(a.dtype)
        elif p == float("inf"):
            d = jnp.abs(diff).max(-1)
        else:
            d = (jnp.abs(diff) ** p).sum(-1) ** (1.0 / p)
        iu, ju = jnp.triu_indices(n, k=1)
        return d[iu, ju]
    return apply(fn, x, op_name="pdist")


def top_p_sampling(x, ps, threshold=None, seed=None, name=None):
    """paddle.tensor.top_p_sampling — nucleus sampling over the last axis
    of probabilities ``x`` with per-row cumulative threshold ``ps``.
    Returns (selected probability, selected index)."""
    from ..framework import random as prandom

    def fn(probs, p_row):
        if threshold is not None:
            # reference threshold mode: tokens below it never sample
            probs = jnp.where(probs >= threshold, probs, 0.0)
        sorted_p = jnp.sort(probs, axis=-1)[..., ::-1]
        csum = jnp.cumsum(sorted_p, axis=-1)
        # keep the smallest prefix with cumulative mass >= ps
        keep_sorted = csum - sorted_p < p_row[..., None]
        kth = jnp.sum(keep_sorted, axis=-1) - 1
        cutoff = jnp.take_along_axis(sorted_p, kth[..., None], axis=-1)
        masked = jnp.where(probs >= cutoff, probs, 0.0)
        logits = jnp.log(jnp.maximum(masked, 1e-30))
        key = prandom.next_key() if seed is None else jax.random.key(seed)
        idx = jax.random.categorical(key, logits, axis=-1)
        val = jnp.take_along_axis(probs, idx[..., None], axis=-1)
        return val, idx[..., None].astype(INT_DTYPE)
    return apply(fn, x, ps, op_name="top_p_sampling")
