"""Flat op namespace: everything paddle exposes at top level lives here.

Replaces the reference's generated ``_C_ops`` + ``python/paddle/tensor/*``
wrappers (SURVEY.md §3.1 call stack) — dispatch is the autograd tape in
``paddle_tpu/autograd/tape.py``; kernels are jnp/lax, compiled by XLA."""
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from . import linalg  # noqa: F401
