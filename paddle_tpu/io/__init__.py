"""paddle.io — Dataset / DataLoader (reference: ``python/paddle/io/`` —
SURVEY.md §2.2/§3.5: multiprocess workers + index queues + reorder + pinned
double-buffered H2D prefetch in ``buffered_reader.cc``).

TPU-native pipeline: worker processes produce numpy batches → a background
thread converts + ``jax.device_put``s them with prefetch depth 2 (the
buffered_reader analogue) so the accelerator never waits on host collate.
"""
from __future__ import annotations

import itertools
import math
import os
import queue
import threading
import multiprocessing as mp

import numpy as np
import jax

from ..framework.core import Tensor
from ..framework import random as prandom

_TELEMETRY = None      # lazily bound registry families


def _telemetry():
    """DataLoader metrics in the unified registry: how long the train
    loop WAITED for each batch (a stalled input pipeline shows up here
    long before it shows in step time), prefetch-queue depth (0 = the
    accelerator is starved, full = input-bound nowhere), and worker
    failures."""
    global _TELEMETRY
    if _TELEMETRY is None:
        from ..profiler.telemetry import get_registry
        r = get_registry()
        _TELEMETRY = {
            "wait": r.histogram("paddle_dataloader_batch_wait_seconds",
                                "train-loop wall time blocked waiting for "
                                "the next batch"),
            "batches": r.counter("paddle_dataloader_batches_total",
                                 "batches handed to the consumer"),
            "depth": r.gauge("paddle_dataloader_queue_depth",
                             "prefetch queue depth at the last batch "
                             "handoff"),
            "failures": r.counter("paddle_dataloader_worker_failures_total",
                                  "worker pools torn down because a worker "
                                  "process died or raised"),
        }
    return _TELEMETRY


# ---------------------------------------------------------------------------
# datasets
# ---------------------------------------------------------------------------

class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cum[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        di = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if di == 0 else self.cum[di - 1]
        return self.datasets[di][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):
        n = len(dataset)
        lengths = [int(math.floor(n * f)) for f in lengths]
        lengths[-1] += len(dataset) - sum(lengths)
    perm = np.random.permutation(len(dataset)).tolist()
    out = []
    offset = 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset:offset + l]))
        offset += l
    return out


# ---------------------------------------------------------------------------
# samplers
# ---------------------------------------------------------------------------

class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray([float(w) for w in weights])
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        return iter(np.random.choice(len(self.weights), self.num_samples,
                                     replace=self.replacement, p=p).tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    """reference ``paddle.io.SubsetRandomSampler`` — random permutation of
    an explicit index subset."""

    def __init__(self, indices):
        self.indices = list(indices)

    def __iter__(self):
        return iter(np.random.permutation(self.indices).tolist())

    def __len__(self):
        return len(self.indices)


class BatchSampler(Sampler):
    """Default batch sampler, now deterministically resumable: with a
    ``seed`` the shuffle order is a pure function of ``(seed, epoch)``,
    and ``state_dict()``/``set_state_dict()`` (epoch, consumed batches,
    seed) let a restored loader skip exactly the batches already handed
    out instead of replaying the epoch (the elastic loop /
    ``TrainingSupervisor`` resume contract). Without a seed the legacy
    behavior (global-RNG shuffle) is unchanged — resumable only for
    unshuffled iteration."""

    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False, seed=None):
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self._own_sampler = sampler is None
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    _consumed = 0       # batches yielded so far this epoch
    _resume_from = 0    # one-shot skip armed by set_state_dict

    def _index_iter(self):
        if self.shuffle and self.seed is not None and self._own_sampler:
            n = len(self.sampler.data_source)
            rng = np.random.RandomState((int(self.seed) + self.epoch)
                                        % (2 ** 31))
            return iter(rng.permutation(n).tolist())
        return iter(self.sampler)

    def __iter__(self):
        skip, self._resume_from = self._resume_from, 0
        if skip and self.shuffle and self._own_sampler and self.seed is None:
            raise ValueError(
                "BatchSampler resume with shuffle=True needs a seed "
                "(the shuffle order is otherwise unreproducible)")
        produced = 0
        batch = []
        for idx in self._index_iter():
            batch.append(idx)
            if len(batch) == self.batch_size:
                produced += 1
                if produced > skip:
                    self._consumed = produced
                    yield batch
                batch = []
        if batch and not self.drop_last:
            produced += 1
            if produced > skip:
                self._consumed = produced
                yield batch
        if skip > produced:
            raise ValueError(
                f"sampler resume state skips {skip} batches but this epoch "
                f"has only {produced} — the checkpoint was taken with a "
                "different batch size / dataset")
        self._consumed = 0             # exhausted: next epoch is fresh

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = int(epoch)

    def state_dict(self):
        return {"epoch": self.epoch, "consumed_batches": self._consumed,
                "seed": self.seed}

    def set_state_dict(self, state):
        self.epoch = int(state.get("epoch", 0))
        if state.get("seed") is not None:
            self.seed = state["seed"]
        self._resume_from = int(state.get("consumed_batches", 0))
        self._consumed = self._resume_from

    load_state_dict = set_state_dict


class DistributedBatchSampler(BatchSampler):
    """Rank-sharded batches (reference: ``python/paddle/io/dataloader/
    batch_sampler.py`` DistributedBatchSampler — SURVEY.md §3.5)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import get_world_size, get_rank
            num_replicas = num_replicas if num_replicas is not None else get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def _batches(self):
        indices = np.arange(len(self.dataset)).tolist()
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices += indices[: (self.total_size - len(indices))]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        out = [indices[i:i + self.batch_size]
               for i in range(0, len(indices), self.batch_size)]
        if self.drop_last and out and len(out[-1]) < self.batch_size:
            out.pop()
        return out

    def __iter__(self):
        # one-shot resume offset: a fresh iteration after a break/early
        # stop must NOT skip (the skip happens only on the iteration
        # right after set_state_dict)
        skip, self._resume_from = self._resume_from, 0
        batches = self._batches()
        if skip > len(batches):
            raise ValueError(
                f"sampler resume state skips {skip} batches but this "
                f"epoch has only {len(batches)} — the checkpoint was "
                "taken with a different batch size / dataset / replicas")
        for b_idx in range(skip, len(batches)):
            self._consumed = b_idx + 1     # progress for state_dict
            yield batches[b_idx]
        self._consumed = 0                 # exhausted: next epoch is fresh

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch

    # -- deterministic resume (reference: sampler state in checkpoints;
    #    SURVEY.md §5.4 / §7.3 hard part 3) --------------------------------
    _consumed = 0       # batches yielded so far this epoch (live progress)
    _resume_from = 0    # one-shot skip target set by set_state_dict

    def state_dict(self):
        """Epoch + consumed-batch counter: restoring and re-iterating
        skips exactly the batches already yielded (same shuffle order —
        the epoch seeds the permutation). Valid after a mid-epoch break
        too (progress is tracked per yield, not reset on abandonment)."""
        return {"epoch": self.epoch, "consumed_batches": self._consumed}

    def set_state_dict(self, state):
        self.epoch = int(state.get("epoch", 0))
        self._resume_from = int(state.get("consumed_batches", 0))
        self._consumed = self._resume_from

    load_state_dict = set_state_dict


# ---------------------------------------------------------------------------
# collate
# ---------------------------------------------------------------------------

def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (np.ndarray, np.generic)):
        return np.stack([np.asarray(b) for b in batch])
    if isinstance(sample, Tensor):
        return np.stack([b.numpy() for b in batch])
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, float):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(t)) for t in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, (str, bytes)):
        return list(batch)
    return np.asarray(batch)


def default_convert_fn(batch):
    return batch


def _to_device(np_batch):
    def conv(x):
        if isinstance(x, np.ndarray):
            return Tensor(x)
        return x
    if isinstance(np_batch, (list, tuple)):
        return [conv(b) if not isinstance(b, (list, tuple, dict))
                else _to_device(b) for b in np_batch]
    if isinstance(np_batch, dict):
        return {k: _to_device(v) if isinstance(v, (list, tuple, dict)) else conv(v)
                for k, v in np_batch.items()}
    return conv(np_batch)


# ---------------------------------------------------------------------------
# worker loop
# ---------------------------------------------------------------------------

def _worker_loop(dataset, index_queue, result_queue, collate_fn, worker_id,
                 worker_init_fn, base_seed):
    if isinstance(result_queue, tuple) and result_queue[0] == "shm":
        # native shared-memory transport (io/native/shm_queue.cpp)
        from .native import ShmQueue
        result_queue = ShmQueue(result_queue[1])
    np.random.seed((base_seed + worker_id) % (2 ** 31))
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    while True:
        item = index_queue.get()
        if item is None:
            break
        bidx, indices = item
        try:
            samples = [dataset[i] for i in indices]
            batch = collate_fn(samples)
            result_queue.put((bidx, batch, None))
        except Exception as e:  # propagate
            from .native import QueueClosed
            if isinstance(e, QueueClosed):
                break           # consumer is shutting down; exit quietly
            import traceback
            try:
                result_queue.put((bidx, None, f"{e}\n{traceback.format_exc()}"))
            except QueueClosed:
                break


class _MultiprocessIter:
    """Index-queue/result-queue worker pool with in-order reassembly —
    the ``_DataLoaderIterMultiProcess`` analogue (SURVEY.md §3.5)."""

    def __init__(self, loader):
        self.loader = loader
        _LIVE_ITERS.add(self)
        self._shutdown_lock = threading.Lock()
        self._shut = False
        self.batches = list(iter(loader.batch_sampler))
        self.n = len(self.batches)
        ctx = mp.get_context("fork" if "fork" in mp.get_all_start_methods()
                             else "spawn")
        nw = loader.num_workers
        # native shared-memory result transport (reference: shared-mem
        # tensor blobs + C++ blocking queue — SURVEY.md §3.5); fall back to
        # multiprocessing.Queue when the native lib can't build
        self._shm = None
        worker_result = None
        if loader.use_shared_memory:
            from . import native
            if native.available():
                qname = f"ptq_{os.getpid()}_{id(self)}"
                self._shm = native.ShmQueue(
                    qname, create=True,
                    slots=max(2 * nw, loader.prefetch_factor * nw))
                self.result_queue = self._shm
                worker_result = ("shm", qname)
        if worker_result is None:
            self.result_queue = ctx.Queue()
            worker_result = self.result_queue
        self.index_queues = [ctx.Queue() for _ in range(nw)]
        base_seed = int(np.random.randint(0, 2 ** 31))
        self.workers = []
        for w in range(nw):
            p = ctx.Process(
                target=_worker_loop,
                args=(loader.dataset, self.index_queues[w], worker_result,
                      loader.collate_fn, w, loader.worker_init_fn, base_seed),
                daemon=True)
            p.start()
            self.workers.append(p)
        for i, b in enumerate(self.batches):
            self.index_queues[i % nw].put((i, b))
        for q in self.index_queues:
            q.put(None)
        self._pending = {}
        self._next = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self._next >= self.n:
            self._shutdown()
            raise StopIteration
        from .native import QueueClosed
        while self._next not in self._pending:
            try:
                bidx, batch, err = self.result_queue.get(timeout=5)
            except (TimeoutError, queue.Empty):
                if not any(p.is_alive() for p in self.workers):
                    _telemetry()["failures"].inc()
                    from ..profiler import flight_recorder as _flight
                    _flight.record_event(
                        "dataloader_worker_failure",
                        error="DataLoader workers exited unexpectedly",
                        exitcodes=[p.exitcode for p in self.workers])
                    self._shutdown()
                    raise RuntimeError(
                        "DataLoader workers exited unexpectedly")
                continue
            except QueueClosed:
                raise StopIteration    # interrupted for shutdown
            if err is not None:
                _telemetry()["failures"].inc()
                # the traceback goes into the flight ring too — a
                # post-hang dump must explain input-pipeline deaths, not
                # just count them
                from ..profiler import flight_recorder as _flight
                _flight.record_event("dataloader_worker_failure",
                                     traceback=str(err))
                self._shutdown()
                raise RuntimeError(f"DataLoader worker failed: {err}")
            self._pending[bidx] = batch
        batch = self._pending.pop(self._next)
        self._next += 1
        return _to_device(batch)

    def interrupt(self):
        """Wake any thread blocked in ``__next__``/worker ``put`` so the
        pool can be torn down without closing a mapped segment under a
        live waiter (io/native shmq_interrupt contract). Returns True when
        a native interrupt was actually delivered (shm transport); the
        mp.Queue fallback has no wakeup and returns False."""
        if self._shm is not None:
            self._shm.interrupt()
            return True
        return False

    def _shutdown(self):
        # both the consumer and the prefetch thread's exit path call this;
        # closing the shm segment twice (double munmap) is a segfault
        with self._shutdown_lock:
            if self._shut:
                return
            self._shut = True
        self.interrupt()
        for p in self.workers:
            if p.is_alive():
                p.terminate()
        for p in self.workers:
            p.join(timeout=5)
        if self._shm is not None:
            self._shm.close()
            self._shm = None

    # public alias: _PrefetchIter and the abandoned-iterator reclaim path
    # retire the worker pool through getattr(inner, "shutdown")
    shutdown = _shutdown

    def __del__(self):
        self._shutdown()


class _SingleProcessIter:
    def __init__(self, loader):
        self.loader = loader
        self.sampler_iter = iter(loader.batch_sampler)

    def __iter__(self):
        return self

    def __next__(self):
        indices = next(self.sampler_iter)
        samples = [self.loader.dataset[i] for i in indices]
        return _to_device(self.loader.collate_fn(samples))


def _prefetch_run(wref, inner, q, stop, done):
    """Producer loop of :class:`_PrefetchIter`. Holds only a weakref to the
    wrapper so an abandoned iterator (collected without shutdown()) lets
    this thread notice via the dead ref and exit instead of spinning on a
    full queue forever."""
    def owner():
        return wref()

    err = None
    try:
        for item in inner:
            while not stop.is_set():
                if owner() is None:
                    stop.set()
                    break
                try:
                    q.put(item, timeout=0.1)
                    break
                except queue.Full:
                    continue
            if stop.is_set():
                return
    except Exception as e:
        err = e
    finally:
        self = owner()
        if self is not None:
            if err is not None:
                self.err = err
            # best-effort sentinel; _finished is the authoritative end
            # signal (consumer falls back to it when the queue is full)
            self._finished = True
        try:
            q.put_nowait(done)
        except queue.Full:
            pass
        if stop.is_set() or self is None:
            close = getattr(inner, "close", None) or \
                getattr(inner, "shutdown", None)
            if close:
                try:
                    close()
                except Exception:
                    pass


def _retire_live_iters():
    """atexit: shut down every still-live iterator in interrupt→join→close
    order. A daemon prefetch thread that wakes inside the C shm pop during
    interpreter finalization aborts the whole process (pthread_exit's
    forced unwind through the ctypes frame hits std::terminate), so the
    pools must be retired while the interpreter is still fully alive.
    Prefetch WRAPPERS go first — their shutdown joins the producer thread
    before the inner pool (and its shm mapping) is torn down."""
    live = list(_LIVE_ITERS)
    for it in sorted(live, key=lambda x: not isinstance(x, _PrefetchIter)):
        try:
            it.shutdown()
        except Exception:
            pass


import atexit as _atexit
import weakref as _weakref

_LIVE_ITERS = _weakref.WeakSet()
_atexit.register(_retire_live_iters)


class _PrefetchIter:
    """Depth-k device prefetch wrapper (buffered_reader analogue)."""

    def __init__(self, inner, depth=2):
        import weakref
        self.inner = inner
        _LIVE_ITERS.add(self)
        self.depth = depth
        self.q = queue.Queue(maxsize=depth)
        self.done = object()
        self.err = None
        self._finished = False
        self._stop = threading.Event()
        self.thread = threading.Thread(
            target=_prefetch_run,
            args=(weakref.ref(self), inner, self.q, self._stop, self.done),
            daemon=True)
        self.thread.start()

    def shutdown(self):
        """Unblock and retire the prefetch thread (mid-epoch break path:
        without this, an abandoned iterator leaks the thread blocked on a
        full queue — and through it the worker processes). Order matters:
        interrupt → join → close. Closing the shm segment while the
        producer thread is still inside ``shmq_pop`` unmaps the semaphore
        it is sleeping on (and a daemon thread waking in C during
        interpreter finalization aborts the process)."""
        self._stop.set()
        interrupt = getattr(self.inner, "interrupt", None)
        has_native_interrupt = False
        if interrupt:
            try:
                has_native_interrupt = bool(interrupt())
            except Exception:
                pass
        try:
            while True:
                self.q.get_nowait()
        except queue.Empty:
            pass
        close = getattr(self.inner, "close", None) or \
            getattr(self.inner, "shutdown", None)
        if has_native_interrupt:
            # shm transport: the interrupt already woke the producer thread
            # (QueueClosed); it exits in ms — join BEFORE close so the
            # mapping is never destroyed under a live shmq_pop
            self.thread.join(timeout=6)
            if close:
                try:
                    close()
                except Exception:
                    pass
        else:
            # mp.Queue fallback: nothing can wake the producer's blocking
            # get but worker teardown itself — close first (as before),
            # then join; shmq_close's own drain covers any shm edge case
            if close:
                try:
                    close()
                except Exception:
                    pass
            self.thread.join(timeout=6)

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            try:
                item = self.q.get(timeout=0.1)
                break
            except queue.Empty:
                if self._finished or not self.thread.is_alive():
                    # producer exited — but it may have put final batches
                    # (and/or the sentinel) AFTER our get() timed out:
                    # drain once more before concluding the epoch is over
                    try:
                        item = self.q.get_nowait()
                    except queue.Empty:
                        item = self.done   # truly drained; sentinel may
                    break                  # have been dropped when full
        if item is self.done:
            if self.err:
                raise self.err
            raise StopIteration
        return item


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False, seed=None):
        self.dataset = dataset
        self.num_workers = int(os.environ.get("PADDLE_TPU_NUM_WORKERS",
                                              num_workers))
        self.collate_fn = collate_fn or default_collate_fn
        self.worker_init_fn = worker_init_fn
        self.use_buffer_reader = use_buffer_reader
        self.use_shared_memory = use_shared_memory
        self.prefetch_factor = prefetch_factor
        self.return_list = return_list
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last, seed=seed)

    _yielded = 0        # batches handed to the TRAIN LOOP this epoch

    def state_dict(self):
        """Deterministic-resume state. The consumed count is tracked at
        the LOADER boundary (batches handed to the train loop), so the
        buffered reader's prefetch depth cannot over-report (reference:
        dataloader/sampler state in train checkpoints). Carries the
        sampler's epoch and shuffle seed when it exposes them."""
        sd = getattr(self.batch_sampler, "state_dict", None)
        state = dict(sd()) if sd is not None else {
            "epoch": getattr(self.batch_sampler, "epoch", 0)}
        state["consumed_batches"] = self._yielded
        return state

    def set_state_dict(self, state):
        ss = getattr(self.batch_sampler, "set_state_dict", None)
        if ss is None:
            if state and state.get("consumed_batches"):
                raise ValueError(
                    "DataLoader resume needs a sampler with set_state_dict "
                    "(BatchSampler / DistributedBatchSampler); this custom "
                    "sampler cannot skip consumed batches")
            return
        ss(state)
        self._yielded = int(state.get("consumed_batches", 0))

    load_state_dict = set_state_dict

    _active_inner_ref = None

    @property
    def _active_inner(self):
        """Live inner iterator of the current epoch (or None) — transport
        introspection; weakly held so it can't outlive its consumer."""
        return (self._active_inner_ref()
                if self._active_inner_ref is not None else None)

    def __iter__(self):
        # the loader's consumed base is whatever skip the sampler has
        # armed, read BEFORE the inner iterator (and its prefetch thread)
        # can consume it — keeps the two in sync even if this iterator is
        # later abandoned without a single next()
        base = getattr(self.batch_sampler, "_resume_from", 0)
        # NB: a previous epoch's live iterator is NOT retired here —
        # nested/concurrent iteration over one loader must keep working;
        # abandoned iterators are reclaimed by _prefetch_run's weakref
        inner_it = self._inner_iter()
        # weakref: the loader must not keep an abandoned iterator (and its
        # prefetch thread / worker pool) alive — introspection only
        import weakref
        self._active_inner_ref = weakref.ref(inner_it)
        self._yielded = base

        def counted():
            import time as _time
            tele = _telemetry()
            it = iter(inner_it)
            q = getattr(inner_it, "q", None)   # prefetch queue, if any
            try:
                while True:
                    t0 = _time.perf_counter()
                    try:
                        item = next(it)
                    except StopIteration:
                        self._yielded = 0      # clean epoch end
                        break
                    tele["wait"].observe(_time.perf_counter() - t0)
                    tele["batches"].inc()
                    if q is not None:
                        tele["depth"].set(q.qsize())
                    # count BEFORE handing out: a checkpoint inside the
                    # loop body sees the current batch as consumed
                    self._yielded += 1
                    yield item
            finally:
                stop = getattr(inner_it, "shutdown", None)
                if stop:               # break/early-stop: retire prefetch
                    stop()

        return counted()

    def _inner_iter(self):
        if self._iterable_mode:
            inner = self._iter_iterable()
        elif self.num_workers > 0:
            inner = _MultiprocessIter(self)
        else:
            inner = _SingleProcessIter(self)
        if self.use_buffer_reader:
            return _PrefetchIter(inner, self.prefetch_factor)
        return iter(inner)

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield _to_device(self.collate_fn(batch))
                batch = []
        if batch and not self.drop_last:
            yield _to_device(self.collate_fn(batch))

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    @staticmethod
    def from_generator(*args, **kwargs):
        raise NotImplementedError("from_generator is legacy; use Dataset")


def get_worker_info():
    return None
