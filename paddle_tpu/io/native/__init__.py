"""Native (C++) DataLoader transport — builds and wraps shm_queue.cpp.

The reference keeps its DataLoader hot path native (``blocking_queue.h`` +
shared-memory tensor blobs + ``buffered_reader.cc``; SURVEY.md §2.1/§3.5);
this is the TPU-build equivalent: a POSIX shared-memory blocking ring queue
compiled with g++ at first use (ctypes ABI — no pybind11 in the image) and a
Python ``ShmQueue`` wrapper speaking pickled numpy batches. Falls back to
``multiprocessing.Queue`` transparently when the toolchain or /dev/shm is
unavailable (``available()`` is the gate).
"""
from __future__ import annotations

import ctypes
import itertools
import os
import pickle
import struct
import subprocess
import sys
import tempfile
import threading

_LIB = None
_LIB_ERR = None
_BUILD_LOCK = threading.Lock()


def _build_lib():
    src = os.path.join(os.path.dirname(__file__), "shm_queue.cpp")
    build_dir = os.path.join(tempfile.gettempdir(),
                             f"paddle_tpu_native_{os.getuid()}")
    os.makedirs(build_dir, exist_ok=True)
    so = os.path.join(build_dir, "libshmqueue.so")
    if (not os.path.exists(so)
            or os.path.getmtime(so) < os.path.getmtime(src)):
        cmd = ["g++", "-O2", "-shared", "-fPIC", src, "-o", so + ".tmp",
               "-lrt", "-pthread"]
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(so + ".tmp", so)
    return so


def _load():
    global _LIB, _LIB_ERR
    if _LIB is not None or _LIB_ERR is not None:
        return _LIB
    with _BUILD_LOCK:
        if _LIB is not None or _LIB_ERR is not None:
            return _LIB
        try:
            lib = ctypes.CDLL(_build_lib())
            lib.shmq_create.restype = ctypes.c_void_p
            lib.shmq_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                        ctypes.c_uint64]
            lib.shmq_open.restype = ctypes.c_void_p
            lib.shmq_open.argtypes = [ctypes.c_char_p]
            lib.shmq_push.restype = ctypes.c_int
            lib.shmq_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_uint64, ctypes.c_int]
            lib.shmq_pushv.restype = ctypes.c_int
            lib.shmq_pushv.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_uint64, ctypes.c_char_p,
                                       ctypes.c_uint64, ctypes.c_uint64,
                                       ctypes.c_int]
            lib.shmq_pop.restype = ctypes.c_int64
            lib.shmq_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                     ctypes.c_uint64, ctypes.c_int,
                                     ctypes.POINTER(ctypes.c_uint64)]
            for f in ("shmq_slot_bytes", "shmq_size", "shmq_pushed",
                      "shmq_popped"):
                getattr(lib, f).restype = ctypes.c_uint64
                getattr(lib, f).argtypes = [ctypes.c_void_p]
            lib.shmq_close.argtypes = [ctypes.c_void_p]
            lib.shmq_interrupt.argtypes = [ctypes.c_void_p]
            _LIB = lib
        except (OSError, subprocess.CalledProcessError) as e:
            _LIB_ERR = e
            _LIB = None
    return _LIB


def available() -> bool:
    return sys.platform == "linux" and _load() is not None


class QueueClosed(Exception):
    """The queue was interrupted for shutdown; no further transfers."""


class ShmQueue:
    """Blocking shared-memory queue of pickled python objects.

    Parent: ``ShmQueue(name, create=True)``; workers: ``ShmQueue(name)``.

    Messages larger than one ring slot are transparently split across
    slot-sized chunks (the reference's shared-mem blobs have no fixed blob
    cap either — ``memory/allocation/mmap_allocator`` sizes the segment to
    the tensor). Each chunk carries a ``(producer msg id, index, total)``
    frame header; the consumer reassembles, so multiple workers can
    interleave chunked pushes on the same ring safely. Message completion
    order — not push order — determines ``get`` order, which is fine for
    the DataLoader (it reorders by batch index anyway).
    """

    DEFAULT_SLOTS = 8
    DEFAULT_SLOT_BYTES = 64 << 20     # tmpfs pages are lazy — virtual only

    _HDR = struct.Struct("<4sQII")    # magic, msg_id, chunk_idx, n_chunks
    _MAGIC = b"PTQ1"

    def __init__(self, name, create=False, slots=DEFAULT_SLOTS,
                 slot_bytes=DEFAULT_SLOT_BYTES):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native shm queue unavailable: {_LIB_ERR}")
        self._lib = lib
        self.name = name if name.startswith("/") else "/" + name
        bname = self.name.encode()
        self._h = (lib.shmq_create(bname, slots, slot_bytes) if create
                   else lib.shmq_open(bname))
        if not self._h:
            raise RuntimeError(f"shmq_{'create' if create else 'open'} failed "
                               f"for {self.name}")
        self._slot_bytes = int(lib.shmq_slot_bytes(self._h))
        if self._slot_bytes <= self._HDR.size:
            lib.shmq_close(self._h)
            self._h = None
            raise ValueError(f"slot_bytes={self._slot_bytes} must exceed the "
                             f"{self._HDR.size}-byte frame header")
        self._recv_buf = ctypes.create_string_buffer(1 << 20)
        self._msg_counter = itertools.count()
        # producer identity = pid MIXED WITH a per-process random nonce:
        # a recycled pid alone would let a new worker's msg ids collide
        # with stale incomplete partials of a dead worker (its counter
        # restarts at 0, so ctr-based eviction never fires and chunks of
        # two different messages could merge). 16 nonce bits make that a
        # 1/65536 event instead of a certainty on pid reuse.
        self._producer_id = (os.getpid() << 16) | int.from_bytes(
            os.urandom(2), "little")
        self._partial = {}            # msg_id -> [n_seen, [chunks]]

    def put(self, obj, timeout=None):
        import time as _time
        if not self._h:
            raise QueueClosed(self.name)
        blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        # `timeout` bounds the WHOLE message, not each chunk: track a
        # deadline so an n-chunk put can't block n× the requested budget
        deadline = None if timeout is None else _time.monotonic() + timeout
        payload = self._slot_bytes - self._HDR.size
        n_chunks = max(1, -(-len(blob) // payload))
        msg_id = (self._producer_id << 24) | (next(self._msg_counter)
                                              & 0xFFFFFF)
        for i in range(n_chunks):
            hdr = self._HDR.pack(self._MAGIC, msg_id, i, n_chunks)
            off = i * payload
            n = min(payload, len(blob) - off)
            if not self._h:
                raise QueueClosed(self.name)
            if deadline is None:
                to_ms = -1
            else:
                to_ms = max(0, int((deadline - _time.monotonic()) * 1000))
            # two-part push: the C side copies blob[off:off+n] straight from
            # the pickle buffer — no per-chunk slice/concat of 64 MiB blobs
            rc = self._lib.shmq_pushv(self._h, hdr, len(hdr), blob, off, n,
                                      to_ms)
            if rc == -1:
                raise TimeoutError(f"ShmQueue.put timed out ({self.name})")
            if rc == -2:
                raise ValueError(f"chunk of {len(hdr) + n} bytes exceeds "
                                 f"slot size {self._slot_bytes}")
            if rc == -4:
                raise QueueClosed(self.name)
        return True

    def get(self, timeout=None):
        to_ms = -1 if timeout is None else int(timeout * 1000)
        need = ctypes.c_uint64(0)
        while True:
            if not self._h:
                raise QueueClosed(self.name)
            n = self._lib.shmq_pop(self._h, self._recv_buf,
                                   len(self._recv_buf), to_ms,
                                   ctypes.byref(need))
            if n == -1:
                raise TimeoutError(f"ShmQueue.get timed out ({self.name})")
            if n == -4:
                raise QueueClosed(self.name)
            if n == -3:
                self._recv_buf = ctypes.create_string_buffer(
                    int(need.value))
                continue
            raw = self._recv_buf.raw[:n]
            magic, msg_id, idx, total = self._HDR.unpack_from(raw)
            if magic != self._MAGIC:
                raise RuntimeError(
                    f"ShmQueue frame corruption on {self.name}")
            chunk = raw[self._HDR.size:]
            # producers are sequential per process: a chunk of msg N from
            # producer P (pid+nonce) means any incomplete older msg from P
            # is abandoned (its put timed out mid-message) — evict, don't
            # leak. A dead producer's partials keep a different nonce, so
            # they can never merge with a pid-recycling successor's chunks.
            src, ctr = msg_id >> 24, msg_id & 0xFFFFFF
            stale = [m for m in self._partial
                     if m >> 24 == src and (m & 0xFFFFFF) < ctr]
            for m in stale:
                del self._partial[m]
            if total == 1:
                return pickle.loads(chunk)
            seen, chunks = self._partial.setdefault(
                msg_id, [0, [None] * total])
            if chunks[idx] is None:
                chunks[idx] = chunk
                self._partial[msg_id][0] = seen + 1
            if self._partial[msg_id][0] == total:
                del self._partial[msg_id]
                return pickle.loads(b"".join(chunks))

    def interrupt(self):
        """Wake every blocked producer/consumer with :class:`QueueClosed`.
        Call before ``close`` whenever another thread may still be inside
        ``get``/``put`` — closing unmaps the pages a blocked waiter would
        wake up on."""
        if getattr(self, "_h", None):
            self._lib.shmq_interrupt(self._h)

    def qsize(self):
        return int(self._lib.shmq_size(self._h))

    def stats(self):
        return {"pushed": int(self._lib.shmq_pushed(self._h)),
                "popped": int(self._lib.shmq_popped(self._h))}

    def close(self):
        if getattr(self, "_h", None):
            self._lib.shmq_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
