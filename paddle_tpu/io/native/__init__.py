"""Native (C++) DataLoader transport — builds and wraps shm_queue.cpp.

The reference keeps its DataLoader hot path native (``blocking_queue.h`` +
shared-memory tensor blobs + ``buffered_reader.cc``; SURVEY.md §2.1/§3.5);
this is the TPU-build equivalent: a POSIX shared-memory blocking ring queue
compiled with g++ at first use (ctypes ABI — no pybind11 in the image) and a
Python ``ShmQueue`` wrapper speaking pickled numpy batches. Falls back to
``multiprocessing.Queue`` transparently when the toolchain or /dev/shm is
unavailable (``available()`` is the gate).
"""
from __future__ import annotations

import ctypes
import os
import pickle
import subprocess
import sys
import tempfile
import threading

_LIB = None
_LIB_ERR = None
_BUILD_LOCK = threading.Lock()


def _build_lib():
    src = os.path.join(os.path.dirname(__file__), "shm_queue.cpp")
    build_dir = os.path.join(tempfile.gettempdir(),
                             f"paddle_tpu_native_{os.getuid()}")
    os.makedirs(build_dir, exist_ok=True)
    so = os.path.join(build_dir, "libshmqueue.so")
    if (not os.path.exists(so)
            or os.path.getmtime(so) < os.path.getmtime(src)):
        cmd = ["g++", "-O2", "-shared", "-fPIC", src, "-o", so + ".tmp",
               "-lrt", "-pthread"]
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(so + ".tmp", so)
    return so


def _load():
    global _LIB, _LIB_ERR
    if _LIB is not None or _LIB_ERR is not None:
        return _LIB
    with _BUILD_LOCK:
        if _LIB is not None or _LIB_ERR is not None:
            return _LIB
        try:
            lib = ctypes.CDLL(_build_lib())
            lib.shmq_create.restype = ctypes.c_void_p
            lib.shmq_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                        ctypes.c_uint64]
            lib.shmq_open.restype = ctypes.c_void_p
            lib.shmq_open.argtypes = [ctypes.c_char_p]
            lib.shmq_push.restype = ctypes.c_int
            lib.shmq_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_uint64, ctypes.c_int]
            lib.shmq_pop.restype = ctypes.c_int64
            lib.shmq_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                     ctypes.c_uint64, ctypes.c_int,
                                     ctypes.POINTER(ctypes.c_uint64)]
            for f in ("shmq_slot_bytes", "shmq_size", "shmq_pushed",
                      "shmq_popped"):
                getattr(lib, f).restype = ctypes.c_uint64
                getattr(lib, f).argtypes = [ctypes.c_void_p]
            lib.shmq_close.argtypes = [ctypes.c_void_p]
            _LIB = lib
        except (OSError, subprocess.CalledProcessError) as e:
            _LIB_ERR = e
            _LIB = None
    return _LIB


def available() -> bool:
    return sys.platform == "linux" and _load() is not None


class ShmQueue:
    """Blocking shared-memory queue of pickled python objects.

    Parent: ``ShmQueue(name, create=True)``; workers: ``ShmQueue(name)``.
    """

    DEFAULT_SLOTS = 8
    DEFAULT_SLOT_BYTES = 64 << 20     # tmpfs pages are lazy — virtual only

    def __init__(self, name, create=False, slots=DEFAULT_SLOTS,
                 slot_bytes=DEFAULT_SLOT_BYTES):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native shm queue unavailable: {_LIB_ERR}")
        self._lib = lib
        self.name = name if name.startswith("/") else "/" + name
        bname = self.name.encode()
        self._h = (lib.shmq_create(bname, slots, slot_bytes) if create
                   else lib.shmq_open(bname))
        if not self._h:
            raise RuntimeError(f"shmq_{'create' if create else 'open'} failed "
                               f"for {self.name}")
        self._recv_buf = ctypes.create_string_buffer(1 << 20)

    def put(self, obj, timeout=None):
        blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        to_ms = -1 if timeout is None else int(timeout * 1000)
        rc = self._lib.shmq_push(self._h, blob, len(blob), to_ms)
        if rc == -1:
            raise TimeoutError(f"ShmQueue.put timed out ({self.name})")
        if rc == -2:
            raise ValueError(f"batch of {len(blob)} bytes exceeds slot size "
                             f"{self._lib.shmq_slot_bytes(self._h)}")
        return True

    def get(self, timeout=None):
        to_ms = -1 if timeout is None else int(timeout * 1000)
        need = ctypes.c_uint64(0)
        while True:
            n = self._lib.shmq_pop(self._h, self._recv_buf,
                                   len(self._recv_buf), to_ms,
                                   ctypes.byref(need))
            if n == -1:
                raise TimeoutError(f"ShmQueue.get timed out ({self.name})")
            if n == -3:
                self._recv_buf = ctypes.create_string_buffer(
                    int(need.value))
                continue
            return pickle.loads(self._recv_buf.raw[:n])

    def qsize(self):
        return int(self._lib.shmq_size(self._h))

    def stats(self):
        return {"pushed": int(self._lib.shmq_pushed(self._h)),
                "popped": int(self._lib.shmq_popped(self._h))}

    def close(self):
        if getattr(self, "_h", None):
            self._lib.shmq_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
