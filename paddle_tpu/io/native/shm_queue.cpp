// Shared-memory blocking ring queue for the DataLoader hot path.
//
// Reference analogue: paddle/fluid/operators/reader/blocking_queue.h (the
// C++ bounded queue between DataLoader workers and the consumer) plus the
// shared-memory LoDTensor blobs of the multiprocess DataLoader
// (SURVEY.md §3.5). TPU-native: worker processes serialize numpy batches
// into fixed-size slots of a POSIX shm segment; the trainer process pops
// without the multiprocessing.Queue pipe/socket copy. Multi-producer /
// multi-consumer safe via process-shared POSIX semaphores; slot pages are
// tmpfs-lazy so generous slot sizes cost no physical memory until used.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).
//
// Build: g++ -O2 -shared -fPIC shm_queue.cpp -o libshmqueue.so -lrt -pthread

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <semaphore.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Ctrl {
  uint64_t magic;
  uint64_t slots;
  uint64_t slot_bytes;
  uint64_t head;   // next slot to write (producers)
  uint64_t tail;   // next slot to read (consumers)
  sem_t free_sem;  // counts empty slots
  sem_t item_sem;  // counts filled slots
  sem_t pmu;       // producer mutex
  sem_t cmu;       // consumer mutex
  uint64_t pushed; // stats
  uint64_t popped;
  uint64_t closing; // set by shmq_interrupt: waiters drain with -4
};

constexpr uint64_t kMagic = 0x70616464746f7571ULL;  // "paddtouq"

struct Handle {
  Ctrl* ctrl;
  uint8_t* data;   // slots * (8 + slot_bytes)
  uint64_t map_len;
  int fd;
  bool owner;
  volatile long active;  // threads currently inside pop/push on this handle
  char name[128];
};

struct ActiveGuard {
  Handle* h;
  explicit ActiveGuard(Handle* hh) : h(hh) { __sync_fetch_and_add(&h->active, 1); }
  ~ActiveGuard() { __sync_fetch_and_sub(&h->active, 1); }
};

// slot layout: [len:8][ready:8][payload:slot_bytes]. `ready` is written
// LAST by the producer (release) and awaited by the consumer: item_sem
// counts COMPLETED pushes globally, but slots are read in tail order, so
// a slow producer's reserved-but-unfinished slot must not be popped torn.
uint64_t slot_stride(const Ctrl* c) { return 16 + c->slot_bytes; }

int timed_wait(sem_t* s, int timeout_ms) {
  if (timeout_ms < 0) {
    while (sem_wait(s) == -1 && errno == EINTR) {}
    return 0;
  }
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  ts.tv_sec += timeout_ms / 1000;
  ts.tv_nsec += (long)(timeout_ms % 1000) * 1000000L;
  if (ts.tv_nsec >= 1000000000L) { ts.tv_sec += 1; ts.tv_nsec -= 1000000000L; }
  while (true) {
    if (sem_timedwait(s, &ts) == 0) return 0;
    if (errno == EINTR) continue;
    return -1;  // ETIMEDOUT
  }
}

}  // namespace

extern "C" {

void* shmq_create(const char* name, uint64_t slots, uint64_t slot_bytes) {
  shm_unlink(name);  // stale segment from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t len = sizeof(Ctrl) + slots * (16 + slot_bytes);
  if (ftruncate(fd, (off_t)len) != 0) { close(fd); shm_unlink(name); return nullptr; }
  void* mem = mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) { close(fd); shm_unlink(name); return nullptr; }
  Ctrl* c = (Ctrl*)mem;
  c->slots = slots;
  c->slot_bytes = slot_bytes;
  c->head = c->tail = 0;
  c->pushed = c->popped = 0;
  c->closing = 0;
  sem_init(&c->free_sem, 1, (unsigned)slots);
  sem_init(&c->item_sem, 1, 0);
  sem_init(&c->pmu, 1, 1);
  sem_init(&c->cmu, 1, 1);
  c->magic = kMagic;
  Handle* h = new Handle();
  h->ctrl = c;
  h->data = (uint8_t*)mem + sizeof(Ctrl);
  h->map_len = len;
  h->fd = fd;
  h->owner = true;
  h->active = 0;
  strncpy(h->name, name, sizeof(h->name) - 1);
  return h;
}

void* shmq_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return nullptr; }
  void* mem = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) { close(fd); return nullptr; }
  Ctrl* c = (Ctrl*)mem;
  if (c->magic != kMagic) { munmap(mem, (size_t)st.st_size); close(fd); return nullptr; }
  Handle* h = new Handle();
  h->ctrl = c;
  h->data = (uint8_t*)mem + sizeof(Ctrl);
  h->map_len = (uint64_t)st.st_size;
  h->fd = fd;
  h->owner = false;
  h->active = 0;
  strncpy(h->name, name, sizeof(h->name) - 1);
  return h;
}

// 0 ok; -1 timeout; -2 payload larger than slot; -4 queue closing.
// Two-part write (header + payload at an offset into one buffer) so the
// Python wrapper can frame chunked messages without concatenating 64 MiB
// slices per chunk.
int shmq_pushv(void* hv, const void* hdr, uint64_t hdr_len, const void* buf,
               uint64_t off, uint64_t len, int timeout_ms) {
  Handle* h = (Handle*)hv;
  ActiveGuard ag(h);
  Ctrl* c = h->ctrl;
  uint64_t total = hdr_len + len;
  if (total > c->slot_bytes) return -2;
  if (c->closing) return -4;
  if (timed_wait(&c->free_sem, timeout_ms) != 0) return -1;
  if (c->closing) { sem_post(&c->free_sem); return -4; }
  timed_wait(&c->pmu, -1);
  uint64_t slot = c->head % c->slots;
  c->head++;
  uint8_t* p = h->data + slot * slot_stride(c);
  sem_post(&c->pmu);
  memcpy(p, &total, 8);
  if (hdr_len) memcpy(p + 16, hdr, hdr_len);
  if (len) memcpy(p + 16 + hdr_len, (const uint8_t*)buf + off, len);
  __sync_synchronize();
  uint64_t one = 1;
  memcpy(p + 8, &one, 8);  // ready: release the slot to the consumer
  __sync_synchronize();
  __sync_fetch_and_add(&c->pushed, 1);
  sem_post(&c->item_sem);
  return 0;
}

int shmq_push(void* hv, const void* buf, uint64_t len, int timeout_ms) {
  return shmq_pushv(hv, nullptr, 0, buf, 0, len, timeout_ms);
}

// >=0: payload length; -1 timeout; -3 caller buffer too small (len returned
// via *need); -4 queue closing
int64_t shmq_pop(void* hv, void* out, uint64_t cap, int timeout_ms,
                 uint64_t* need) {
  Handle* h = (Handle*)hv;
  ActiveGuard ag(h);
  Ctrl* c = h->ctrl;
  // the caller's timeout bounds the WHOLE pop — compute the absolute
  // deadline up front so the ready-flag spin below inherits whatever
  // budget the item_sem wait left over
  struct timespec deadline;
  if (timeout_ms >= 0) {
    clock_gettime(CLOCK_REALTIME, &deadline);
    deadline.tv_sec += timeout_ms / 1000;
    deadline.tv_nsec += (long)(timeout_ms % 1000) * 1000000L;
    if (deadline.tv_nsec >= 1000000000L) {
      deadline.tv_sec += 1;
      deadline.tv_nsec -= 1000000000L;
    }
  }
  if (timed_wait(&c->item_sem, timeout_ms) != 0) return -1;
  if (c->closing) { sem_post(&c->item_sem); return -4; }
  timed_wait(&c->cmu, -1);
  uint64_t slot = c->tail % c->slots;
  uint8_t* p = h->data + slot * slot_stride(c);
  // item_sem counted a COMPLETED push somewhere, but tail order may reach
  // a slot whose producer is still copying — await its ready flag. The
  // wait is bounded by the pop deadline: a producer killed between slot
  // reservation (head++) and setting `ready` would otherwise leave the
  // consumer spinning forever while holding cmu, so the Python side's
  // workers-alive check could never fire. On expiry re-post item_sem and
  // cmu (the item is NOT consumed; a later pop may retry) and return -1.
  uint64_t ready = 0;
  struct timespec ms = {0, 200000};  // 0.2 ms
  while (true) {
    memcpy(&ready, p + 8, 8);
    if (ready) break;
    if (c->closing) { sem_post(&c->cmu); sem_post(&c->item_sem); return -4; }
    if (timeout_ms >= 0) {
      struct timespec now;
      clock_gettime(CLOCK_REALTIME, &now);
      if (now.tv_sec > deadline.tv_sec ||
          (now.tv_sec == deadline.tv_sec &&
           now.tv_nsec >= deadline.tv_nsec)) {
        sem_post(&c->cmu);
        sem_post(&c->item_sem);
        return -1;
      }
    }
    nanosleep(&ms, nullptr);
  }
  __sync_synchronize();
  uint64_t len;
  memcpy(&len, p, 8);
  if (len > cap) {
    // leave item in place for a retry with a bigger buffer
    if (need) *need = len;
    sem_post(&c->cmu);
    sem_post(&c->item_sem);
    return -3;
  }
  memcpy(out, p + 16, len);
  uint64_t zero = 0;
  memcpy(p + 8, &zero, 8);  // clear ready before the slot is reused
  c->tail++;
  c->popped++;
  sem_post(&c->cmu);
  sem_post(&c->free_sem);
  return (int64_t)len;
}

uint64_t shmq_slot_bytes(void* hv) { return ((Handle*)hv)->ctrl->slot_bytes; }
uint64_t shmq_size(void* hv) {
  Ctrl* c = ((Handle*)hv)->ctrl;
  int v = 0;
  sem_getvalue(&c->item_sem, &v);
  return (uint64_t)(v < 0 ? 0 : v);
}
uint64_t shmq_pushed(void* hv) { return ((Handle*)hv)->ctrl->pushed; }
uint64_t shmq_popped(void* hv) { return ((Handle*)hv)->ctrl->popped; }

// Wake every blocked producer/consumer; they return -4 instead of touching
// slot memory again. MUST precede shmq_close whenever another thread may
// still be inside shmq_pop/shmq_push on the same segment — closing unmaps
// the pages a blocked sem_timedwait would otherwise wake up on (the
// teardown abort this interrupt exists to prevent).
void shmq_interrupt(void* hv) {
  Ctrl* c = ((Handle*)hv)->ctrl;
  c->closing = 1;
  __sync_synchronize();
  for (uint64_t i = 0; i < c->slots + 64; ++i) {
    sem_post(&c->item_sem);
    sem_post(&c->free_sem);
  }
}

void shmq_close(void* hv) {
  Handle* h = (Handle*)hv;
  // a sibling thread may still be inside pop/push (its semaphore lives in
  // the mapping we are about to destroy) — interrupt + drain before unmap.
  // Only the OWNER may set the shared closing flag: a worker closing its
  // handle on normal exit must not shut the queue down for everyone.
  if (h->owner) {
    h->ctrl->closing = 1;
    __sync_synchronize();
    if (__sync_fetch_and_add(&h->active, 0) != 0) {
      for (uint64_t i = 0; i < h->ctrl->slots + 64; ++i) {
        sem_post(&h->ctrl->item_sem);
        sem_post(&h->ctrl->free_sem);
      }
    }
  }
  struct timespec ms = {0, 1000000};
  for (int spin = 0; spin < 10000; ++spin) {  // cap ~10 s
    if (__sync_fetch_and_add(&h->active, 0) == 0) break;
    nanosleep(&ms, nullptr);
  }
  bool owner = h->owner;
  char name[128];
  strncpy(name, h->name, sizeof(name));
  munmap((void*)h->ctrl, h->map_len);
  close(h->fd);
  delete h;
  if (owner) shm_unlink(name);
}

}  // extern "C"
