"""paddle.fft (reference: ``python/paddle/fft.py`` — FFT API over phi fft
kernels (cuFFT/pocketfft); SURVEY.md §2.2 tensor-ops surface).

TPU-native: ``jnp.fft`` lowers to XLA's FFT HLO. All functions are
differentiable through the tape.
"""
from __future__ import annotations

import jax.numpy as jnp

from .autograd.tape import apply

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2",
    "fftn", "ifftn", "rfftn", "irfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _norm(norm):
    return {"backward": "backward", "forward": "forward", "ortho": "ortho",
            None: "backward"}[norm]


def _wrap1(jfn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return apply(lambda a: jfn(a, n=n, axis=axis, norm=_norm(norm)), x,
                     op_name=jfn.__name__)
    return op


def _wrapn(jfn, axes_default=None):
    def op(x, s=None, axes=axes_default, norm="backward", name=None):
        return apply(lambda a: jfn(a, s=s, axes=axes, norm=_norm(norm)), x,
                     op_name=jfn.__name__)
    return op


fft = _wrap1(jnp.fft.fft)
ifft = _wrap1(jnp.fft.ifft)
rfft = _wrap1(jnp.fft.rfft)
irfft = _wrap1(jnp.fft.irfft)
hfft = _wrap1(jnp.fft.hfft)
ihfft = _wrap1(jnp.fft.ihfft)

fft2 = _wrapn(jnp.fft.fft2, (-2, -1))
ifft2 = _wrapn(jnp.fft.ifft2, (-2, -1))
rfft2 = _wrapn(jnp.fft.rfft2, (-2, -1))
irfft2 = _wrapn(jnp.fft.irfft2, (-2, -1))
fftn = _wrapn(jnp.fft.fftn)
ifftn = _wrapn(jnp.fft.ifftn)
rfftn = _wrapn(jnp.fft.rfftn)
irfftn = _wrapn(jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .framework.core import Tensor
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype or jnp.float32))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .framework.core import Tensor
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype or jnp.float32))


def fftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.fftshift(a, axes=axes), x,
                 op_name="fftshift")


def ifftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.ifftshift(a, axes=axes), x,
                 op_name="ifftshift")
