"""paddle.fft (reference: ``python/paddle/fft.py`` — FFT API over phi fft
kernels (cuFFT/pocketfft); SURVEY.md §2.2 tensor-ops surface).

TPU-native: ``jnp.fft`` lowers to XLA's FFT HLO. All functions are
differentiable through the tape.
"""
from __future__ import annotations

import jax.numpy as jnp

from .autograd.tape import apply

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _norm(norm):
    return {"backward": "backward", "forward": "forward", "ortho": "ortho",
            None: "backward"}[norm]


def _wrap1(jfn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return apply(lambda a: jfn(a, n=n, axis=axis, norm=_norm(norm)), x,
                     op_name=jfn.__name__)
    return op


def _wrapn(jfn, axes_default=None):
    def op(x, s=None, axes=axes_default, norm="backward", name=None):
        return apply(lambda a: jfn(a, s=s, axes=axes, norm=_norm(norm)), x,
                     op_name=jfn.__name__)
    return op


fft = _wrap1(jnp.fft.fft)
ifft = _wrap1(jnp.fft.ifft)
rfft = _wrap1(jnp.fft.rfft)
irfft = _wrap1(jnp.fft.irfft)
hfft = _wrap1(jnp.fft.hfft)
ihfft = _wrap1(jnp.fft.ihfft)

fft2 = _wrapn(jnp.fft.fft2, (-2, -1))
ifft2 = _wrapn(jnp.fft.ifft2, (-2, -1))
rfft2 = _wrapn(jnp.fft.rfft2, (-2, -1))
irfft2 = _wrapn(jnp.fft.irfft2, (-2, -1))
fftn = _wrapn(jnp.fft.fftn)
ifftn = _wrapn(jnp.fft.ifftn)
rfftn = _wrapn(jnp.fft.rfftn)
irfftn = _wrapn(jnp.fft.irfftn)


def _hfftn_impl(a, s, axes, norm):
    """Hermitian FFT over multiple axes (torch/paddle semantics: plain
    FFT over the leading axes, hermitian (real-output) FFT on the last —
    numpy only ships the 1-D hfft)."""
    axes = tuple(axes)
    lead, last = axes[:-1], axes[-1]
    n_last = None if s is None else s[-1]
    if lead:
        a = jnp.fft.fftn(a, s=None if s is None else s[:-1], axes=lead,
                         norm=norm)
    return jnp.fft.hfft(a, n=n_last, axis=last, norm=norm)


def _ihfftn_impl(a, s, axes, norm):
    axes = tuple(axes)
    lead, last = axes[:-1], axes[-1]
    n_last = None if s is None else s[-1]
    out = jnp.fft.ihfft(a, n=n_last, axis=last, norm=norm)
    if lead:
        out = jnp.fft.ifftn(out, s=None if s is None else s[:-1], axes=lead,
                            norm=norm)
    return out


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    """paddle.fft.hfft2 — 2-D FFT of a Hermitian-symmetric signal (real
    output)."""
    return apply(lambda a: _hfftn_impl(a, s, axes, _norm(norm)), x,
                 op_name="hfft2")


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    """paddle.fft.ihfft2 — inverse of :func:`hfft2` (Hermitian output)."""
    return apply(lambda a: _ihfftn_impl(a, s, axes, _norm(norm)), x,
                 op_name="ihfft2")


def _default_axes(a, s, axes):
    if axes is not None:
        return tuple(axes)
    # numpy/paddle contract: with s given, the LAST len(s) axes
    return tuple(range(a.ndim - len(s), a.ndim)) if s is not None \
        else tuple(range(a.ndim))


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    """paddle.fft.hfftn — N-D Hermitian FFT (real output)."""
    def fn(a):
        return _hfftn_impl(a, s, _default_axes(a, s, axes), _norm(norm))
    return apply(fn, x, op_name="hfftn")


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    """paddle.fft.ihfftn — inverse of :func:`hfftn`."""
    def fn(a):
        return _ihfftn_impl(a, s, _default_axes(a, s, axes), _norm(norm))
    return apply(fn, x, op_name="ihfftn")


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .framework.core import Tensor
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype or jnp.float32))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .framework.core import Tensor
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype or jnp.float32))


def fftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.fftshift(a, axes=axes), x,
                 op_name="fftshift")


def ifftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.ifftshift(a, axes=axes), x,
                 op_name="ifftshift")
