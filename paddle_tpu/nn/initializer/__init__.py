"""nn.initializer (reference: ``python/paddle/nn/initializer/`` — SURVEY.md §2.2).

Initializers are callables ``init(shape, dtype) -> jax array`` drawing from the
global generator (``framework/random.py``)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework import dtype as dtypes
from ...framework import random as prandom


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels [out_c, in_c, *spatial] (paddle layout)
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype="float32"):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        return jnp.full(tuple(shape), self.value, dtypes.convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        dt = dtypes.convert_dtype(dtype)
        return jax.random.normal(prandom.next_key(), tuple(shape), dt) * self.std + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype="float32"):
        dt = dtypes.convert_dtype(dtype)
        z = jax.random.truncated_normal(prandom.next_key(),
                                        (self.a - 0.0), (self.b - 0.0),
                                        tuple(shape), dt)
        return z * self.std + self.mean


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype="float32"):
        dt = dtypes.convert_dtype(dtype)
        return jax.random.uniform(prandom.next_key(), tuple(shape), dt,
                                  minval=self.low, maxval=self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fin, fout = _fans(shape)
        fin = self.fan_in or fin
        fout = self.fan_out or fout
        std = self.gain * math.sqrt(2.0 / (fin + fout))
        return Normal(0.0, std)(shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fin, fout = _fans(shape)
        fin = self.fan_in or fin
        fout = self.fan_out or fout
        limit = self.gain * math.sqrt(6.0 / (fin + fout))
        return Uniform(-limit, limit)(shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype="float32"):
        fin, _ = _fans(shape)
        fin = self.fan_in or fin
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) \
            if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        std = gain / math.sqrt(fin)
        return Normal(0.0, std)(shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype="float32"):
        fin, _ = _fans(shape)
        fin = self.fan_in or fin
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) \
            if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        limit = gain * math.sqrt(3.0 / fin)
        return Uniform(-limit, limit)(shape, dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        from ...framework.core import Tensor
        v = self.value.numpy() if isinstance(self.value, Tensor) else np.asarray(self.value)
        v = v.reshape(tuple(shape)) if tuple(v.shape) != tuple(shape) else v
        return jnp.asarray(v, dtypes.convert_dtype(dtype))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype="float32"):
        dt = dtypes.convert_dtype(dtype)
        rows, cols = shape[0], int(np.prod(shape[1:]))
        flat = jax.random.normal(prandom.next_key(), (max(rows, cols), min(rows, cols)))
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        q = q.T if rows < cols else q
        return (self.gain * q[:rows, :cols]).reshape(tuple(shape)).astype(dt)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype="float32"):
        out = np.zeros(tuple(shape), np.dtype(dtypes.convert_dtype(dtype)))
        oc, ic = shape[0], shape[1]
        mins = min(oc // self.groups, ic)
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(mins):
                out[(g * (oc // self.groups) + i, i) + tuple(centers)] = 1.0
        return jnp.asarray(out)


def calculate_gain(nonlinearity, param=None):
    gains = {"sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
             "conv3d": 1.0, "tanh": 5.0 / 3, "relu": math.sqrt(2.0),
             "selu": 3.0 / 4}
    if nonlinearity == "leaky_relu":
        slope = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + slope ** 2))
    return gains.get(nonlinearity, 1.0)


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init, _global_bias_init = weight_init, bias_init


_global_weight_init = None
_global_bias_init = None


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transpose convs (reference
    ``paddle.nn.initializer.Bilinear``: each [kh, kw] slice is the
    separable triangle filter; channel slices identical)."""

    def __call__(self, shape, dtype="float32"):
        import numpy as np
        shape = tuple(shape)
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D weight")
        kh, kw = shape[2], shape[3]
        # reference (caffe) bilinear kernel: f = ceil(k/2),
        # c = (2f - 1 - f%2) / (2f); w(x) = 1 - |x/f - c|
        fh, fw = (kh + 1) // 2, (kw + 1) // 2
        ch = (2 * fh - 1 - fh % 2) / (2.0 * fh)
        cw = (2 * fw - 1 - fw % 2) / (2.0 * fw)
        og = np.ogrid[:kh, :kw]
        filt = ((1 - np.abs(og[0] / fh - ch))
                * (1 - np.abs(og[1] / fw - cw)))
        w = np.zeros(shape, np.float32)
        w[:, :] = filt
        return jnp.asarray(w, dtypes.convert_dtype(dtype))
