"""Gradient clipping (reference: ``python/paddle/nn/clip.py`` —
``ClipGradByGlobalNorm`` et al., used by Optimizer.grad_clip; SURVEY.md §2.2).

In hybrid-parallel runs ``HybridParallelOptimizer`` swaps in a clip that
psums the squared norms across mesh axes (see distributed/fleet)."""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import Tensor
from ..autograd.tape import no_grad


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    @no_grad()
    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._data, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    @no_grad()
    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._data.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((g._data * scale).astype(g.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _global_norm_sq(self, params_grads):
        sq = [jnp.sum(jnp.square(g._data.astype(jnp.float32)))
              for p, g in params_grads
              if g is not None and getattr(p, "need_clip", True)]
        if not sq:
            return None
        total = sq[0]
        for s in sq[1:]:
            total = total + s
        return total

    @no_grad()
    def __call__(self, params_grads):
        total = self._global_norm_sq(params_grads)
        if total is None:
            return params_grads
        global_norm = jnp.sqrt(total)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
            else:
                out.append((p, Tensor((g._data * scale).astype(g.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    params = [p for p in (parameters if isinstance(parameters, (list, tuple))
                          else [parameters]) if p.grad is not None]
    if not params:
        return Tensor(jnp.zeros([]))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(p.grad._data)) for p in params]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(p.grad._data.astype(jnp.float32)) ** norm_type)
             for p in params])) ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in params:
        p.grad._data = (p.grad._data * scale).astype(p.grad.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    params = parameters if isinstance(parameters, (list, tuple)) else [parameters]
    for p in params:
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -clip_value, clip_value)
