"""Functional breadth batch 2 (reference: ``python/paddle/nn/functional/``
— pooling.py 3-D + unpool, conv.py 1-D/3-D transpose, vision.py
affine_grid/grid_sample/pixel_unshuffle/temporal_shift, common.py fold,
extension.py sequence_mask/gather_tree, loss.py tail)."""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...autograd.tape import apply
from .common import _tuple, _conv_padding, _pool


# ---------------------------------------------------------------------------
# pooling: 3-D + indices + unpool
# ---------------------------------------------------------------------------

def _check_index_pool_args(padding, ceil_mode, data_format, expect_df):
    if isinstance(padding, str):
        raise NotImplementedError(
            "return_mask pooling: string padding unsupported (use ints)")
    if ceil_mode:
        raise NotImplementedError(
            "return_mask pooling: ceil_mode unsupported")
    if data_format != expect_df:
        raise NotImplementedError(
            f"return_mask pooling: only {expect_df} layout")


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW", name=None):
    ksize = _tuple(kernel_size, 3)
    strides = _tuple(stride, 3) if stride is not None else ksize
    if return_mask:
        _check_index_pool_args(padding, ceil_mode, data_format, "NCDHW")
        return _max_pool_with_index(x, ksize, strides,
                                    _tuple(padding, 3))
    pad = _conv_padding(padding, 3) if not isinstance(padding, str) else padding
    return _pool(x, ksize, strides, pad, lax.max, -jnp.inf, data_format,
                 ceil_mode)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    from .common import _avg_pool_impl
    ksize = _tuple(kernel_size, 3)
    strides = _tuple(stride, 3) if stride is not None else ksize
    pad = _conv_padding(padding, 3) if not isinstance(padding, str) else padding
    return _avg_pool_impl(x, ksize, strides, pad, data_format, ceil_mode,
                          exclusive, divisor_override)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    """General-bin adaptive mean pooling (floor/ceil bin edges — same
    algorithm as adaptive_avg_pool2d in common.py)."""
    if data_format != "NCDHW":
        raise NotImplementedError("adaptive_avg_pool3d: NCDHW only")
    sizes = _tuple(output_size, 3)

    def fn(a):
        n, c, d, h, w = a.shape
        od, oh, ow = sizes[0] or d, sizes[1] or h, sizes[2] or w
        if d % od == 0 and h % oh == 0 and w % ow == 0:
            v = a.reshape(n, c, od, d // od, oh, h // oh, ow, w // ow)
            return v.mean(axis=(3, 5, 7))
        outs = []
        for i in range(od):
            d0, d1 = (i * d) // od, -((-(i + 1) * d) // od)
            rows = []
            for j in range(oh):
                h0, h1 = (j * h) // oh, -((-(j + 1) * h) // oh)
                cols = []
                for k in range(ow):
                    w0, w1 = (k * w) // ow, -((-(k + 1) * w) // ow)
                    cols.append(a[:, :, d0:d1, h0:h1, w0:w1]
                                .mean(axis=(2, 3, 4)))
                rows.append(jnp.stack(cols, -1))
            outs.append(jnp.stack(rows, -2))
        return jnp.stack(outs, -3)

    return apply(fn, x, op_name="adaptive_avg_pool3d")


def _max_pool_with_index(x, ksize, strides, pads):
    """Window argmax pooling: returns (values, flat spatial indices into the
    UNPADDED input) — the mask `paddle.nn.functional.max_pool*d(...,
    return_mask=True)` contract that MaxUnPool consumes."""
    nd = len(ksize)

    def fn(a):
        n, c = a.shape[:2]
        spatial = a.shape[2:]
        padded = jnp.pad(
            a, [(0, 0), (0, 0)] + [(p, p) for p in pads],
            constant_values=-jnp.inf)
        outs = [(padded.shape[2 + i] - ksize[i]) // strides[i] + 1
                for i in range(nd)]
        # gather every window position: iterate the (static, small) kernel
        windows = []
        flat_idx = []
        for off in np.ndindex(*ksize):
            sl = [slice(None), slice(None)]
            idx_terms = []
            for i in range(nd):
                start = off[i]
                sl.append(slice(start, start + outs[i] * strides[i],
                                strides[i]))
            windows.append(padded[tuple(sl)])
            # index of this element in the unpadded input
            coord = []
            for i in range(nd):
                pos = (jnp.arange(outs[i]) * strides[i] + off[i] - pads[i])
                coord.append(pos)
            flat = jnp.zeros([1] * nd, jnp.int32)
            mult = 1
            for i in reversed(range(nd)):
                shape = [1] * nd
                shape[i] = outs[i]
                flat = flat + coord[i].reshape(shape) * mult
                mult *= spatial[i]
            flat_idx.append(jnp.broadcast_to(flat, outs))
        stack = jnp.stack(windows, axis=-1)       # [n, c, *outs, K]
        idxs = jnp.stack(flat_idx, axis=-1)       # [*outs, K]
        arg = jnp.argmax(stack, axis=-1)
        vals = jnp.take_along_axis(stack, arg[..., None], -1)[..., 0]
        mask = jnp.take_along_axis(
            jnp.broadcast_to(idxs, stack.shape), arg[..., None], -1)[..., 0]
        return vals, mask.astype(jnp.int32)

    return apply(fn, x, op_name="max_pool_index")


def max_pool2d_with_index(x, kernel_size, stride=None, padding=0):
    _check_index_pool_args(padding, False, "NCHW", "NCHW")
    ksize = _tuple(kernel_size, 2)
    strides = _tuple(stride, 2) if stride is not None else ksize
    return _max_pool_with_index(x, ksize, strides, _tuple(padding, 2))


def max_pool1d_with_index(x, kernel_size, stride=None, padding=0):
    _check_index_pool_args(padding, False, "NCL", "NCL")
    ksize = _tuple(kernel_size, 1)
    strides = _tuple(stride, 1) if stride is not None else ksize
    return _max_pool_with_index(x, ksize, strides, _tuple(padding, 1))


def _max_unpool(x, indices, nd, kernel_size, stride, padding, output_size,
                data_format):
    if data_format not in ("NCL", "NCHW", "NCDHW"):
        raise NotImplementedError(
            f"max_unpool: channels-first only (got {data_format})")
    ksize = _tuple(kernel_size, nd)
    strides = _tuple(stride, nd) if stride is not None else ksize
    pads = _tuple(padding, nd)

    def fn(a, idx):
        n, c = a.shape[:2]
        outs_in = a.shape[2:]
        if output_size is not None:
            out_sp = tuple(int(s) for s in tuple(output_size)[-nd:])
        else:
            out_sp = tuple((outs_in[i] - 1) * strides[i] - 2 * pads[i]
                           + ksize[i] for i in range(nd))
        total = int(np.prod(out_sp))
        ai = a.reshape(n, c, -1)
        ii = idx.reshape(n, c, -1).astype(jnp.int32)
        if not isinstance(ii, jax.core.Tracer):
            # eager: match the reference's error on out-of-range indices
            # (under jit XLA silently drops OOB scatters)
            hi = int(jnp.max(ii)) if ii.size else 0
            if hi >= total:
                raise ValueError(
                    f"max_unpool: index {hi} out of range for output "
                    f"size {out_sp} ({total} elements) — pass a larger "
                    "output_size")
        flat = jnp.zeros((n, c, total), a.dtype)
        flat = flat.at[jnp.arange(n)[:, None, None],
                       jnp.arange(c)[None, :, None], ii].set(ai)
        return flat.reshape((n, c) + out_sp)

    return apply(fn, x, indices, op_name="max_unpool")


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCL", name=None):
    return _max_unpool(x, indices, 1, kernel_size, stride, padding,
                       output_size, data_format)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW", name=None):
    return _max_unpool(x, indices, 2, kernel_size, stride, padding,
                       output_size, data_format)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCDHW", name=None):
    return _max_unpool(x, indices, 3, kernel_size, stride, padding,
                       output_size, data_format)


# ---------------------------------------------------------------------------
# transposed convs (1-D / 3-D) — shared N-D core in common.py
# ---------------------------------------------------------------------------

def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     data_format="NCL", output_size=None, name=None):
    from .common import _conv_transpose_nd
    return _conv_transpose_nd(x, weight, bias, 1, stride, padding,
                              output_padding, groups, dilation, output_size,
                              "conv1d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     data_format="NCDHW", output_size=None, name=None):
    from .common import _conv_transpose_nd
    return _conv_transpose_nd(x, weight, bias, 3, stride, padding,
                              output_padding, groups, dilation, output_size,
                              "conv3d_transpose")


# ---------------------------------------------------------------------------
# vision
# ---------------------------------------------------------------------------

def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    if data_format != "NCHW":
        raise NotImplementedError("pixel_unshuffle: NCHW only")
    r = int(downscale_factor)

    def fn(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c, h // r, r, w // r, r)
        a = jnp.transpose(a, (0, 1, 3, 5, 2, 4))
        return a.reshape(n, c * r * r, h // r, w // r)

    return apply(fn, x, op_name="pixel_unshuffle")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im — inverse of :func:`unfold` (overlaps sum)."""
    out_hw = _tuple(output_sizes, 2)
    ks = _tuple(kernel_sizes, 2)
    st = _tuple(strides, 2)
    pd = _tuple(paddings, 2)
    dl = _tuple(dilations, 2)

    def fn(a):
        n, ckk, L = a.shape
        c = ckk // (ks[0] * ks[1])
        ph, pw = out_hw[0] + 2 * pd[0], out_hw[1] + 2 * pd[1]
        oh = (ph - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (pw - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        cols = a.reshape(n, c, ks[0], ks[1], oh, ow)
        out = jnp.zeros((n, c, ph, pw), a.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                out = out.at[:, :,
                             i * dl[0]: i * dl[0] + oh * st[0]: st[0],
                             j * dl[1]: j * dl[1] + ow * st[1]: st[1]].add(
                    cols[:, :, i, j])
        return out[:, :, pd[0]: pd[0] + out_hw[0], pd[1]: pd[1] + out_hw[1]]

    return apply(fn, x, op_name="fold")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """theta [N, 2, 3] -> sampling grid [N, H, W, 2] (x, y in [-1, 1])."""
    if hasattr(out_shape, "tolist"):
        out_shape = out_shape.tolist()
    n, _, h, w = [int(s) for s in out_shape]

    def fn(th):
        if align_corners:
            xs = jnp.linspace(-1.0, 1.0, w)
            ys = jnp.linspace(-1.0, 1.0, h)
        else:
            xs = (jnp.arange(w) + 0.5) * 2.0 / w - 1.0
            ys = (jnp.arange(h) + 0.5) * 2.0 / h - 1.0
        gx, gy = jnp.meshgrid(xs, ys)            # [h, w]
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)   # [h, w, 3]
        out = jnp.einsum("hwk,nok->nhwo", base, th)  # [n, h, w, 2]
        return out

    return apply(fn, theta, op_name="affine_grid")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """x [N, C, H, W], grid [N, Ho, Wo, 2] (x, y normalized) ->
    [N, C, Ho, Wo]. padding_mode: zeros | border."""
    if padding_mode not in ("zeros", "border"):
        raise NotImplementedError(
            f"grid_sample padding_mode={padding_mode!r} unsupported "
            "(zeros/border only)")
    if mode not in ("bilinear", "nearest"):
        raise NotImplementedError(f"grid_sample mode={mode!r} unsupported")

    def fn(a, g):
        n, c, h, w = a.shape

        def unnorm(coord, size):
            if align_corners:
                return (coord + 1.0) * (size - 1) / 2.0
            return ((coord + 1.0) * size - 1.0) / 2.0

        gx = unnorm(g[..., 0], w)                 # [n, ho, wo]
        gy = unnorm(g[..., 1], h)

        def sample(ix, iy):
            inb = ((ix >= 0) & (ix < w) & (iy >= 0) & (iy < h))
            ixc = jnp.clip(ix, 0, w - 1)
            iyc = jnp.clip(iy, 0, h - 1)
            v = a[jnp.arange(n)[:, None, None], :, iyc, ixc]  # [n,ho,wo,c]
            if padding_mode == "zeros":
                v = jnp.where(inb[..., None], v, 0.0)
            return v

        if mode == "nearest":
            out = sample(jnp.round(gx).astype(jnp.int32),
                         jnp.round(gy).astype(jnp.int32))
        else:
            x0 = jnp.floor(gx).astype(jnp.int32)
            y0 = jnp.floor(gy).astype(jnp.int32)
            x1, y1 = x0 + 1, y0 + 1
            wx = gx - x0
            wy = gy - y0
            out = (sample(x0, y0) * ((1 - wx) * (1 - wy))[..., None]
                   + sample(x1, y0) * (wx * (1 - wy))[..., None]
                   + sample(x0, y1) * ((1 - wx) * wy)[..., None]
                   + sample(x1, y1) * (wx * wy)[..., None])
        return jnp.transpose(out, (0, 3, 1, 2))

    return apply(fn, x, grid, op_name="grid_sample")


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """TSM temporal shift (reference phi temporal_shift kernel)."""
    if data_format != "NCHW":
        raise NotImplementedError("temporal_shift: NCHW only")

    def fn(a):
        nt, c, h, w = a.shape
        n = nt // seg_num
        v = a.reshape(n, seg_num, c, h, w)
        fold_c = int(c * shift_ratio)
        left = jnp.concatenate(
            [v[:, 1:, :fold_c], jnp.zeros_like(v[:, :1, :fold_c])], axis=1)
        right = jnp.concatenate(
            [jnp.zeros_like(v[:, :1, fold_c:2 * fold_c]),
             v[:, :-1, fold_c:2 * fold_c]], axis=1)
        rest = v[:, :, 2 * fold_c:]
        return jnp.concatenate([left, right, rest],
                               axis=2).reshape(nt, c, h, w)

    return apply(fn, x, op_name="temporal_shift")


# ---------------------------------------------------------------------------
# sequence extension ops
# ---------------------------------------------------------------------------

def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    from ...framework import dtype as dtypes

    def fn(lens):
        m = maxlen if maxlen is not None else int(jnp.max(lens))
        rng = jnp.arange(m)
        return (rng[None, :] < lens[..., None]).astype(
            dtypes.convert_dtype(dtype))

    return apply(fn, x, op_name="sequence_mask")


def gather_tree(ids, parents):
    """Beam-search backtrace (reference phi gather_tree kernel):
    ids/parents [max_time, batch, beam] -> full sequences per beam."""

    def fn(i, p):
        T = i.shape[0]

        def step(beams, t):
            # beams: current beam index per [batch, beam]
            tok = jnp.take_along_axis(i[t], beams, axis=-1)
            par = jnp.take_along_axis(p[t], beams, axis=-1)
            return par, tok

        init = jnp.broadcast_to(jnp.arange(i.shape[2]), i.shape[1:])
        _, toks = lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return toks[::-1]

    return apply(fn, ids, parents, op_name="gather_tree")


# ---------------------------------------------------------------------------
# distance / losses
# ---------------------------------------------------------------------------

def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def fn(a, b):
        d = a - b + epsilon
        return jnp.linalg.norm(d, ord=p, axis=-1, keepdims=keepdim)
    return apply(fn, x, y, op_name="pairwise_distance")


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def fn(x, y):
        if log_input:
            loss = jnp.exp(x) - y * x
        else:
            loss = x - y * jnp.log(x + epsilon)
        if full:
            stirling = (y * jnp.log(y) - y
                        + 0.5 * jnp.log(2 * math.pi * y))
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)
    return apply(fn, input, label, op_name="poisson_nll_loss")


def soft_margin_loss(input, label, reduction="mean", name=None):
    def fn(x, y):
        # softplus(-yx) == log1p(exp(-yx)) without float32 overflow at
        # confident wrong predictions
        return _reduce(jax.nn.softplus(-y * x), reduction)
    return apply(fn, input, label, op_name="soft_margin_loss")


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    def fn(x, y, *w):
        loss = -(y * jax.nn.log_sigmoid(x)
                 + (1 - y) * jax.nn.log_sigmoid(-x))
        if w:
            loss = loss * w[0]
        return _reduce(jnp.mean(loss, axis=-1), reduction)
    args = (input, label) + ((weight,) if weight is not None else ())
    return apply(fn, *args, op_name="multi_label_soft_margin_loss")


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    def fn(x, y, *w):
        n, c = x.shape
        correct = jnp.take_along_axis(x, y[:, None].astype(jnp.int32), 1)
        m = jnp.maximum(margin - correct + x, 0.0) ** p
        if w:
            m = m * jnp.take(w[0], y.astype(jnp.int32))[:, None]
        hot = jax.nn.one_hot(y.astype(jnp.int32), c, dtype=x.dtype)
        return _reduce(jnp.sum(m * (1 - hot), -1) / c, reduction)
    args = (input, label) + ((weight,) if weight is not None else ())
    return apply(fn, *args, op_name="multi_margin_loss")


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    if distance_function is not None:
        dp = distance_function(input, positive)
        dn = distance_function(input, negative)
        if swap:
            dpn = distance_function(positive, negative)
            dn = apply(lambda a, b: jnp.minimum(a, b), dn, dpn,
                       op_name="tm_swap")
        return apply(lambda a, b:
                     _reduce(jnp.maximum(a - b + margin, 0.0), reduction),
                     dp, dn, op_name="triplet_margin_distance")

    def fn(a, pos, neg):
        dp = jnp.linalg.norm(a - pos, axis=-1)
        dn = jnp.linalg.norm(a - neg, axis=-1)
        if swap:
            dn = jnp.minimum(dn, jnp.linalg.norm(pos - neg, axis=-1))
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)
    return apply(fn, input, positive, negative,
                 op_name="triplet_margin_distance")


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid over a complete binary tree (default) or a
    custom path table (reference phi hsigmoid_loss kernel: heap-numbered
    internal nodes 1..K-1; leaf for class c is node c+K; loss sums
    -log sigmoid((1-2*code)*(w_n.x+b_n)) over the root->leaf path)."""
    K = int(num_classes)
    depth = max(K - 1, 1).bit_length() + 1

    def fn(x, y, w, *rest):
        it = iter(rest)
        b = next(it) if bias is not None else None
        pt = next(it) if path_table is not None else None
        pc = next(it) if path_code is not None else None
        yl = y.reshape(-1).astype(jnp.int32)
        if pt is not None:
            nodes = pt.astype(jnp.int32)         # [n, path_len]
            codes = pc.astype(x.dtype)
            valid = nodes >= 0
            nodes = jnp.maximum(nodes, 0)
        else:
            leaf = yl + K                        # heap leaf id
            # bits of `leaf` below its MSB, walked root->leaf
            nbits = jnp.floor(jnp.log2(leaf.astype(jnp.float32))
                              ).astype(jnp.int32)
            steps = jnp.arange(depth)
            shift = nbits[:, None] - 1 - steps[None, :]
            valid = shift >= 0
            sh = jnp.maximum(shift, 0)
            codes = ((leaf[:, None] >> sh) & 1).astype(x.dtype)
            # node visited before consuming each bit
            nodes = leaf[:, None] >> (sh + 1)
            nodes = jnp.where(valid, nodes, 1) - 1   # 0-based rows of w
        logits = jnp.einsum("nd,npd->np", x,
                            jnp.take(w, nodes, axis=0))
        if b is not None:
            logits = logits + jnp.take(b.reshape(-1), nodes)
        per_step = -jax.nn.log_sigmoid((1.0 - 2.0 * codes) * logits)
        loss = jnp.sum(jnp.where(valid, per_step, 0.0), axis=-1)
        return loss[:, None]

    args = [input, label, weight]
    if bias is not None:
        args.append(bias)
    if path_table is not None:
        args += [path_table, path_code]
    return apply(fn, *args, op_name="hsigmoid_loss")
