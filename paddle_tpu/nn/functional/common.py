"""nn.functional core ops: linear, conv, pooling, dropout, embedding, attention,
interpolate (reference: ``python/paddle/nn/functional/{common,conv,pooling,
input}.py`` — SURVEY.md §2.2). All map to lax/XLA; conv/matmul hit the MXU."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...framework.core import Tensor
from ...framework import random as prandom
from ...autograd.tape import apply, defop


# ---------------------------------------------------------------------------
# linear / embedding
# ---------------------------------------------------------------------------

def linear(x, weight, bias=None, name=None):
    """paddle linear: weight is [in, out] (note: transposed vs torch)."""
    if bias is None:
        return apply(lambda a, w: a @ w, x, weight, op_name="linear")
    return apply(lambda a, w, b: a @ w + b, x, weight, bias, op_name="linear")


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def fn(w, idx):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    idx = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return apply(lambda w: fn(w, idx), weight, op_name="embedding")


def one_hot(x, num_classes, name=None):
    from ...ops.manipulation import one_hot as _oh
    return _oh(x, num_classes)


# ---------------------------------------------------------------------------
# dropout
# ---------------------------------------------------------------------------

def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training and p > 0.0:
            return apply(lambda a: a * (1.0 - p), x, op_name="dropout")
        return x if isinstance(x, Tensor) else Tensor(x)
    key = prandom.next_key()

    def fn(a):
        shape = list(a.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)

    return apply(fn, x, op_name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    key = prandom.next_key()

    def fn(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p ** 2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return a_coef * jnp.where(keep, a, alpha_p) + b_coef

    return apply(fn, x, op_name="alpha_dropout")


# ---------------------------------------------------------------------------
# conv
# ---------------------------------------------------------------------------

def _conv_padding(padding, ndim, strides=None, ksize=None, dilation=None):
    """paddle padding: int, list, 'SAME'/'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * ndim
    pads = list(padding)
    if len(pads) == ndim and all(isinstance(p, int) for p in pads):
        return [(p, p) for p in pads]
    if len(pads) == 2 * ndim:
        return [(pads[2 * i], pads[2 * i + 1]) for i in range(ndim)]
    return [tuple(p) for p in pads]


def _tuple(v, n):
    return tuple(v) if isinstance(v, (list, tuple)) else (v,) * n


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    nd = 2
    strides = _tuple(stride, nd)
    dil = _tuple(dilation, nd)
    pad = _conv_padding(padding, nd)
    dn = ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "HWIO", "NHWC")

    def fn(a, w, *b):
        if data_format != "NCHW":
            w = jnp.transpose(w, (2, 3, 1, 0))
        out = lax.conv_general_dilated(
            a, w, window_strides=strides, padding=pad,
            rhs_dilation=dil, dimension_numbers=dn, feature_group_count=groups,
            preferred_element_type=None)
        if b:
            bias_shape = [1, -1, 1, 1] if data_format == "NCHW" else [1, 1, 1, -1]
            out = out + b[0].reshape(bias_shape)
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply(fn, *args, op_name="conv2d")


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    strides = _tuple(stride, 1)
    dil = _tuple(dilation, 1)
    pad = _conv_padding(padding, 1)
    dn = ("NCH", "OIH", "NCH") if data_format == "NCL" else ("NHC", "HIO", "NHC")

    def fn(a, w, *b):
        if data_format != "NCL":
            # weights come in Paddle [out, in, k] layout; lax expects HIO here
            w = jnp.transpose(w, (2, 1, 0))
        out = lax.conv_general_dilated(a, w, window_strides=strides, padding=pad,
                                       rhs_dilation=dil, dimension_numbers=dn,
                                       feature_group_count=groups)
        if b:
            shape = [1, -1, 1] if data_format == "NCL" else [1, 1, -1]
            out = out + b[0].reshape(shape)
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply(fn, *args, op_name="conv1d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    strides = _tuple(stride, 3)
    dil = _tuple(dilation, 3)
    pad = _conv_padding(padding, 3)
    dn = ("NCDHW", "OIDHW", "NCDHW")

    def fn(a, w, *b):
        out = lax.conv_general_dilated(a, w, window_strides=strides, padding=pad,
                                       rhs_dilation=dil, dimension_numbers=dn,
                                       feature_group_count=groups)
        if b:
            out = out + b[0].reshape([1, -1, 1, 1, 1])
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply(fn, *args, op_name="conv3d")


def _conv_transpose_nd(x, weight, bias, nd, stride, padding, output_padding,
                       groups, dilation, output_size, op_name):
    """Shared N-D transposed convolution (paddle weight layout
    [in_c, out_c/groups, *k]); ``output_size`` resolves the stride
    ambiguity by overriding the per-dim output padding."""
    strides = _tuple(stride, nd)
    dil = _tuple(dilation, nd)
    opad = list(_tuple(output_padding, nd))
    padding_ = padding
    dn_map = {1: ("NCH", "OIH", "NCH"), 2: ("NCHW", "OIHW", "NCHW"),
              3: ("NCDHW", "OIDHW", "NCDHW")}
    if output_size is not None:
        if isinstance(padding_, str):
            raise NotImplementedError(
                "output_size with string padding is unsupported")
        if hasattr(output_size, "tolist"):
            output_size = output_size.tolist()
        out_sp = [int(s) for s in tuple(output_size)[-nd:]]
        p = _conv_padding(padding_, nd)
        kshape = weight.shape[2:]
        in_sp = x.shape[2:2 + nd]
        for i in range(nd):
            base = ((int(in_sp[i]) - 1) * strides[i] - p[i][0] - p[i][1]
                    + dil[i] * (int(kshape[i]) - 1) + 1)
            extra = out_sp[i] - base
            if extra < 0 or extra >= strides[i] + max(0, dil[i] - 1):
                raise ValueError(
                    f"output_size[{i}]={out_sp[i]} unreachable "
                    f"(base {base}, stride {strides[i]})")
            opad[i] = extra

    def fn(a, w, *b):
        kshape = w.shape[2:]
        if isinstance(padding_, str):
            pad = padding_.upper()
        else:
            p = _conv_padding(padding_, nd)
            # transposed conv padding math (gradient-style):
            # pad_t = dil*(k-1) - pad, high side + output_padding
            pad = [(dil[i] * (kshape[i] - 1) - p[i][0],
                    dil[i] * (kshape[i] - 1) - p[i][1] + opad[i])
                   for i in range(nd)]
        w_flip = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
        if groups == 1:
            w_t = jnp.swapaxes(w_flip, 0, 1)   # -> [out_c, in_c, *k]
        else:
            ic, ocg = w.shape[0], w.shape[1]
            w_g = w_flip.reshape(groups, ic // groups, ocg, *kshape)
            w_t = jnp.swapaxes(w_g, 1, 2).reshape(groups * ocg, ic // groups,
                                                  *kshape)
        out = lax.conv_general_dilated(
            a, w_t, window_strides=(1,) * nd, padding=pad,
            lhs_dilation=strides, rhs_dilation=dil,
            dimension_numbers=dn_map[nd], feature_group_count=groups)
        if b:
            out = out + b[0].reshape([1, -1] + [1] * nd)
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply(fn, *args, op_name=op_name)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, data_format="NCHW", output_size=None,
                     name=None):
    return _conv_transpose_nd(x, weight, bias, 2, stride, padding,
                              output_padding, groups, dilation, output_size,
                              "conv2d_transpose")


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------

def _pool(x, ksize, strides, padding, reducer, init, data_format="NCHW",
          ceil_mode=False, norm=None, count_include_pad=True):
    nd = len(ksize)

    def fn(a):
        channels_first = data_format in ("NCHW", "NCL", "NCDHW")
        spatial = a.shape[2:2 + nd] if channels_first else a.shape[1:1 + nd]
        if isinstance(padding, str):
            spad = [(0, 0)] * nd if padding.upper() == "VALID" else None
            if spad is None:  # SAME
                spad = []
                for i in range(nd):
                    out_i = -(-spatial[i] // strides[i])
                    tot = max((out_i - 1) * strides[i] + ksize[i] - spatial[i], 0)
                    spad.append((tot // 2, tot - tot // 2))
        else:
            spad = [tuple(p) for p in padding]
        counted_pad = list(spad)  # pad that counts toward avg when include_pad
        if ceil_mode:
            # extend the high side so the last partial window is produced
            spad = list(spad)
            for i in range(nd):
                eff = spatial[i] + spad[i][0] + spad[i][1]
                rem = (eff - ksize[i]) % strides[i]
                if rem:
                    spad[i] = (spad[i][0], spad[i][1] + strides[i] - rem)
        if channels_first:
            window = (1, 1) + tuple(ksize)
            strd = (1, 1) + tuple(strides)
            pad = [(0, 0), (0, 0)] + spad
            cpad = [(0, 0), (0, 0)] + counted_pad
        else:
            window = (1,) + tuple(ksize) + (1,)
            strd = (1,) + tuple(strides) + (1,)
            pad = [(0, 0)] + spad + [(0, 0)]
            cpad = [(0, 0)] + counted_pad + [(0, 0)]
        out = lax.reduce_window(a, init, reducer, window, strd, pad)
        if norm == "avg":
            if count_include_pad and not ceil_mode \
                    and all(p == (0, 0) for p in spad):
                out = out / float(np.prod(ksize))
            else:
                # count only real elements (+ user padding when include_pad):
                # reduce a ones-array padded the same way
                ones = jnp.ones_like(a)
                if count_include_pad:
                    ones = jnp.pad(ones, cpad, constant_values=1.0)
                    extra = [(p[0] - c[0], p[1] - c[1])
                             for p, c in zip(pad, cpad)]
                    counts = lax.reduce_window(ones, 0.0, lax.add, window, strd,
                                               extra)
                else:
                    counts = lax.reduce_window(ones, 0.0, lax.add, window, strd,
                                               pad)
                out = out / counts
        return out

    return apply(fn, x, op_name="pool")


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    ksize = _tuple(kernel_size, 2)
    strides = _tuple(stride, 2) if stride is not None else ksize
    if return_mask:
        from .extras import _max_pool_with_index, _check_index_pool_args
        _check_index_pool_args(padding, ceil_mode, data_format, "NCHW")
        return _max_pool_with_index(x, ksize, strides, _tuple(padding, 2))
    pad = _conv_padding(padding, 2) if not isinstance(padding, str) else padding
    return _pool(x, ksize, strides, pad, lax.max, -jnp.inf, data_format, ceil_mode)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    ksize = _tuple(kernel_size, 2)
    strides = _tuple(stride, 2) if stride is not None else ksize
    pad = _conv_padding(padding, 2) if not isinstance(padding, str) else padding
    return _avg_pool_impl(x, ksize, strides, pad, data_format, ceil_mode,
                          exclusive, divisor_override)


def _avg_pool_impl(x, ksize, strides, pad, data_format, ceil_mode,
                   exclusive, divisor_override):
    """Shared avg-pool tail: divisor_override = fixed divisor (window
    sums / divisor), else true mean with the exclusive/include-pad rule."""
    if divisor_override:
        sums = _pool(x, ksize, strides, pad, lax.add, 0.0, data_format,
                     ceil_mode)
        return apply(lambda s: s / float(divisor_override), sums,
                     op_name="avg_pool_divisor")
    return _pool(x, ksize, strides, pad, lax.add, 0.0, data_format,
                 ceil_mode, norm="avg", count_include_pad=not exclusive)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    ksize = _tuple(kernel_size, 1)
    strides = _tuple(stride, 1) if stride is not None else ksize
    if return_mask:
        from .extras import _max_pool_with_index, _check_index_pool_args
        _check_index_pool_args(padding, ceil_mode, "NCL", "NCL")
        return _max_pool_with_index(x, ksize, strides, _tuple(padding, 1))
    pad = _conv_padding(padding, 1) if not isinstance(padding, str) else padding
    return _pool(x, ksize, strides, pad, lax.max, -jnp.inf, "NCL", ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    ksize = _tuple(kernel_size, 1)
    strides = _tuple(stride, 1) if stride is not None else ksize
    pad = _conv_padding(padding, 1) if not isinstance(padding, str) else padding
    return _pool(x, ksize, strides, pad, lax.add, 0.0, "NCL", ceil_mode, norm="avg",
                 count_include_pad=not exclusive)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    out_hw = _tuple(output_size, 2)

    def fn(a):
        h, w = (a.shape[2], a.shape[3]) if data_format == "NCHW" else (a.shape[1], a.shape[2])
        oh = out_hw[0] or h
        ow = out_hw[1] or w
        if h % oh == 0 and w % ow == 0:
            kh, kw = h // oh, w // ow
            if data_format == "NCHW":
                r = a.reshape(a.shape[0], a.shape[1], oh, kh, ow, kw)
                return r.mean(axis=(3, 5))
            r = a.reshape(a.shape[0], oh, kh, ow, kw, a.shape[3])
            return r.mean(axis=(2, 4))
        # general case: integral-image style via per-output-bin mean
        hi = [int(np.floor(i * h / oh)) for i in range(oh)]
        hie = [int(np.ceil((i + 1) * h / oh)) for i in range(oh)]
        wi = [int(np.floor(j * w / ow)) for j in range(ow)]
        wie = [int(np.ceil((j + 1) * w / ow)) for j in range(ow)]
        rows = []
        for i in range(oh):
            cols = []
            for j in range(ow):
                if data_format == "NCHW":
                    cols.append(a[:, :, hi[i]:hie[i], wi[j]:wie[j]].mean(axis=(2, 3)))
                else:
                    cols.append(a[:, hi[i]:hie[i], wi[j]:wie[j], :].mean(axis=(1, 2)))
            rows.append(jnp.stack(cols, axis=-1))
        out = jnp.stack(rows, axis=-2)
        return out

    return apply(fn, x, op_name="adaptive_avg_pool2d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out_hw = _tuple(output_size, 2)

    def fn(a):
        h, w = a.shape[2], a.shape[3]
        oh, ow = out_hw[0] or h, out_hw[1] or w
        if h % oh == 0 and w % ow == 0:
            kh, kw = h // oh, w // ow
            r = a.reshape(a.shape[0], a.shape[1], oh, kh, ow, kw)
            return r.max(axis=(3, 5))
        raise NotImplementedError("adaptive_max_pool2d with non-divisible sizes")

    return apply(fn, x, op_name="adaptive_max_pool2d")


def adaptive_avg_pool1d(x, output_size, name=None):
    def fn(a):
        l = a.shape[2]
        ol = output_size
        if l % ol == 0:
            return a.reshape(a.shape[0], a.shape[1], ol, l // ol).mean(axis=3)
        raise NotImplementedError

    return apply(fn, x, op_name="adaptive_avg_pool1d")


# ---------------------------------------------------------------------------
# padding / upsample
# ---------------------------------------------------------------------------

def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ...ops.manipulation import pad as _pad
    return _pad(x, pad, mode, value, data_format)


def _bilinear_align_corners(a, oh, ow):
    """Bilinear resize with align_corners=True grid (src = i*(H-1)/(OH-1));
    jax.image.resize only does the half-pixel convention."""
    h, w = a.shape[2], a.shape[3]
    ys = jnp.linspace(0.0, h - 1.0, oh)
    xs = jnp.linspace(0.0, w - 1.0, ow)
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    wy = (ys - y0).astype(a.dtype)[:, None]      # [oh, 1]
    wx = (xs - x0).astype(a.dtype)[None, :]      # [1, ow]
    tl = a[:, :, y0][:, :, :, x0]
    tr = a[:, :, y0][:, :, :, x1]
    bl = a[:, :, y1][:, :, :, x0]
    br = a[:, :, y1][:, :, :, x1]
    top = tl * (1 - wx) + tr * wx
    bot = bl * (1 - wx) + br * wx
    return top * (1 - wy) + bot * wy


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW", name=None):
    # 3-D (NCL/NWC) input: treat length as W with a singleton H, then squeeze.
    if x.ndim == 3:
        chan_last = data_format in ("NWC", "NLC")
        xs = x.unsqueeze(2) if not chan_last else x.unsqueeze(1)
        size2 = [1, int(size[0] if isinstance(size, (list, tuple)) else size)] \
            if size is not None else None
        sf = scale_factor
        if sf is not None:
            sf = [1, sf[0] if isinstance(sf, (list, tuple)) else sf]
        mode2 = "bilinear" if mode == "linear" else mode
        out = interpolate(xs, size2, sf, mode2, align_corners, align_mode,
                          "NCHW" if not chan_last else "NHWC")
        return out.squeeze(2) if not chan_last else out.squeeze(1)

    def fn(a):
        n, c, h, w = a.shape if data_format == "NCHW" else \
            (a.shape[0], a.shape[3], a.shape[1], a.shape[2])
        if size is not None:
            oh, ow = int(size[0]), int(size[1])
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
                else [scale_factor, scale_factor]
            oh, ow = int(h * sf[0]), int(w * sf[1])
        if data_format != "NCHW":
            a = jnp.transpose(a, (0, 3, 1, 2))
        if mode == "nearest":
            ridx = (jnp.arange(oh) * h // oh).astype(jnp.int32)
            cidx = (jnp.arange(ow) * w // ow).astype(jnp.int32)
            out = a[:, :, ridx][:, :, :, cidx]
        elif mode in ("bilinear", "linear"):
            if align_corners and oh > 1 and ow > 1:
                out = _bilinear_align_corners(a, oh, ow)
            else:
                out = jax.image.resize(a, (a.shape[0], a.shape[1], oh, ow),
                                       method="linear")
        elif mode == "bicubic":
            out = jax.image.resize(a, (a.shape[0], a.shape[1], oh, ow), method="cubic")
        else:
            raise NotImplementedError(mode)
        if data_format != "NCHW":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return apply(fn, x, op_name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def fn(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c // (r * r), r, r, h, w)
        a = jnp.transpose(a, (0, 1, 4, 2, 5, 3))
        return a.reshape(n, c // (r * r), h * r, w * r)

    return apply(fn, x, op_name="pixel_shuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    """reference: ``paddle.nn.functional.channel_shuffle``."""
    def fn(a):
        if data_format == "NHWC":
            n, h, w, c = a.shape
            a = a.reshape(n, h, w, groups, c // groups)
            a = jnp.swapaxes(a, 3, 4)
            return a.reshape(n, h, w, c)
        n, c, h, w = a.shape
        a = a.reshape(n, groups, c // groups, h, w)
        a = jnp.swapaxes(a, 1, 2)
        return a.reshape(n, c, h, w)

    return apply(fn, x, op_name="channel_shuffle")


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    ks = _tuple(kernel_sizes, 2)
    st = _tuple(strides, 2)
    pd = _tuple(paddings, 2)
    dl = _tuple(dilations, 2)

    def fn(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, [(0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])])
        oh = (a.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (a.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        patches = []
        for i in range(ks[0]):
            for j in range(ks[1]):
                patches.append(a[:, :, i * dl[0]: i * dl[0] + oh * st[0]: st[0],
                                 j * dl[1]: j * dl[1] + ow * st[1]: st[1]])
        out = jnp.stack(patches, axis=2)  # [n, c, k*k, oh, ow]
        return out.reshape(n, c * ks[0] * ks[1], oh * ow)

    return apply(fn, x, op_name="unfold")


# ---------------------------------------------------------------------------
# attention
# ---------------------------------------------------------------------------

def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None):
    """paddle.nn.functional.scaled_dot_product_attention.

    Layout [batch, seq, heads, head_dim] (paddle flash-attn convention —
    reference wires FA2 as a phi kernel, SURVEY.md §2.1). Causal/full attention
    without mask/dropout dispatches to the Pallas flash-attention kernel
    (``paddle_tpu/ops/pallas/flash_attention.py``) on TPU — FA2's phi-kernel
    role; gate with FLAGS_use_flash_attention. Masked/dropout paths use XLA.
    """
    from ...flags import flag as _flag
    use_flash = (_flag("FLAGS_use_flash_attention", True)
                 and attn_mask is None
                 and (dropout_p == 0.0 or not training)
                 and jax.default_backend() == "tpu"
                 and query.shape[1] >= 128 and query.shape[-1] % 64 == 0)
    if use_flash:
        from ...ops.pallas import flash_attention as _fa
        # bottom-right causal alignment when sq != sk (KV-cache decode):
        # local query i sits at global position (sk - sq) + i
        q_off = key.shape[1] - query.shape[1]

        def flash_fn(q, k, v):
            return _fa(q, k, v, causal=is_causal, q_offset=q_off,
                       interpret=False)

        return apply(flash_fn, query, key, value, op_name="flash_attn")

    dk = prandom.next_key() if (dropout_p > 0.0 and training) else None

    # long-sequence memory safety: with flash unavailable (quarantined
    # kernel, disabled flag, CPU) a no-mask/no-dropout attention at
    # seq >= 4096 would materialize an S×S fp32 logits tensor — route it
    # through the pure-XLA tier dispatcher instead (flash-like memory:
    # per-chunk remat + causal kv-prefix trim, or the scan tiers per
    # PADDLE_TPU_XFA)
    # trigger on EITHER the seq product (any single [sq, sk] logits plane
    # at 4096^2 is flash territory regardless of b*h) OR total logits
    # bytes (b=8, h=32, s=2048 is ~4.3 GB of fp32 logits with a tiny
    # seq product)
    n_logits = (query.shape[0] * query.shape[2]
                * query.shape[1] * key.shape[1])
    if (attn_mask is None and (dropout_p == 0.0 or not training)
            and query.shape[1] > 1
            and (query.shape[1] * key.shape[1] >= 4096 * 4096
                 or n_logits * 4 >= 1 << 30)):   # >= 1 GiB of fp32 logits
        from ...ops.pallas.flash_attention import xla_attention

        def chunked_fn(q, k, v):
            qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
            q_off = kt.shape[2] - qt.shape[2] if is_causal else 0
            out = xla_attention(qt, kt, vt, causal=is_causal,
                                q_offset=q_off)
            return jnp.swapaxes(out, 1, 2)

        return apply(chunked_fn, query, key, value, op_name="sdpa_chunked")

    def fn(q, k, v, *mask):
        scale = 1.0 / np.sqrt(q.shape[-1])
        # [b, s, h, d] -> [b, h, s, d]
        qt = jnp.swapaxes(q, 1, 2)
        kt = jnp.swapaxes(k, 1, 2)
        vt = jnp.swapaxes(v, 1, 2)
        if kt.shape[1] != qt.shape[1]:
            # GQA: grouped einsum — no materialized K/V repeats
            rep = qt.shape[1] // kt.shape[1]
            qg = qt.reshape(qt.shape[0], kt.shape[1], rep, *qt.shape[2:])
            logits = jnp.einsum("bhgqd,bhkd->bhgqk", qg, kt) * scale
            logits = logits.reshape(qt.shape[0], qt.shape[1],
                                    *logits.shape[3:])
        else:
            logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
        if is_causal:
            sq, sk = logits.shape[-2], logits.shape[-1]
            causal = jnp.tril(jnp.ones((sq, sk), bool), sk - sq)
            logits = jnp.where(causal, logits, -jnp.inf)
        if mask:
            m = mask[0]
            if m.dtype == jnp.bool_:
                logits = jnp.where(m, logits, -jnp.inf)
            else:
                logits = logits + m
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
        if dk is not None:
            keep = jax.random.bernoulli(dk, 1.0 - dropout_p, probs.shape)
            probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
        if vt.shape[1] != qt.shape[1]:
            rep = qt.shape[1] // vt.shape[1]
            pg = probs.reshape(probs.shape[0], vt.shape[1], rep,
                               *probs.shape[2:])
            out = jnp.einsum("bhgqk,bhkd->bhgqd", pg, vt)
            out = out.reshape(probs.shape[0], qt.shape[1], *out.shape[3:])
        else:
            out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
        return jnp.swapaxes(out, 1, 2)

    args = (query, key, value) + ((attn_mask,) if attn_mask is not None else ())
    return apply(fn, *args, op_name="sdpa")


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------

def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def fn(l):
        k = l.shape[-1]
        smooth = (1.0 - epsilon) * l + epsilon * (1.0 / k if prior_dist is None else prior_dist)
        return smooth

    return apply(fn, label, op_name="label_smooth")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(a):
        nrm = jnp.linalg.norm(a, ord=p, axis=axis, keepdims=True)
        return a / jnp.maximum(nrm, epsilon)

    return apply(fn, x, op_name="normalize")


def unfold_channels(x, kernel_sizes, strides=1, paddings=0, dilations=1,
                    name=None):
    """im2col with channel-major patch ordering ([c0·k00, c0·k01, …]) —
    the layout :func:`unfold` already produces; kept as a distinct name
    for callers that spell the reference's channels variant."""
    return unfold(x, kernel_sizes, strides=strides, paddings=paddings,
                  dilations=dilations, name=name)


def bilinear(x1, x2, weight, bias=None, name=None):
    def fn(a, b, w, *bias_):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bias_:
            out = out + bias_[0]
        return out

    args = (x1, x2, weight) + ((bias,) if bias is not None else ())
    return apply(fn, *args, op_name="bilinear")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """reference: ``paddle.nn.functional.zeropad2d`` — [left, right,
    top, bottom] zero padding."""
    return pad(x, padding, mode="constant", value=0.0,
               data_format=data_format)


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """Alpha dropout that drops whole channels (reference:
    ``paddle.nn.functional.feature_alpha_dropout``)."""
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    key = prandom.next_key()

    def fn(a):
        mshape = a.shape[:2] + (1,) * (a.ndim - 2)
        keep = jax.random.bernoulli(key, 1.0 - p, mshape)
        q = 1.0 - p
        a_coef = (q + alpha_p ** 2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return a_coef * jnp.where(keep, a, alpha_p) + b_coef

    return apply(fn, x, op_name="feature_alpha_dropout")


def class_center_sample(label, num_classes, num_samples, group=None):
    """reference: ``paddle.nn.functional.class_center_sample`` (PLSC) —
    sample the positive class centers plus random negatives; returns
    (remapped_label, sampled_class_indices). Host-side sampling (eager
    data-prep op in the reference too)."""
    import numpy as np_
    yv = np_.asarray(label.numpy() if hasattr(label, "numpy")
                     else label).reshape(-1)
    pos = np_.unique(yv)
    n_extra = max(int(num_samples) - pos.size, 0)
    rest = np_.setdiff1d(np_.arange(num_classes), pos, assume_unique=False)
    if n_extra > 0 and rest.size:
        # negatives drawn through the framework's seeded key tree, not
        # numpy's global RNG — deterministic under paddle.seed() and
        # identical across same-seed data-parallel workers
        seed = int(jax.random.randint(prandom.next_key(), (), 0, 2 ** 31 - 1))
        extra = np_.random.default_rng(seed).permutation(rest)[:n_extra]
        sampled = np_.concatenate([pos, np_.sort(extra)])
    else:
        sampled = pos
    remap = np_.full(num_classes, -1, np_.int64)
    remap[sampled] = np_.arange(sampled.size)
    from ...framework.core import Tensor as _T
    return (_T(jnp.asarray(remap[yv], jnp.int32)),
            _T(jnp.asarray(sampled, jnp.int32)))


def sparse_attention(query, key, value, sparse_csr_offset=None,
                     sparse_csr_columns=None, sparse_mask=None,
                     key_padding_mask=None, attn_mask=None, name=None):
    """reference: ``paddle.nn.functional.sparse_attention`` — attention
    restricted to a per-(batch, head) CSR pattern (offset [B,H,S+1],
    columns [B,H,nnz]), with optional ``key_padding_mask`` [B,S] and
    additive ``attn_mask`` [S,S]. The MXU has no sparse systolic path
    (same rationale as paddle_tpu.sparse's attention tier), so the
    pattern becomes an additive dense mask over one fused
    einsum+softmax chain."""
    def _np(t):
        return np.asarray(t.numpy() if hasattr(t, "numpy") else t)

    b, h, s, _ = query.shape
    if sparse_mask is not None:
        from ...sparse import is_sparse as _is_sp
        dense = _np(sparse_mask.to_dense() if _is_sp(sparse_mask)
                    else sparse_mask).reshape(b, h, s, s)
        allowed = dense != 0
    elif sparse_csr_offset is not None and sparse_csr_columns is not None:
        offs = _np(sparse_csr_offset).reshape(b, h, s + 1).astype(np.int64)
        cols = _np(sparse_csr_columns).reshape(b, h, -1).astype(np.int64)
        # vectorized CSR expansion: nnz entry j of (bi, hi) belongs to the
        # row whose offset range contains j
        allowed = np.zeros((b, h, s, s), bool)
        nnz = cols.shape[-1]
        j = np.arange(nnz)
        # rows[bi, hi, j] = searchsorted(offs[bi, hi], j, side='right') - 1
        rows = (offs[..., None, 1:-1] <= j[:, None]).sum(-1)  # [B,H,nnz]
        valid = j < offs[..., -1:]                            # inside nnz
        bi, hi, ji = np.nonzero(valid)
        allowed[bi, hi, rows[bi, hi, ji], cols[bi, hi, ji]] = True
    else:
        raise ValueError("sparse_attention needs sparse_mask or CSR "
                         "offset+columns")
    if key_padding_mask is not None:
        # [B, S]: zero/False marks padded keys — disallowed for every query
        keep = _np(key_padding_mask).astype(bool)
        allowed = allowed & keep[:, None, None, :]
    add = None
    if attn_mask is not None:
        add = jnp.asarray(_np(attn_mask), jnp.float32)
    allowed_j = jnp.asarray(allowed)

    # a row with NO allowed keys (empty CSR row, or key_padding_mask
    # masking every key) must output zero, not a uniform average over all
    # keys — the -1e30 fill alone would softmax to uniform
    dead_row = jnp.asarray(~allowed.any(-1))          # [B, H, S]

    def fn(q, k, v):
        lg = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / (q.shape[-1] ** 0.5)
        if add is not None:
            lg = lg + add
        lg = jnp.where(allowed_j, lg, -1e30)
        w = jax.nn.softmax(lg, axis=-1)
        w = jnp.where(dead_row[..., None], 0.0, w)
        return jnp.einsum("bhqk,bhkd->bhqd", w,
                          v.astype(jnp.float32)).astype(q.dtype)

    return apply(fn, query, key, value, op_name="sparse_attention")
