"""nn.functional activations (reference: ``python/paddle/nn/functional/
activation.py`` — SURVEY.md §2.2)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...autograd.tape import apply, defop


@defop
def relu(x):
    return jax.nn.relu(x)


def relu_(x):
    out = relu(x)
    return x._replace_(out._data, out._grad_node, out._out_idx)


@defop
def relu6(x):
    return jnp.clip(x, 0, 6)


def gelu(x, approximate=False):
    return apply(lambda a: jax.nn.gelu(a, approximate=approximate), x, op_name="gelu")


@defop
def silu(x):
    return jax.nn.silu(x)


swish = silu


@defop
def sigmoid(x):
    return jax.nn.sigmoid(x)


@defop
def hardsigmoid(x, slope=0.1666667, offset=0.5):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


@defop
def hardswish(x):
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


@defop
def hardtanh(x, min=-1.0, max=1.0):
    return jnp.clip(x, min, max)


@defop
def tanh(x):
    return jnp.tanh(x)


@defop
def tanhshrink(x):
    return x - jnp.tanh(x)


def leaky_relu(x, negative_slope=0.01):
    return apply(lambda a: jax.nn.leaky_relu(a, negative_slope), x, op_name="leaky_relu")


def elu(x, alpha=1.0):
    return apply(lambda a: jax.nn.elu(a, alpha), x, op_name="elu")


def celu(x, alpha=1.0):
    return apply(lambda a: jax.nn.celu(a, alpha), x, op_name="celu")


@defop
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@defop
def softplus(x, beta=1.0, threshold=20.0):
    return jnp.where(x * beta > threshold, x,
                     jnp.log1p(jnp.exp(beta * jnp.minimum(x, threshold / beta))) / beta)


@defop
def softshrink(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


@defop
def hardshrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


@defop
def softsign(x):
    return x / (1 + jnp.abs(x))


@defop
def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


def softmax(x, axis=-1, dtype=None, name=None):
    return apply(lambda a: jax.nn.softmax(
        a.astype(dtype) if dtype else a, axis=axis), x, op_name="softmax")


def log_softmax(x, axis=-1, dtype=None, name=None):
    return apply(lambda a: jax.nn.log_softmax(
        a.astype(dtype) if dtype else a, axis=axis), x, op_name="log_softmax")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...framework import random as prandom

    def fn(a):
        g = jax.random.gumbel(prandom.next_key(), a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if not hard:
            return y
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        y_hard = jnp.zeros_like(y)
        y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
        return y + jax.lax.stop_gradient(y_hard - y)

    return apply(fn, x, op_name="gumbel_softmax")


@defop
def maxout(x, groups, axis=1):
    # phi MaxOutFunctor: output channel i = max over CONSECUTIVE input
    # channels {i*groups + k}, so the channel dim splits as
    # (c//groups, groups) with the max over the inner factor.
    c = x.shape[axis]
    new_shape = list(x.shape)
    new_shape[axis] = c // groups
    new_shape.insert(axis + 1, groups)
    return jnp.max(jnp.reshape(x, new_shape), axis=axis + 1)


@defop
def glu(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


def prelu(x, weight, data_format="NCHW"):
    def fn(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        shape = [1] * a.ndim
        ch_axis = 1 if data_format == "NCHW" else a.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(a > 0, a, w.reshape(shape) * a)

    return apply(fn, x, weight, op_name="prelu")


def rrelu(x, lower=1.0 / 8, upper=1.0 / 3, training=True):
    from ...framework import random as prandom

    def fn(a):
        if training:
            slope = jax.random.uniform(prandom.next_key(), a.shape, a.dtype,
                                       minval=lower, maxval=upper)
        else:
            slope = (lower + upper) / 2.0
        return jnp.where(a >= 0, a, slope * a)

    return apply(fn, x, op_name="rrelu")


@defop
def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


# reference spells both: paddle.nn.functional.log_sigmoid is canonical,
# logsigmoid survives as the compat alias
logsigmoid = log_sigmoid
