"""Loss functionals (reference: ``python/paddle/nn/functional/loss.py`` —
SURVEY.md §2.2)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor
from ...autograd.tape import apply


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    def fn(logits, lab, *w):
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis) if use_softmax \
            else jnp.log(jnp.maximum(logits.astype(jnp.float32), 1e-30))
        n_classes = logits.shape[axis]
        if soft_label:
            soft = lab
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
            if w:
                wshape = [1] * lp.ndim
                wshape[axis % lp.ndim] = -1
                soft = soft * w[0].reshape(wshape)
            loss = -jnp.sum(soft * lp, axis=axis)
        else:
            idx = lab.astype(jnp.int32)
            if idx.ndim == lp.ndim:  # [N, 1] -> [N]
                idx = jnp.squeeze(idx, axis)
            safe_idx = jnp.where(idx == ignore_index, 0, idx)
            picked = jnp.take_along_axis(lp, safe_idx[..., None], axis=axis)[..., 0]
            if label_smoothing > 0:
                smooth_loss = -jnp.mean(lp, axis=axis)
                loss = -(1 - label_smoothing) * picked + label_smoothing * smooth_loss
            else:
                loss = -picked
            mask = (idx != ignore_index)
            loss = jnp.where(mask, loss, 0.0)
            if w:
                cw = jnp.take(w[0], safe_idx)
                loss = loss * jnp.where(mask, cw, 0.0)
                if reduction == "mean":
                    return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(mask, cw, 0.0)), 1e-12)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(mask.astype(jnp.float32)), 1.0)
        return _reduce(loss, reduction)

    args = (input, label) + ((weight,) if weight is not None else ())
    return apply(fn, *args, op_name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    loss = loss.unsqueeze(-1)
    if return_softmax:
        from .activation import softmax
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    """paddle.nn.functional.nll_loss — input is LOG-probabilities
    [N, C, d1, ...] (torch/paddle contract; NOT probabilities — that is
    ``cross_entropy(use_softmax=False)``'s convention)."""
    def fn(lp, lab, *w):
        lp = lp.astype(jnp.float32)
        idx = lab.astype(jnp.int32)
        safe = jnp.where(idx == ignore_index, 0, idx)
        # class axis is 1 for N-D input ([N, C, d1, ...])
        picked = jnp.take_along_axis(lp, safe[:, None] if lp.ndim > 1
                                     else safe[None], axis=1 if lp.ndim > 1
                                     else 0)
        loss = -jnp.squeeze(picked, axis=1) if lp.ndim > 1 else -picked
        mask = idx != ignore_index
        loss = jnp.where(mask, loss, 0.0)
        if w:
            cw = jnp.take(w[0], safe) * mask.astype(jnp.float32)
            if reduction == "mean":
                return jnp.sum(loss * cw) / jnp.maximum(jnp.sum(cw), 1e-30)
            loss = loss * cw
        elif reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(mask.astype(jnp.float32)), 1.0)
        return _reduce(loss, reduction)

    args = (input, label) + ((weight,) if weight is not None else ())
    return apply(fn, *args, op_name="nll_loss")


def mse_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.square(a - b), reduction), input, label,
                 op_name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label,
                 op_name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)

    return apply(fn, input, label, op_name="smooth_l1_loss")


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    """Paddle's huber_loss argument order; the Huber form itself is
    smooth_l1 (reference: ``paddle.nn.functional.huber_loss``)."""
    return smooth_l1_loss(input, label, reduction, delta)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    """Negative log likelihood of a diagonal Gaussian
    (reference: ``paddle.nn.functional.gaussian_nll_loss``)."""
    def fn(mu, y, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + (y - mu) ** 2 / var)
        if full:
            loss = loss + 0.5 * float(np.log(2 * np.pi))
        return _reduce(loss, reduction)

    return apply(fn, input, label, variance, op_name="gaussian_nll_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def fn(p, y, *w):
        p_ = jnp.clip(p, 1e-12, 1 - 1e-7)
        loss = -(y * jnp.log(p_) + (1 - y) * jnp.log1p(-p_))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    args = (input, label) + ((weight,) if weight is not None else ())
    return apply(fn, *args, op_name="bce")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def fn(z, y, *rest):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = rest[i]
            i += 1
        if pos_weight is not None:
            pw = rest[i]
        # stable: max(z,0) - z*y + log(1+exp(-|z|)), with optional pos_weight
        if pw is not None:
            log_w = (pw - 1) * y + 1
            loss = (1 - y) * z + log_w * (jnp.log1p(jnp.exp(-jnp.abs(z)))
                                          + jnp.maximum(-z, 0))
        else:
            loss = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    args = (logit, label) + tuple(t for t in (weight, pos_weight) if t is not None)
    return apply(fn, *args, op_name="bce_with_logits")


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def fn(lp, y):
        tgt = jnp.exp(y) if log_target else y
        logt = y if log_target else jnp.log(jnp.maximum(y, 1e-30))
        loss = tgt * (logt - lp)
        if reduction == "batchmean":
            return jnp.sum(loss) / lp.shape[0]
        return _reduce(loss, reduction)

    return apply(fn, input, label, op_name="kl_div")


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def fn(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.linalg.norm(a, axis=axis)
        nb = jnp.linalg.norm(b, axis=axis)
        return dot / jnp.maximum(na * nb, eps)

    return apply(fn, x1, x2, op_name="cosine_similarity")


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def fn(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return apply(fn, input1, input2, label, op_name="cosine_embedding_loss")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return apply(lambda a, b, y: _reduce(jnp.maximum(0.0, -y * (a - b) + margin),
                                         reduction),
                 input, other, label, op_name="margin_ranking_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return apply(lambda a, y: _reduce(
        jnp.where(y == 1, a, jnp.maximum(0.0, margin - a)), reduction),
        input, label, op_name="hinge_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def fn(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)

    return apply(fn, input, positive, negative, op_name="triplet_margin_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def fn(z, y, *n):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce(loss, reduction)

    args = (logit, label) + ((normalizer,) if normalizer is not None else ())
    return apply(fn, *args, op_name="sigmoid_focal_loss")


def square_error_cost(input, label):
    return apply(lambda a, b: jnp.square(a - b), input, label, op_name="square_error_cost")


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply(lambda p, y: -y * jnp.log(p + epsilon)
                 - (1 - y) * jnp.log(1 - p + epsilon), input, label, op_name="log_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss via the standard log-space alpha (forward) recursion as one
    ``lax.scan`` over time (reference: ``phi warpctc_kernel``; layouts per
    paddle: log_probs [T, B, C] logits or log-probs, labels [B, L]).

    Differentiable through the tape (grad of logsumexp-recursion = the
    soft alignment posteriors — no custom backward needed)."""
    import jax

    def fn(lp, lab, in_len, lab_len):
        T, B, C = lp.shape
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        L = lab.shape[1]
        S = 2 * L + 1
        lab = lab.astype(jnp.int32)
        # extended label sequence: blank, l1, blank, l2, ... blank
        ext = jnp.full((B, S), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab)
        # allowed skip transition s-2 -> s: only onto a non-blank that
        # differs from the previous non-blank
        skip_ok = jnp.zeros((B, S), bool)
        if L > 1:
            diff = lab[:, 1:] != lab[:, :-1]
            skip_ok = skip_ok.at[:, 3::2].set(diff)
        neg_inf = -1e30
        alpha0 = jnp.full((B, S), neg_inf, jnp.float32)
        alpha0 = alpha0.at[:, 0].set(lp[0, jnp.arange(B), ext[:, 0]])
        if S > 1:
            alpha0 = alpha0.at[:, 1].set(lp[0, jnp.arange(B), ext[:, 1]])

        def step(alpha, lp_t):
            stay = alpha
            prev = jnp.concatenate(
                [jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
            prev2 = jnp.concatenate(
                [jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
            prev2 = jnp.where(skip_ok, prev2, neg_inf)
            merged = jnp.logaddexp(jnp.logaddexp(stay, prev), prev2)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)   # [B, S]
            return merged + emit, merged + emit

        _, alphas = jax.lax.scan(step, alpha0, lp[1:])
        alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T,B,S]
        # per-sequence terminal: t = in_len-1, states 2*lab_len-1 / 2*lab_len
        tt = jnp.clip(in_len.astype(jnp.int32) - 1, 0, T - 1)
        at_t = alphas[tt, jnp.arange(B)]                   # [B, S]
        s_last = jnp.clip(2 * lab_len.astype(jnp.int32), 0, S - 1)
        s_prev = jnp.clip(2 * lab_len.astype(jnp.int32) - 1, 0, S - 1)
        ll = jnp.logaddexp(
            jnp.take_along_axis(at_t, s_last[:, None], 1)[:, 0],
            jnp.take_along_axis(at_t, s_prev[:, None], 1)[:, 0])
        loss = -ll
        if norm_by_times:
            loss = loss / jnp.maximum(in_len.astype(jnp.float32), 1.0)
        if reduction == "mean":
            # reference semantics: each sample's loss is divided by its
            # label_length before averaging (mean(loss / label_lengths))
            loss = loss / jnp.maximum(lab_len.astype(jnp.float32), 1.0)
        return _reduce(loss, reduction)

    return apply(fn, log_probs, labels, input_lengths, label_lengths,
                 op_name="ctc_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """reference: ``paddle.nn.functional.npair_loss`` — N-pair metric
    loss: cross entropy over anchor·positiveᵀ similarities + L2 on the
    embeddings."""
    def fn(a, p, y):
        # reference: l2loss * 0.25 * l2_reg (Beta=0.25 in the paper)
        l2 = 0.25 * l2_reg * (jnp.sum(a * a) + jnp.sum(p * p)) / a.shape[0]
        sim = a @ p.T
        yv = y.reshape(-1)
        same = (yv[:, None] == yv[None, :]).astype(jnp.float32)
        tgt = same / jnp.maximum(same.sum(-1, keepdims=True), 1)
        logp = jax.nn.log_softmax(sim.astype(jnp.float32), axis=-1)
        ce = -(tgt * logp).sum(-1).mean()
        return ce + l2
    return apply(fn, anchor, positive, labels, op_name="npair_loss")


def dice_loss(input, label, epsilon=1e-5, name=None):
    """reference: ``paddle.nn.functional.dice_loss`` — 1 - Dice
    coefficient; ``input`` is per-class probabilities, ``label`` int
    class ids with trailing dim 1."""
    def fn(p, y):
        nclass = p.shape[-1]
        yv = jax.nn.one_hot(y.reshape(*p.shape[:-1]), nclass, dtype=p.dtype)
        red = tuple(range(1, p.ndim))
        inter = (p * yv).sum(red)
        union = p.sum(red) + yv.sum(red)
        return (1 - (2 * inter + epsilon) / (union + epsilon)).mean()
    return apply(fn, input, label, op_name="dice_loss")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """reference: ``paddle.nn.functional.margin_cross_entropy`` — the
    ArcFace/CosFace-family margin softmax
    (cos(m1·θ + m2) − m3 on the target class, scaled). The reference's
    model-parallel path shards classes over ``group``; here GSPMD owns
    sharding, so ``group`` only needs to be None/world."""
    def fn(lg, y):
        cos = jnp.clip(lg.astype(jnp.float32), -1.0, 1.0)
        theta = jnp.arccos(cos)
        target = jnp.cos(margin1 * theta + margin2) - margin3
        yh = jax.nn.one_hot(y.reshape(-1), lg.shape[-1], dtype=cos.dtype)
        adj = scale * jnp.where(yh > 0, target, cos)
        logp = jax.nn.log_softmax(adj, axis=-1)
        ce = -(yh * logp).sum(-1)
        sm = jax.nn.softmax(adj, axis=-1)
        ce = {"mean": ce.mean(), "sum": ce.sum(), "none": ce}[reduction]
        return (ce, sm)

    loss, sm = apply(fn, logits, label, op_name="margin_cross_entropy")
    return (loss, sm) if return_softmax else loss


def _adaptive_args(input_, head_weight, tail_weights, cutoffs, head_bias,
                   label=None):
    """Shared arg flattening + validation for the adaptive-softmax
    functionals (ONE pack/unpack protocol — forward and log_prob must
    agree on the parameter layout)."""
    if len(tail_weights) != len(cutoffs) - 1:
        raise ValueError(
            f"adaptive softmax: {len(tail_weights)} tail cluster(s) for "
            f"cutoffs {cutoffs} — expected len(cutoffs)-1")
    args = [input_] + ([label] if label is not None else []) + [head_weight]
    if head_bias is not None:
        args.append(head_bias)
    for pair in tail_weights:
        args.extend(pair)
    return args


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """reference: ``paddle.nn.functional.adaptive_log_softmax_with_loss``
    — hierarchical (adaptive) softmax. ``head_weight`` [in, c0+K] scores
    the first ``cutoffs[0]`` frequent classes plus K cluster tokens;
    ``tail_weights[k]`` is a low-rank pair [[in, h], [h, csz]] for
    cluster k. Returns ``(output, loss)``: per-sample target
    log-probability and its mean NLL. Vectorized with masks — no
    data-dependent branching, so it stays one compiled program."""
    cuts = [0] + list(cutoffs)
    c0 = cuts[1]
    n_classes = cuts[-1]
    # eager bounds check (reference raises on out-of-range labels; a
    # masked gather would silently train on garbage). Concrete labels
    # only — traced label values can't be inspected.
    lab_arr = getattr(label, "_data", label)
    if not isinstance(lab_arr, jax.core.Tracer):
        lv = np.asarray(lab_arr).reshape(-1)
        if lv.size and (lv.min() < 0 or lv.max() >= n_classes):
            raise ValueError(
                f"adaptive_log_softmax_with_loss: label values must be in "
                f"[0, {n_classes}); got [{lv.min()}, {lv.max()}]")

    def fn(x, y, hw, *rest):
        hb = rest[0] if head_bias is not None else None
        toff = 1 if head_bias is not None else 0
        tails = [(rest[j], rest[j + 1]) for j in range(toff, len(rest), 2)]
        head = x @ hw
        if hb is not None:
            head = head + hb
        head_lp = jax.nn.log_softmax(head.astype(jnp.float32), axis=-1)
        yv = y.reshape(-1).astype(jnp.int32)
        # head part: classes < c0 read directly
        safe_head = jnp.clip(yv, 0, c0 - 1)
        out = jnp.take_along_axis(head_lp, safe_head[:, None], axis=1)[:, 0]
        for k, (w1, w2) in enumerate(tails):
            lo, hi = cuts[k + 1], cuts[k + 2]
            csz = hi - lo
            tail_lp = jax.nn.log_softmax(
                ((x @ w1) @ w2).astype(jnp.float32), axis=-1)
            in_k = (yv >= lo) & (yv < hi)
            idx = jnp.clip(yv - lo, 0, csz - 1)
            t = jnp.take_along_axis(tail_lp, idx[:, None], axis=1)[:, 0]
            cluster_lp = head_lp[:, c0 + k]
            out = jnp.where(in_k, cluster_lp + t, out)
        loss = -out.mean()
        return out, loss

    args = _adaptive_args(input, head_weight, tail_weights, cutoffs,
                          head_bias, label=label)
    return apply(fn, *args, op_name="adaptive_log_softmax_with_loss")


def adaptive_log_softmax_log_prob(input, head_weight, tail_weights, cutoffs,
                                  head_bias=None, name=None):
    """Full [N, n_classes] log-distribution of the adaptive softmax —
    the ``AdaptiveLogSoftmaxWithLoss.log_prob`` computation."""
    cuts = [0] + list(cutoffs)
    c0 = cuts[1]

    def fn(x, hw, *rest):
        hb = rest[0] if head_bias is not None else None
        toff = 1 if head_bias is not None else 0
        tails = [(rest[j], rest[j + 1]) for j in range(toff, len(rest), 2)]
        head = x @ hw
        if hb is not None:
            head = head + hb
        head_lp = jax.nn.log_softmax(head.astype(jnp.float32), axis=-1)
        parts = [head_lp[:, :c0]]
        for k, (w1, w2) in enumerate(tails):
            tail_lp = jax.nn.log_softmax(
                ((x @ w1) @ w2).astype(jnp.float32), axis=-1)
            parts.append(head_lp[:, c0 + k][:, None] + tail_lp)
        return jnp.concatenate(parts, axis=-1)

    args = _adaptive_args(input, head_weight, tail_weights, cutoffs,
                          head_bias)
    return apply(fn, *args, op_name="adaptive_log_softmax_log_prob")
